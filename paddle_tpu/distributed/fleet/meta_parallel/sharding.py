"""GroupSharded (ZeRO-2/3) model+optimizer wrappers.

Parity with /root/reference/python/paddle/distributed/fleet/meta_parallel/
sharding/group_sharded_stage2.py:47, group_sharded_optimizer_stage2.py:53,
group_sharded_stage3.py:85.

TPU-native mechanics: "sharding a buffer across the group" is a NamedSharding
over the 'sharding' mesh axis on the buffer's dim 0.  Per-device memory then
holds 1/n of the array, exactly like the reference's per-rank slices, but
gather/release is compiler-inserted (GSPMD gathers params on demand inside
the forward — the reference implements the same thing as python forward
hooks, group_sharded_stage3.py:235).  With nranks==1 or no mesh everything
degenerates to the plain layer/optimizer.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ....nn.layer.layers import Layer

__all__ = ["GroupShardedStage2", "GroupShardedOptimizerStage2",
           "GroupShardedStage3", "sharding_mesh_for_group"]

_AXIS = "sharding"


def sharding_mesh_for_group(group=None):
    """Resolve (mesh, nranks) for the sharding axis: the fleet hybrid mesh if
    initialised, else a 1-axis mesh over the group's own devices; with no
    group at all, default to ALL local devices (the reference defaults to
    the world group)."""
    from ..base import fleet as _fleet
    hcg = _fleet._hcg
    if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
        mesh = hcg.get_jax_mesh()
        if mesh is not None:
            return mesh, hcg.get_sharding_parallel_world_size()
    devs = jax.devices()
    if group is not None:
        if group.nranks > 1 and max(group.ranks) < len(devs):
            chosen = [devs[r] for r in group.ranks]
            return Mesh(np.array(chosen), (_AXIS,)), group.nranks
        return None, 1
    if len(devs) > 1:
        return Mesh(np.array(devs), (_AXIS,)), len(devs)
    return None, 1


def _shard0(arr, mesh, n):
    """Place `arr` sharded on dim 0 over the sharding axis (replicate when
    indivisible — the reference pads instead; XLA handles uneven shards but
    divisibility keeps layouts clean)."""
    if mesh is None or arr.ndim == 0 or arr.shape[0] % n != 0:
        return arr
    spec = P(*([_AXIS] + [None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec))


def _shard_slot_init(optimizer, mesh, n):
    """Wrap optimizer._init_slot so every new accumulator slot is created
    dim0-sharded across the group (the optimizer-state half of ZeRO)."""
    orig_init = optimizer._init_slot

    def sharded_init(name, p):
        return _shard0(orig_init(name, p), mesh, n)
    optimizer._init_slot = sharded_init


class GroupShardedOptimizerStage2:
    """Optimizer wrapper that keeps every accumulator slot sharded across the
    group (ZeRO-2's optimizer-state half; reference
    group_sharded_optimizer_stage2.py:53)."""

    def __init__(self, params, optim, group=None, offload=False,
                 device="tpu", **kwargs):
        self._optim = optim
        self._group = group
        self.mesh, self.nranks = sharding_mesh_for_group(group)
        if self._optim._parameter_list is None:
            self._optim._parameter_list = list(params)
        _shard_slot_init(self._optim, self.mesh, self.nranks)

    def __getattr__(self, item):
        return getattr(self._optim, item)

    def step(self):
        self._optim.step()

    def clear_grad(self, set_to_zero=True):
        self._optim.clear_grad(set_to_zero)

    clear_gradients = clear_grad


class GroupShardedStage2(Layer):
    """ZeRO-2: shard gradients + optimizer states (reference
    group_sharded_stage2.py:47).  Gradient sharding = post-accumulation hook
    placing each grad dim0-sharded over the group."""

    def __init__(self, layer, sharding_optimizer, group=None, sync_buffers=False,
                 buffer_max_size=2 ** 23, auto_refresh_trainable=True,
                 device="tpu", dp_group=None, **kwargs):
        super().__init__()
        self._layers = layer
        self._sharding_optimizers = (
            sharding_optimizer if isinstance(sharding_optimizer, (list, tuple))
            else [sharding_optimizer])
        self._group = group
        self.mesh, self.nranks = sharding_mesh_for_group(group)
        if self.nranks > 1:
            mesh, n = self.mesh, self.nranks

            def hook(grad):
                grad._data = _shard0(grad._data, mesh, n)
                return grad
            for p in layer.parameters():
                if not p.stop_gradient:
                    p.register_hook(hook)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def to(self, *args, **kwargs):
        return self._layers.to(*args, **kwargs)

    def clear_gradients(self):
        self._layers.clear_gradients()


class GroupShardedStage3(Layer):
    """ZeRO-3: parameters themselves live sharded; the compiler all-gathers
    them on demand inside forward/backward and the gathered copy is freed
    after use — the semantic the reference implements with _param2buffer
    segmentation + forward hooks (group_sharded_stage3.py:173,:235)."""

    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False,
                 device="tpu", segment_size=2 ** 20, pretrain_sync_models=True,
                 offload=False, sync_comm=False, dp_group=None,
                 exclude_layer=None, **kwargs):
        super().__init__()
        self._layers = layer
        self._group = group
        self.mesh, self.nranks = sharding_mesh_for_group(group)
        self._optim = optimizer
        if self.nranks > 1:
            for p in layer.parameters():
                p._data = _shard0(p._data, self.mesh, self.nranks)
            if optimizer is not None:
                _shard_slot_init(optimizer, self.mesh, self.nranks)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def get_all_parameters(self, convert2cpu=False):
        """Reference API: materialise full (replicated) parameters."""
        if self.mesh is not None:
            for p in self._layers.parameters():
                p._data = jax.device_put(
                    p._data,
                    NamedSharding(self.mesh, P(*([None] * p.ndim))))
        return list(self._layers.parameters())

    def clear_gradients(self):
        self._layers.clear_gradients()
