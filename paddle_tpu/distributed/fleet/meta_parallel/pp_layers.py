"""Pipeline model description: LayerDesc / SharedLayerDesc / PipelineLayer.

Parity with /root/reference/python/paddle/distributed/fleet/meta_parallel/
parallel_layers/pp_layers.py:258 (PipelineLayer): the model is described as a
flat list of layer descriptors, segmented into `num_stages` contiguous
stages; shared descriptors (tied embeddings) alias one parameter across
stages.

TPU-native: every stage's parameters are placed on that stage's device(s)
(single-controller: jax.device_put onto jax.devices()[stage]); activations
migrate between stages automatically when the next stage's ops consume them
— the explicit NCCL p2p of the reference becomes XLA host-driven transfers,
and in captured mode (paddle_tpu.parallel.transformer) ppermute over the pp
mesh axis.
"""
from __future__ import annotations

import math
import re

import jax

from ....nn.layer.layers import Layer
from ....nn.layer.container import LayerList, Sequential

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


def _to_stage_device(x, dev):
    """Move a microbatch activation to the next stage's device — the XLA
    analog of the reference's p2p send/recv (pp_utils/p2p_communication.py):
    forward transfers src->dst, backward returns the cotangent dst->src."""
    from ....autograd.py_layer import PyLayer
    from ....core.tensor import Tensor

    if not isinstance(x, Tensor):
        return x
    cur = list(x._data.devices())[0]
    if cur == dev:
        return x

    class _Transfer(PyLayer):
        @staticmethod
        def forward(ctx, t):
            ctx.src = cur
            return Tensor(jax.device_put(t._data, dev),
                          stop_gradient=t.stop_gradient)

        @staticmethod
        def backward(ctx, g):
            return Tensor(jax.device_put(g._data, ctx.src))

    return _Transfer.apply(x)


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Segment a layer-descriptor list into pipeline stages
    (reference pp_layers.py:258)."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        self._recompute_interval = recompute_interval
        if num_stages is None and topology is None:
            from ..base import fleet as _fleet
            hcg = _fleet._hcg
            num_stages = (hcg.get_pipe_parallel_world_size()
                          if hcg is not None else 1)
        if num_stages is None:
            num_stages = self._topo.get_dim("pipe")
        self._num_stages = int(num_stages)
        self._num_virtual = num_virtual_pipeline_stages or 1

        self._descs = list(layers)
        self._shared_layers = {}
        built = []
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self._shared_layers:
                    self._shared_layers[d.layer_name] = d.build_layer()
                base = self._shared_layers[d.layer_name]
                if d.forward_func is None:
                    built.append(base)
                else:
                    fwd, shared = d.forward_func, base

                    class _SharedCall(Layer):
                        def __init__(self):
                            super().__init__()
                            self._base = shared

                        def forward(self, *a, **k):
                            return fwd(self._base, *a, **k)
                    built.append(_SharedCall())
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(d)
            else:
                raise TypeError(f"unsupported layer description {d!r}")

        self._all_layers = built
        self.segments = self._segment(seg_method)
        self.run_function = LayerList(
            [l for l in built if isinstance(l, Layer)])
        self._place_stages()

    # -- segmentation ----------------------------------------------------
    def _segment(self, seg_method):
        n, stages = len(self._all_layers), self._num_stages * self._num_virtual
        if seg_method == "uniform":
            bounds = [round(i * n / stages) for i in range(stages + 1)]
        elif seg_method.startswith("layer:"):
            pat = seg_method[len("layer:"):]
            marks = [i for i, l in enumerate(self._all_layers)
                     if re.search(pat, type(l).__name__)]
            per = math.ceil(len(marks) / stages) if marks else 1
            bounds = [0]
            for s in range(1, stages):
                idx = s * per
                bounds.append(marks[idx] if idx < len(marks) else n)
            bounds.append(n)
        else:
            raise ValueError(f"unknown seg_method {seg_method}")
        return bounds

    def _place_stages(self):
        """Place each stage's params on its pipeline device (best effort)."""
        devs = jax.devices()
        self._stage_devices = None
        if self._num_stages <= 1 or len(devs) < self._num_stages:
            return
        self._stage_devices = devs[:self._num_stages]
        # params referenced from more than one stage (tied embeddings) must
        # stay UNcommitted: jax freely migrates uncommitted buffers to
        # whichever stage device the consuming op runs on, while a committed
        # buffer would raise an incompatible-devices error on the other stage
        owner = {}
        shared = set()
        for s in range(self._num_stages):
            for chunk in range(self._num_virtual):
                for l in self.stage_layers(s, chunk):
                    if isinstance(l, Layer):
                        for p in l.parameters():
                            if owner.setdefault(id(p), s) != s:
                                shared.add(id(p))
        for s in range(self._num_stages):
            dev = devs[s]
            for chunk in range(self._num_virtual):
                for l in self.stage_layers(s, chunk):
                    if isinstance(l, Layer):
                        for p in l.parameters():
                            if id(p) not in shared and owner[id(p)] == s:
                                p._data = jax.device_put(p._data, dev)

    # -- access ----------------------------------------------------------
    def get_num_stages(self):
        return self._num_stages

    def stage_layers(self, stage, chunk=0):
        i = chunk * self._num_stages + stage
        return self._all_layers[self.segments[i]:self.segments[i + 1]]

    def get_stage_from_index(self, index):
        for s in range(len(self.segments) - 1):
            if self.segments[s] <= index < self.segments[s + 1]:
                return s % self._num_stages
        return self._num_stages - 1

    def forward_stage(self, x, stage, chunk=0):
        if self._stage_devices is not None:
            dev = self._stage_devices[stage]
            x = (_to_stage_device(x, dev) if not isinstance(x, tuple)
                 else tuple(_to_stage_device(t, dev) for t in x))
        for l in self.stage_layers(stage, chunk):
            if self._recompute_interval > 0 and isinstance(l, Layer):
                from ..recompute import recompute
                x = recompute(l, x) if not isinstance(x, tuple) \
                    else recompute(l, *x)
            else:
                x = l(x) if not isinstance(x, tuple) else l(*x)
        return x

    def forward(self, x):
        for chunk in range(self._num_virtual):
            for s in range(self._num_stages):
                x = self.forward_stage(x, s, chunk)
        return x
