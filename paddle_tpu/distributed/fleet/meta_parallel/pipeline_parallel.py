"""Pipeline-parallel schedules: F-then-B, 1F1B, interleaved.

Parity with /root/reference/python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py (train_batch :940, forward_backward_pipeline :684 1F1B,
PipelineParallelWithInterleave :1308).

TPU-native: in the single-controller eager regime all stages are driven by
one Python loop, so the schedule orders (micro-forward, micro-backward) work
items exactly like the reference's 1F1B — bounding live activations to
pp_degree microbatches per stage — while cross-stage activation movement is
XLA device-to-device transfer instead of NCCL p2p.  The throughput-critical
captured form of the same schedule (lax.scan over ticks + ppermute) lives in
paddle_tpu.parallel.transformer; this class is the define-by-run parity
surface.
"""
from __future__ import annotations

import numpy as np

from ....core.tensor import Tensor
from .wrappers import TensorParallel
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave",
           "P2PPipelineParallel"]


def _split_micro(data, n):
    """Split (x, y) batch tensors into n microbatches along dim 0."""
    x, y = data

    def split(t):
        if isinstance(t, Tensor):
            b = t.shape[0]
            if b % n != 0:
                raise ValueError(
                    f"batch size {b} must be divisible by accumulate_steps "
                    f"{n} (reference PipelineParallel asserts the same)")
            m = b // n
            return [t[i * m:(i + 1) * m] for i in range(n)]
        return [t] * n
    return list(zip(split(x), split(y)))


class PipelineParallel(TensorParallel):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__(layers, hcg, strategy)
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "PipelineParallel expects a PipelineLayer-described model")
        self._acc_steps = 1
        if strategy is not None:
            self._acc_steps = int(
                strategy.pipeline_configs.get("accumulate_steps", 1))
        self.num_stages = layers.get_num_stages()
        self.total_loss = None

    # -- microbatch work items -------------------------------------------
    def _forward_micro(self, mb):
        x, y = mb
        out = self._layers.forward(x)
        loss_fn = self._layers._loss_fn
        loss = loss_fn(out, y) if loss_fn is not None else out
        return loss

    def _backward_micro(self, loss, scaler=None):
        # grads accumulate onto the tape leaves across microbatches
        scaled = loss * (1.0 / self._acc_steps)
        if scaler is not None:
            scaled = scaler.scale(scaled)
        scaled.backward()
        return float(loss.numpy())

    def forward_backward_pipeline(self, data, scaler=None):
        """1F1B: warmup forwards, steady (1 fwd + 1 bwd), cooldown backwards
        (reference pipeline_parallel.py:684).  In single-controller form the
        schedule is the work-item ordering; its effect is the same activation
        bound: at most `num_stages` live microbatch tapes."""
        M = self._acc_steps
        micro = _split_micro(data, M)
        warmup = min(self.num_stages, M)
        in_flight = []   # forward-done, backward-pending losses (FIFO)
        losses = []

        for i in range(warmup):
            in_flight.append(self._forward_micro(micro[i]))
        for i in range(warmup, M):          # steady 1F1B
            losses.append(self._backward_micro(in_flight.pop(0), scaler))
            in_flight.append(self._forward_micro(micro[i]))
        while in_flight:                     # cooldown
            losses.append(self._backward_micro(in_flight.pop(0), scaler))

        return float(np.mean(losses))

    # -- public API ------------------------------------------------------
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            # dynamic loss scaling must agree ACROSS stages: an overflow
            # seen only in one stage's weight grads would otherwise make
            # that stage skip + rescale while the others step (reference
            # all-reduces found_inf over the pipeline group)
            import jax.numpy as jnp
            scaler.unscale_(optimizer)
            found = scaler._found_inf_t
            flag = self._zeros((1,), "float32")
            flag._data = jnp.where(
                found if found is not None else False, 1.0, 0.0
            ).reshape(1).astype(jnp.float32)
            dist.all_reduce(flag, group=self._group)
            scaler._found_inf_t = flag._data.reshape(()) > 0
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        """compute_loss=False returns the per-microbatch forward outputs
        (logits) instead of a scalar loss, matching the reference
        pipeline_parallel.py eval_batch contract."""
        self._layers.eval()
        from ....core import dispatch
        M = self._acc_steps
        micro = _split_micro(data, M)
        with dispatch.no_grad():
            if not compute_loss:
                return [self._layers.forward(x) for x, _ in micro]
            losses = [float(self._forward_micro(mb).numpy()) for mb in micro]
        return float(np.mean(losses))


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved virtual-pipeline schedule (reference :1308): each rank
    owns num_virtual chunks; microbatches round-robin chunks.  The eager
    single-controller ordering degenerates to 1F1B over (chunk, microbatch)
    pairs with the same activation bound."""

    def __init__(self, layers, hcg, strategy=None):
        super().__init__(layers, hcg, strategy)
        self.num_model_chunks = layers._num_virtual
        # _forward_micro is inherited: PipelineLayer.forward already walks
        # (chunk, stage) pairs in interleaved order


class P2PPipelineParallel:
    """Cross-process eager pipeline engine (VERDICT r3 weak #7): each
    process owns ONE stage's layers and exchanges microbatch activations /
    input-gradients with its neighbors over eager send/recv — the
    define-by-run analog of the reference's p2p pipeline
    (pp_utils/p2p_communication.py + pipeline_parallel.py:940 train_batch),
    with XLA-gloo/ICI p2p in place of NCCL.

    Schedule: F-then-B (GPipe) over ``acc_steps`` microbatches — gradient
    accumulation bounds are identical to the reference's F-then-B mode; the
    throughput-critical 1F1B/VPP forms remain the COMPILED schedules in
    paddle_tpu.parallel.transformer.

    recv_shape/recv_dtype: the per-microbatch activation this stage
    receives (stage > 0) — the reference ships the same metadata in its
    p2p meta messages.
    """

    def __init__(self, local_layers, stage_id, num_stages, loss_fn=None,
                 acc_steps=1, recv_shape=None, recv_dtype="float32",
                 group=None):
        self._layers = local_layers
        self.stage_id = int(stage_id)
        self.num_stages = int(num_stages)
        self._loss_fn = loss_fn
        self._acc_steps = int(acc_steps)
        self._recv_shape = tuple(recv_shape) if recv_shape else None
        self._recv_dtype = recv_dtype
        self._group = group
        if self.stage_id > 0 and self._recv_shape is None:
            raise ValueError("stage > 0 needs recv_shape (per-microbatch "
                             "activation shape from the previous stage)")

    @property
    def is_first(self):
        return self.stage_id == 0

    @property
    def is_last(self):
        return self.stage_id == self.num_stages - 1

    def _zeros(self, shape, dtype=None):
        import numpy as np

        from ....ops.creation import to_tensor
        return to_tensor(np.zeros(shape, dtype or self._recv_dtype))

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """data: (x, y); x is consumed on stage 0, y on the last stage
        (other stages may pass None).  Returns the mean microbatch loss on
        the last stage, else 0.0."""
        from ... import collective as dist

        self._layers.train()
        M = self._acc_steps
        x, y = data
        xs = ys = [None] * M
        if self.is_first:
            xs = [t for t, _ in _split_micro((x, x), M)]
        if self.is_last and y is not None:
            ys = [t for t, _ in _split_micro((y, y), M)]

        saved = []                 # (input_act or None, output or loss)
        losses = []
        for i in range(M):         # forward wave
            if self.is_first:
                inp = xs[i]
            else:
                buf = self._zeros(self._recv_shape)
                dist.recv(buf, src=self.stage_id - 1, group=self._group)
                inp = buf
                inp.stop_gradient = False
            out = self._layers(inp)
            if self.is_last:
                loss = self._loss_fn(out, ys[i]) if self._loss_fn \
                    else out
                saved.append((inp, loss))
                losses.append(loss)
            else:
                dist.send(out, dst=self.stage_id + 1, group=self._group)
                saved.append((inp, out))

        from ....autograd import backward as autograd_backward
        for i in reversed(range(M)):   # backward wave
            inp, out = saved[i]
            if self.is_last:
                scaled = out * (1.0 / M)
                if scaler is not None:
                    scaled = scaler.scale(scaled)
                scaled.backward()
            else:
                # grad buffer matches the OUTPUT's dtype (the activation
                # recv_dtype describes this stage's input, not its output)
                gout = self._zeros(tuple(out.shape), str(out._data.dtype))
                dist.recv(gout, src=self.stage_id + 1, group=self._group)
                autograd_backward([out], [gout], retain_graph=False)
            if not self.is_first:
                dist.send(inp.grad, dst=self.stage_id - 1,
                          group=self._group)

        if scaler is not None:
            # dynamic loss scaling must agree ACROSS stages: an overflow
            # seen only in one stage's weight grads would otherwise make
            # that stage skip + rescale while the others step (reference
            # all-reduces found_inf over the pipeline group)
            import jax.numpy as jnp
            scaler.unscale_(optimizer)
            found = scaler._found_inf_t
            flag = self._zeros((1,), "float32")
            flag._data = jnp.where(
                found if found is not None else False, 1.0, 0.0
            ).reshape(1).astype(jnp.float32)
            dist.all_reduce(flag, group=self._group)
            scaler._found_inf_t = flag._data.reshape(()) > 0
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        if self.is_last:
            import numpy as np
            return float(np.mean([float(l.numpy()) for l in losses]))
        return 0.0
