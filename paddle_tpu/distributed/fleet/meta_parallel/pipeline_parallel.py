"""Pipeline-parallel schedules: F-then-B, 1F1B, interleaved.

Parity with /root/reference/python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py (train_batch :940, forward_backward_pipeline :684 1F1B,
PipelineParallelWithInterleave :1308).

TPU-native: in the single-controller eager regime all stages are driven by
one Python loop, so the schedule orders (micro-forward, micro-backward) work
items exactly like the reference's 1F1B — bounding live activations to
pp_degree microbatches per stage — while cross-stage activation movement is
XLA device-to-device transfer instead of NCCL p2p.  The throughput-critical
captured form of the same schedule (lax.scan over ticks + ppermute) lives in
paddle_tpu.parallel.transformer; this class is the define-by-run parity
surface.
"""
from __future__ import annotations

import numpy as np

from ....core.tensor import Tensor
from .wrappers import TensorParallel
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


def _split_micro(data, n):
    """Split (x, y) batch tensors into n microbatches along dim 0."""
    x, y = data

    def split(t):
        if isinstance(t, Tensor):
            b = t.shape[0]
            if b % n != 0:
                raise ValueError(
                    f"batch size {b} must be divisible by accumulate_steps "
                    f"{n} (reference PipelineParallel asserts the same)")
            m = b // n
            return [t[i * m:(i + 1) * m] for i in range(n)]
        return [t] * n
    return list(zip(split(x), split(y)))


class PipelineParallel(TensorParallel):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__(layers, hcg, strategy)
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "PipelineParallel expects a PipelineLayer-described model")
        self._acc_steps = 1
        if strategy is not None:
            self._acc_steps = int(
                strategy.pipeline_configs.get("accumulate_steps", 1))
        self.num_stages = layers.get_num_stages()
        self.total_loss = None

    # -- microbatch work items -------------------------------------------
    def _forward_micro(self, mb):
        x, y = mb
        out = self._layers.forward(x)
        loss_fn = self._layers._loss_fn
        loss = loss_fn(out, y) if loss_fn is not None else out
        return loss

    def _backward_micro(self, loss, scaler=None):
        # grads accumulate onto the tape leaves across microbatches
        scaled = loss * (1.0 / self._acc_steps)
        if scaler is not None:
            scaled = scaler.scale(scaled)
        scaled.backward()
        return float(loss.numpy())

    def forward_backward_pipeline(self, data, scaler=None):
        """1F1B: warmup forwards, steady (1 fwd + 1 bwd), cooldown backwards
        (reference pipeline_parallel.py:684).  In single-controller form the
        schedule is the work-item ordering; its effect is the same activation
        bound: at most `num_stages` live microbatch tapes."""
        M = self._acc_steps
        micro = _split_micro(data, M)
        warmup = min(self.num_stages, M)
        in_flight = []   # forward-done, backward-pending losses (FIFO)
        losses = []

        for i in range(warmup):
            in_flight.append(self._forward_micro(micro[i]))
        for i in range(warmup, M):          # steady 1F1B
            losses.append(self._backward_micro(in_flight.pop(0), scaler))
            in_flight.append(self._forward_micro(micro[i]))
        while in_flight:                     # cooldown
            losses.append(self._backward_micro(in_flight.pop(0), scaler))

        return float(np.mean(losses))

    # -- public API ------------------------------------------------------
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        """compute_loss=False returns the per-microbatch forward outputs
        (logits) instead of a scalar loss, matching the reference
        pipeline_parallel.py eval_batch contract."""
        self._layers.eval()
        from ....core import dispatch
        M = self._acc_steps
        micro = _split_micro(data, M)
        with dispatch.no_grad():
            if not compute_loss:
                return [self._layers.forward(x) for x, _ in micro]
            losses = [float(self._forward_micro(mb).numpy()) for mb in micro]
        return float(np.mean(losses))


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved virtual-pipeline schedule (reference :1308): each rank
    owns num_virtual chunks; microbatches round-robin chunks.  The eager
    single-controller ordering degenerates to 1F1B over (chunk, microbatch)
    pairs with the same activation bound."""

    def __init__(self, layers, hcg, strategy=None):
        super().__init__(layers, hcg, strategy)
        self.num_model_chunks = layers._num_virtual
        # _forward_micro is inherited: PipelineLayer.forward already walks
        # (chunk, stage) pairs in interleaved order
