from .wrappers import (  # noqa: F401
    HybridParallelOptimizer, TensorParallel, wrap_distributed_model,
)
from .pipeline_parallel import (  # noqa: F401
    PipelineParallel, PipelineParallelWithInterleave,
)
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .sharding import (  # noqa: F401
    GroupShardedOptimizerStage2, GroupShardedStage2, GroupShardedStage3,
)
