"""meta_parallel: model/optimizer wrappers per hybrid strategy.

Parity with /root/reference/python/paddle/distributed/fleet/meta_parallel/
and dygraph_optimizer/hybrid_parallel_optimizer.py:275.  Round-1 scope:
single-controller wrappers (DP via sharded batch handled in the compiled
step; TP layers in fleet.layers.mpu); PP schedule orchestration lands with
the pipeline milestone.
"""
from __future__ import annotations

from ....nn.layer.layers import Layer
from ..topology import ParallelMode

__all__ = ["wrap_distributed_model", "HybridParallelOptimizer",
           "TensorParallel"]


class TensorParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)


def wrap_distributed_model(model, hcg, strategy=None):
    if hcg is None:
        return model
    from .pipeline_parallel import (
        PipelineParallel, PipelineParallelWithInterleave,
    )
    from .pp_layers import PipelineLayer
    mode = hcg.get_parallel_mode()
    if mode == ParallelMode.DATA_PARALLEL and hcg.get_data_parallel_world_size() > 1:
        from ...parallel import DataParallel
        return DataParallel(model, group=hcg.get_data_parallel_group())
    if mode == ParallelMode.PIPELINE_PARALLEL:
        if isinstance(model, PipelineLayer) and model._num_virtual > 1:
            return PipelineParallelWithInterleave(model, hcg, strategy)
        return PipelineParallel(model, hcg, strategy)
    if mode == ParallelMode.TENSOR_PARALLEL:
        return TensorParallel(model, hcg, strategy)
    return model


class HybridParallelOptimizer:
    """Wraps the inner optimizer with hybrid-parallel grad handling.

    In the single-controller TPU model, DP/sharding gradient reductions are
    part of the compiled train step (GSPMD inserts them from shardings), so
    the wrapper's job is clipping across groups + delegating.
    """

    def __init__(self, optimizer, hcg, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner_opt.minimize(loss, startup_program, parameters,
                                        no_grad_set)
