"""Role makers for parameter-server fleets (reference
python/paddle/distributed/fleet/base/role_maker.py — PaddleCloudRoleMaker
reads the cloud env contract, UserDefinedRoleMaker takes explicit args;
Role.WORKER/SERVER enum).
"""
from __future__ import annotations

import os

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    """Answers: what am I, which index, who are the servers/workers."""

    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_num = 1
        self._server_endpoints = []
        self._is_collective = False      # role makers exist for PS fleets

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return self._worker_num

    def server_num(self):
        return len(self._server_endpoints)

    def get_pserver_endpoints(self):
        return list(self._server_endpoints)


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-contract role maker (reference role_maker.py:706):
    TRAINING_ROLE=TRAINER|PSERVER, PADDLE_PSERVERS_IP_PORT_LIST,
    PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ID / PADDLE_PSERVER_ID."""

    def __init__(self, is_collective=False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        if is_collective:
            return
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._role = Role.SERVER if role == "PSERVER" else Role.WORKER
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = [e for e in eps.split(",") if e]
        self._worker_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._current_id = int(
            os.environ.get("PADDLE_PSERVER_ID", "0") if self.is_server()
            else os.environ.get("PADDLE_TRAINER_ID", "0"))


class UserDefinedRoleMaker(RoleMakerBase):
    """Explicit-args role maker (reference role_maker.py: UserDefined*)."""

    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None):
        super().__init__()
        self._current_id = int(current_id)
        self._role = role
        self._worker_num = int(worker_num)
        self._server_endpoints = list(server_endpoints or [])
