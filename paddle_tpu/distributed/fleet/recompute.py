"""Recompute (activation checkpointing).

Parity with /root/reference/python/paddle/distributed/fleet/recompute/
recompute.py (RecomputeFunction :128, recompute :463, recompute_sequential
:630).

TPU-native notes: inside captured (jit) training the idiomatic form is
jax.checkpoint — the hybrid trainer (paddle_tpu.parallel.transformer) uses it
per decoder block.  This module provides the *eager* define-by-run variant:
forward runs without building a tape, backward re-executes the function
under grad to rebuild activations, replaying the RNG state so dropout
patterns match (the reference preserves RNG via the mp RNGStatesTracker).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core import dispatch, random_state
from ...core.tensor import Tensor
from ...autograd.py_layer import PyLayer

__all__ = ["recompute", "recompute_sequential", "RecomputeFunction"]


class RecomputeFunction(PyLayer):
    @staticmethod
    def forward(ctx, run_function, preserve_rng_state, *args):
        ctx.run_function = run_function
        ctx.preserve_rng_state = preserve_rng_state
        ctx.inputs = args
        if preserve_rng_state:
            ctx.rng_state = random_state.get_rng_state()
        with dispatch.no_grad():
            outputs = run_function(*args)
        return outputs

    @staticmethod
    def backward(ctx, *grads):
        # rebuild a detached copy of the inputs that requires grad where the
        # originals did
        detached = []
        for a in ctx.inputs:
            if isinstance(a, Tensor):
                d = a.detach()
                d.stop_gradient = a.stop_gradient
                detached.append(d)
            else:
                detached.append(a)
        saved_rng = None
        if ctx.preserve_rng_state:
            saved_rng = random_state.get_rng_state()
            random_state.set_rng_state(ctx.rng_state)
        try:
            with dispatch.enable_grad():
                outputs = ctx.run_function(*detached)
        finally:
            if saved_rng is not None:
                random_state.set_rng_state(saved_rng)
        outs = outputs if isinstance(outputs, (tuple, list)) else (outputs,)
        out_tensors = [o for o in outs if isinstance(o, Tensor)
                       and not o.stop_gradient]
        grad_list = [Tensor(g) if not isinstance(g, Tensor) else g
                     for g, o in zip(grads, outs)
                     if isinstance(o, Tensor) and not o.stop_gradient]
        from ...core.tape import backward as tape_backward
        tape_backward(out_tensors, grad_list, retain_graph=False)
        input_grads = []
        for a, d in zip(ctx.inputs, detached):
            if isinstance(a, Tensor):
                input_grads.append(None if d.grad is None else d.grad)
            # non-tensors occupy no grad slot
        return tuple(input_grads)


def recompute(function, *args, **kwargs):
    """Checkpoint `function`: don't store intermediate activations; re-run it
    in backward."""
    preserve = kwargs.pop("preserve_rng_state", True)
    kwargs.pop("use_reentrant", None)
    if kwargs:
        raise ValueError(f"unsupported kwargs {list(kwargs)}")
    if not dispatch.is_grad_enabled():
        return function(*args)
    return RecomputeFunction.apply(function, preserve, *args)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Segment a Sequential into `segments` chunks, recompute each
    (reference recompute_sequential :630).  ctx: {"segments": int,
    "preserve_rng_state": bool}."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else int(ctx)
    preserve = (ctx.get("preserve_rng_state", True)
                if isinstance(ctx, dict) else True)
    if hasattr(functions, "children"):
        functions = list(functions.children())
    functions = list(functions)
    seg_size = max(1, len(functions) // max(1, segments))

    def make_seg(fs):
        def run(*inp):
            out = inp
            for f in fs:
                out = f(*out) if isinstance(out, tuple) else f(out)
                if not isinstance(out, tuple):
                    out = (out,)
            return out if len(out) > 1 else out[0]
        return run

    out = args
    for i in range(0, len(functions), seg_size):
        seg = make_seg(functions[i:i + seg_size])
        out = recompute(seg, *out, preserve_rng_state=preserve, **kwargs)
        if not isinstance(out, tuple):
            out = (out,)
    return out if len(out) > 1 else out[0]
