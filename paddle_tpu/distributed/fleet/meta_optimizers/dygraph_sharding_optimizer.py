"""ZeRO-1: DygraphShardingOptimizer.

Parity with /root/reference/python/paddle/distributed/fleet/meta_optimizers/
dygraph_optimizer/dygraph_sharding_optimizer.py:54 — partition the parameter
list across the sharding group (greedy size-balanced, `_partition_parameters`),
each rank updates only its slice of optimizer state, params re-sync after.

TPU-native: the rank partition is kept for API parity/introspection, but the
state sharding itself is a dim-0 NamedSharding over the 'sharding' mesh axis
— per-device HBM holds 1/n of every slot, updates run where the state lives,
and no param broadcast is needed (params stay replicated; GSPMD reads the
sharded slots in place during the fused update program).
"""
from __future__ import annotations

from ..meta_parallel.sharding import (
    _shard_slot_init, sharding_mesh_for_group)

__all__ = ["DygraphShardingOptimizer"]


class DygraphShardingOptimizer:
    def __init__(self, optimizer, hcg=None, group=None, **kwargs):
        self._inner_opt = optimizer
        self._hcg = hcg
        if group is None and hcg is not None:
            group = hcg.get_sharding_parallel_group()
        self._group = group
        self.mesh, self.nranks = sharding_mesh_for_group(group)
        self._rank2params = self._partition_parameters()
        _shard_slot_init(optimizer, self.mesh, self.nranks)

    def _partition_parameters(self):
        """Greedy size-balanced param->rank assignment (reference
        _partition_parameters)."""
        n = max(1, self.nranks)
        mapping = {i: [] for i in range(n)}
        sizes = [0.0] * n
        params = self._inner_opt._parameter_list or []
        for p in sorted(params, key=lambda q: -q.size):
            r = sizes.index(min(sizes))
            mapping[r].append(p)
            sizes[r] += p.size
        return mapping

    @property
    def rank2params(self):
        return self._rank2params

    def _rank_own_params(self, rank):
        return self._rank2params.get(rank, [])

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner_opt.minimize(loss, startup_program, parameters,
                                        no_grad_set)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, state_dict):
        return self._inner_opt.set_state_dict(state_dict)
