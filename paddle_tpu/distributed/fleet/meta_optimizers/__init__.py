from .dygraph_sharding_optimizer import DygraphShardingOptimizer  # noqa: F401
