"""Megatron sequence parallelism utilities.

Parity with /root/reference/python/paddle/distributed/fleet/utils/
sequence_parallel_utils.py (ScatterOp/GatherOp/AllGatherOp/ReduceScatterOp
PyLayers :85-127, mark_as_sequence_parallel_parameter :192,
ColumnSequenceParallelLinear :257, RowSequenceParallelLinear :429).

TPU-native: between TP blocks activations stay sequence-sharded over the mp
axis.  Under shard_map tracing the ops are the exact lax collectives (whose
transposes ARE the reference's hand-written backward pairs: all_gather^T =
psum_scatter, ppermute^T = reverse ppermute).  In single-controller eager
mode the ops place a sharding constraint on the seq dim and let GSPMD move
the data.  mp_degree==1 degenerates to identity.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....autograd.py_layer import PyLayer
from ....core.tensor import Tensor
from ....nn import functional as F
from ....nn.initializer.attr import ParamAttr
from ....nn.layer.layers import Layer
from ..layers.mpu.mp_layers import _mp_context, _shard_param

__all__ = ["ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
           "scatter", "all_gather", "reduce_scatter",
           "mark_as_sequence_parallel_parameter",
           "is_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "create_fused_allreduce_gradient_hooks"]

_SEQ_AXIS = 0  # the reference scatters dim 0 of [s, b, h] activations


def _arr(x):
    return x._data if isinstance(x, Tensor) else x


def _traced(x):
    return isinstance(_arr(x), jax.core.Tracer)


def _mp_axis_info():
    mesh, axis, n = _mp_context(None)
    return mesh, axis, n


def scatter(input, group=None, axis=_SEQ_AXIS):
    """Split the seq dim across the mp group, keep the local slice."""
    mesh, mp_axis, n = _mp_axis_info()
    if n <= 1:
        return input
    arr = _arr(input)
    if _traced(input):
        size = arr.shape[axis] // n
        idx = lax.axis_index("mp")
        out = lax.dynamic_slice_in_dim(arr, idx * size, size, axis=axis)
        return Tensor(out) if isinstance(input, Tensor) else out
    if mesh is not None:
        spec = [None] * arr.ndim
        spec[axis] = "mp"
        out = jax.device_put(arr, NamedSharding(mesh, P(*spec)))
        if isinstance(input, Tensor):
            input._data = out
            return input
        return out
    return input


def all_gather(input, group=None, axis=_SEQ_AXIS):
    """Gather the seq dim from all mp ranks."""
    mesh, mp_axis, n = _mp_axis_info()
    if n <= 1:
        return input
    arr = _arr(input)
    if _traced(input):
        out = lax.all_gather(arr, "mp", axis=axis, tiled=True)
        return Tensor(out) if isinstance(input, Tensor) else out
    if mesh is not None:
        out = jax.device_put(
            arr, NamedSharding(mesh, P(*([None] * arr.ndim))))
        if isinstance(input, Tensor):
            input._data = out
            return input
        return out
    return input


def reduce_scatter(input, group=None, axis=_SEQ_AXIS):
    """Sum partial activations over mp and scatter the seq dim."""
    mesh, mp_axis, n = _mp_axis_info()
    if n <= 1:
        return input
    arr = _arr(input)
    if _traced(input):
        out = lax.psum_scatter(arr, "mp", scatter_dimension=axis, tiled=True)
        return Tensor(out) if isinstance(input, Tensor) else out
    # eager/GSPMD: the contraction's psum already happened inside the matmul
    # (XLA resolves Partial at the use site); only the seq-dim re-sharding
    # remains, which is exactly scatter's constraint.
    return scatter(input, group=group, axis=axis)


class ScatterOp(PyLayer):
    """fwd scatter / bwd all_gather (reference :85)."""

    @staticmethod
    def forward(ctx, input, axis=_SEQ_AXIS):
        ctx.axis = axis
        return scatter(input, axis=axis)

    @staticmethod
    def backward(ctx, grad):
        return all_gather(grad, axis=ctx.axis)


class GatherOp(PyLayer):
    """fwd all_gather / bwd scatter (reference :104)."""

    @staticmethod
    def forward(ctx, input, axis=_SEQ_AXIS):
        ctx.axis = axis
        return all_gather(input, axis=axis)

    @staticmethod
    def backward(ctx, grad):
        return scatter(grad, axis=ctx.axis)


class AllGatherOp(PyLayer):
    """fwd all_gather / bwd reduce_scatter (reference :113)."""

    @staticmethod
    def forward(ctx, input):
        return all_gather(input)

    @staticmethod
    def backward(ctx, grad):
        return reduce_scatter(grad)


class ReduceScatterOp(PyLayer):
    """fwd reduce_scatter / bwd all_gather (reference :127)."""

    @staticmethod
    def forward(ctx, input):
        return reduce_scatter(input)

    @staticmethod
    def backward(ctx, grad):
        return all_gather(grad)


def mark_as_sequence_parallel_parameter(parameter):
    """Parameters used inside the sequence-sharded region (layer norms)
    produce partial grads that need an mp allreduce (reference :192).
    Under GSPMD the reduction is compiler-inserted; the mark is kept for
    API parity and for the explicit-hook path."""
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter):
    return getattr(parameter, "sequence_parallel", False)


def create_fused_allreduce_gradient_hooks(parameter_list, accumulation_steps):
    hooks = []
    for p in parameter_list:
        if is_sequence_parallel_parameter(p):
            def hook(grad, _p=p):
                from ... import collective as C
                from .. import base as fleet_base
                hcg = fleet_base.fleet._hcg
                if hcg is None:
                    return grad
                g = hcg.get_model_parallel_group()
                if g is None or g.nranks <= 1:
                    return grad
                return C.all_reduce(grad, group=g)
            hooks.append(hook)
    return hooks


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    params = [p for p in model.parameters()
              if is_sequence_parallel_parameter(p)]
    for p in params:
        def hook(grad, _p=p):
            from ... import collective as C
            from .. import base as fleet_base
            hcg = fleet_base.fleet._hcg
            if hcg is None:
                return grad
            g = hcg.get_model_parallel_group()
            if g is None or g.nranks <= 1:
                return grad
            return C.all_reduce(grad, group=g)
        p.register_hook(hook)


class ColumnSequenceParallelLinear(Layer):
    """ColumnParallelLinear whose input is sequence-sharded: all_gather the
    seq dim in, compute the column-parallel matmul (reference :257)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        from ..layers.mpu.mp_layers import _mp_context as _ctx
        self.mesh, self.mp_axis, self.world_size = _ctx(mp_group)
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.is_mp = self.world_size > 1
        self.weight = self.create_parameter(
            [in_features, out_features], attr=ParamAttr._to_attr(weight_attr))
        self.bias = (None if has_bias is False else self.create_parameter(
            [out_features], is_bias=True))
        _shard_param(self.weight, self.mesh, P(None, self.mp_axis))
        _shard_param(self.bias, self.mesh, P(self.mp_axis))

    def forward(self, x):
        if self.is_mp:
            x = AllGatherOp.apply(x)
        out = F.linear(x, self.weight, self.bias)
        if self.is_mp and self.mesh is not None and not self.gather_output:
            spec = ([None] * (out.ndim - 1)) + [self.mp_axis]
            out._data = jax.device_put(
                out._data, NamedSharding(self.mesh, P(*spec)))
        return out


class RowSequenceParallelLinear(Layer):
    """RowParallelLinear whose output re-enters the sequence-sharded region:
    partial products are reduce-scattered over the seq dim (reference :429)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        from ..layers.mpu.mp_layers import _mp_context as _ctx
        self.mesh, self.mp_axis, self.world_size = _ctx(mp_group)
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.is_mp = self.world_size > 1
        self.weight = self.create_parameter(
            [in_features, out_features], attr=ParamAttr._to_attr(weight_attr))
        self.bias = (self.create_parameter([out_features], is_bias=True)
                     if has_bias else None)
        _shard_param(self.weight, self.mesh, P(self.mp_axis, None))

    def forward(self, x):
        out = F.linear(x, self.weight, None)
        if self.is_mp:
            out = ReduceScatterOp.apply(out)
        if self.bias is not None:
            out = out + self.bias
        return out
