from . import sequence_parallel_utils  # noqa: F401


def recompute(function, *args, **kwargs):
    from ...fleet.recompute import recompute as _rc
    return _rc(function, *args, **kwargs)
