"""Fleet singleton + DistributedStrategy.

Parity with /root/reference/python/paddle/distributed/fleet/fleet.py:151 and
the strategy protobuf (/root/reference/paddle/fluid/framework/
distributed_strategy.proto) — here a plain attribute bag.
"""
from __future__ import annotations

from ..parallel import get_rank, get_world_size, init_parallel_env
from .topology import CommunicateTopology, HybridCommunicateGroup

__all__ = ["DistributedStrategy", "Fleet", "fleet"]


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.find_unused_parameters = False
        self.fuse_grad_size_in_MB = 32
        self.last_comm_group_size_MB = 1


class Fleet:
    def __init__(self):
        self._is_initialized = False
        self._hcg = None
        self._strategy = None
        self._user_defined_optimizer = None

    def init(self, role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
        self._strategy = strategy or DistributedStrategy()
        init_parallel_env()
        hc = self._strategy.hybrid_configs
        topo = CommunicateTopology(
            hybrid_group_names=["data", "pipe", "sharding", "sep", "model"],
            dims=[hc.get("dp_degree", 1), hc.get("pp_degree", 1),
                  hc.get("sharding_degree", 1), hc.get("sep_degree", 1),
                  hc.get("mp_degree", 1)])
        self._hcg = HybridCommunicateGroup(topo)
        self._is_initialized = True
        return self

    def is_first_worker(self):
        return get_rank() == 0

    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return get_world_size()

    def get_hybrid_communicate_group(self):
        return self._hcg

    def distributed_model(self, model):
        from .meta_parallel import wrap_distributed_model
        return wrap_distributed_model(model, self._hcg, self._strategy)

    def distributed_optimizer(self, optimizer, strategy=None):
        self._user_defined_optimizer = optimizer
        from .meta_parallel import HybridParallelOptimizer
        return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)

    @property
    def strategy(self):
        return self._strategy

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    def stop_worker(self):
        pass


fleet = Fleet()
