"""Fleet singleton + DistributedStrategy.

Parity with /root/reference/python/paddle/distributed/fleet/fleet.py:151 and
the strategy protobuf (/root/reference/paddle/fluid/framework/
distributed_strategy.proto) — here a plain attribute bag.
"""
from __future__ import annotations

from ..parallel import get_rank, get_world_size, init_parallel_env
from .topology import CommunicateTopology, HybridCommunicateGroup

__all__ = ["DistributedStrategy", "Fleet", "fleet"]


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.find_unused_parameters = False
        self.fuse_grad_size_in_MB = 32
        self.last_comm_group_size_MB = 1


class Fleet:
    def __init__(self):
        self._is_initialized = False
        self._hcg = None
        self._strategy = None
        self._user_defined_optimizer = None
        self._role_maker = None
        self._ps_ctx = None

    def init(self, role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
        self._strategy = strategy or DistributedStrategy()
        # parameter-server mode (reference fleet.py:151: a non-collective
        # role maker selects the PS runtime, the_one_ps.py).  The ROLE
        # MAKER drives the mode — the canonical reference call is
        # fleet.init(PaddleCloudRoleMaker(is_collective=False)) with no
        # second argument, so the is_collective parameter is only the
        # fallback when the role maker doesn't say.
        ps_mode = role_maker is not None \
            and not getattr(role_maker, "_is_collective", is_collective)
        if ps_mode:
            from ..ps import init_ps
            self._role_maker = role_maker
            # an explicit-args role maker carries the endpoints itself;
            # init_ps applies the PADDLE_MASTER_ENDPOINT-over-argument
            # precedence for every caller
            eps = role_maker.get_pserver_endpoints()
            self._ps_ctx = init_ps(
                role="server" if role_maker.is_server() else "worker",
                index=(role_maker.server_index() if role_maker.is_server()
                       else role_maker.worker_index()),
                num_servers=role_maker.server_num(),
                num_workers=role_maker.worker_num(),
                master_endpoint=eps[0] if eps else None)
            self._is_initialized = True
            return self
        init_parallel_env()
        hc = self._strategy.hybrid_configs
        topo = CommunicateTopology(
            hybrid_group_names=["data", "pipe", "sharding", "sep", "model"],
            dims=[hc.get("dp_degree", 1), hc.get("pp_degree", 1),
                  hc.get("sharding_degree", 1), hc.get("sep_degree", 1),
                  hc.get("mp_degree", 1)])
        self._hcg = HybridCommunicateGroup(topo)
        self._is_initialized = True
        return self

    def is_first_worker(self):
        if self._role_maker is not None:
            return self._role_maker.is_first_worker()
        return get_rank() == 0

    def worker_index(self):
        if self._role_maker is not None:
            return self._role_maker.worker_index()
        return get_rank()

    def worker_num(self):
        if self._role_maker is not None:
            return self._role_maker.worker_num()
        return get_world_size()

    # -- parameter-server mode (reference fleet.py is_server/init_server/
    #    run_server/init_worker/stop_worker over the_one_ps runtime) -------
    def is_server(self):
        return self._role_maker is not None and self._role_maker.is_server()

    def is_worker(self):
        return self._role_maker is None or self._role_maker.is_worker()

    def server_num(self):
        return self._role_maker.server_num() if self._role_maker else 0

    def server_index(self):
        return self._role_maker.server_index() if self._role_maker else 0

    def init_server(self, dirname=None, **kwargs):
        """Tables materialize on worker broadcast; a checkpoint dirname
        (reference fleet.init_server(model_dir)) is recorded so the load
        happens right after that broadcast creates them."""
        if dirname:
            from ..ps import server as ps_server
            ps_server.set_pending_load(dirname)

    def run_server(self):
        """Serve until a worker calls stop_worker (blocks)."""
        from ..rpc import shutdown
        self._ps_ctx.server.run()
        shutdown()

    def init_worker(self, table_specs=None):
        if table_specs:
            self._ps_ctx.client.create_tables(table_specs)

    @property
    def ps_client(self):
        return self._ps_ctx.client if self._ps_ctx else None

    def get_hybrid_communicate_group(self):
        return self._hcg

    def distributed_model(self, model):
        from .meta_parallel import wrap_distributed_model
        return wrap_distributed_model(model, self._hcg, self._strategy)

    def distributed_optimizer(self, optimizer, strategy=None):
        self._user_defined_optimizer = optimizer
        from .meta_parallel import HybridParallelOptimizer
        return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)

    @property
    def strategy(self):
        return self._strategy

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    def stop_worker(self):
        if self._ps_ctx is not None:
            from ..ps import stop_workers_and_servers
            stop_workers_and_servers(self._ps_ctx)


fleet = Fleet()
