"""TCPStore-backed elastic membership manager.

Reference: ElasticManager (fleet/elastic/manager.py:125) — etcd node
registry at /paddle/nodes, lease-kept-alive heartbeats, a watch callback
that sets need_sync on membership change, and ELASTIC_STOP/exit codes that
drive the launch controller's relaunch loop.

Here the same protocol runs over the TCPStore:
- every node sets  elastic/<job>/node/<host_id> = <monotonic heartbeat>
  every ``heartbeat_interval`` seconds;
- liveness = heartbeat age < ``lease_ttl`` (store entries cannot expire
  server-side like etcd leases, so expiry is evaluated by readers);
- the watch thread re-lists membership and compares against the expected
  node set; under-provisioned -> WAIT, over/changed -> NEED_LAUNCH, within
  the elastic range and stable -> OK.
"""
from __future__ import annotations

import threading
import time

__all__ = ["ElasticManager", "ElasticStatus"]


class ElasticStatus:
    OK = "ok"                # membership matches; keep training
    WAIT = "wait"            # below np_lo: hold for nodes
    NEED_LAUNCH = "relaunch"  # membership changed within range: restart job
    ERROR = "error"          # above np_hi or unrecoverable
    EXIT = "exit"            # shutdown requested


def _parse_np(np_range) -> tuple[int, int]:
    """'2' -> (2,2); '2:4' -> (2,4) (the launch --nnodes contract)."""
    s = str(np_range)
    if ":" in s:
        lo, hi = s.split(":", 1)
        return int(lo), int(hi)
    return int(s), int(s)


class ElasticManager:
    def __init__(self, store, job_id: str, host_id: str, np_range="1",
                 heartbeat_interval: float = 2.0, lease_ttl: float = 10.0):
        self.store = store
        self.job_id = job_id
        self.host_id = host_id
        self.np_lo, self.np_hi = _parse_np(np_range)
        self.heartbeat_interval = heartbeat_interval
        self.lease_ttl = lease_ttl
        self.elastic = self.np_lo != self.np_hi
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._status = ElasticStatus.WAIT
        self._members: list[str] = []
        self._threads: list[threading.Thread] = []

    # --- registry -------------------------------------------------------

    def _key(self, host):
        return f"elastic/{self.job_id}/node/{host}"

    def _nreg_key(self):
        return f"elastic/{self.job_id}/nreg"

    def _slot_key(self, idx):
        return f"elastic/{self.job_id}/reg/{idx}"

    def register(self):
        """Join the registry and start heartbeat + watch threads
        (reference manager.py: etcd put + refresh_lease loop).

        Registration is race-free: each node atomically claims a slot index
        via the store's add counter and writes only its own slot key —
        concurrent joins cannot clobber each other the way a shared
        read-modify-write hosts list would.
        """
        self._slot = self.store.add(self._nreg_key(), 1)
        self.store.set(self._slot_key(self._slot), self.host_id)
        self._beat()
        for fn in (self._heartbeat_loop, self._watch_loop):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"elastic-{fn.__name__}")
            t.start()
            self._threads.append(t)

    def _beat(self):
        self.store.set(self._key(self.host_id), repr(time.time()))

    def _list_registered(self):
        try:
            n = self.store.add(self._nreg_key(), 0)
        except Exception:
            return []
        out = []
        for i in range(1, int(n) + 1):
            try:
                h = self.store.get(self._slot_key(i), timeout=0.5).decode()
            except Exception:
                continue
            if h and h not in out:
                out.append(h)
        return out

    def alive_nodes(self) -> list[str]:
        now = time.time()
        out = []
        for h in self._list_registered():
            try:
                beat = float(self.store.get(self._key(h), timeout=0.5))
            except Exception:
                continue
            if now - beat < self.lease_ttl:
                out.append(h)
        return out

    # --- threads --------------------------------------------------------

    def _heartbeat_loop(self):
        while not self._stop.is_set():
            try:
                self._beat()
            except Exception:
                pass
            self._stop.wait(self.heartbeat_interval)

    def _watch_loop(self):
        prev = None
        while not self._stop.is_set():
            try:
                cur = sorted(self.alive_nodes())
            except Exception:
                cur = []
            with self._lock:
                self._members = cur
                n = len(cur)
                # NEED_LAUNCH latches until consume_relaunch() reads it —
                # a controller polling slower than the heartbeat must not
                # lose the signal (reference need_sync is consumed, not
                # recomputed per watch tick)
                latched = self._status in (ElasticStatus.NEED_LAUNCH,
                                           ElasticStatus.EXIT)
                if n < self.np_lo:
                    if not latched:
                        self._status = ElasticStatus.WAIT
                elif n > self.np_hi:
                    self._status = ElasticStatus.ERROR
                elif prev is not None and cur != prev \
                        and self._status != ElasticStatus.EXIT:
                    # in-range membership change: job must relaunch on the
                    # new node set (reference need_sync + NeedLaunch)
                    self._status = ElasticStatus.NEED_LAUNCH
                elif not latched:
                    self._status = ElasticStatus.OK
            prev = cur
            self._stop.wait(self.heartbeat_interval)

    # --- controller API (consumed by the launch relaunch loop) ---------

    def status(self) -> str:
        with self._lock:
            return self._status

    def members(self) -> list[str]:
        with self._lock:
            return list(self._members)

    def wait_ready(self, timeout: float = 60.0) -> bool:
        """Block until membership reaches the elastic range."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(self.alive_nodes()) >= self.np_lo:
                return True
            time.sleep(self.heartbeat_interval / 2)
        return False

    def consume_relaunch(self) -> bool:
        """True once per membership change (controller restarts the job)."""
        with self._lock:
            if self._status == ElasticStatus.NEED_LAUNCH:
                self._status = ElasticStatus.OK
                return True
            return False

    def exit(self):
        with self._lock:
            self._status = ElasticStatus.EXIT
        self._stop.set()
        # drop this node from the registry so peers see the leave quickly
        try:
            if getattr(self, "_slot", None) is not None:
                self.store.set(self._slot_key(self._slot), "")
            self.store.set(self._key(self.host_id), repr(0.0))
        except Exception:
            pass
        for t in self._threads:
            t.join(timeout=2.0)
