"""Elastic training manager (reference
python/paddle/distributed/fleet/elastic/manager.py:125 ElasticManager —
etcd-backed node registry, membership watch, scale-event relaunch).

TPU-native substitution: the registry rides the native TCPStore instead of
etcd (this build's single coordination service, csrc/tcp_store.cc; no etcd
in a TPU pod's control plane).  Nodes heartbeat a lease key; the watch
thread detects joins/leaves from lease expiry and flips the manager into
NeedLaunch, which the launch controller consumes to restart the job with
the surviving node set.
"""
from .manager import ElasticManager, ElasticStatus

__all__ = ["ElasticManager", "ElasticStatus"]
