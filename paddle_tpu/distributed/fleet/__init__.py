"""fleet namespace: hybrid-parallel orchestration.

Parity target: /root/reference/python/paddle/distributed/fleet/ (topology,
DistributedStrategy, distributed_model, meta_parallel TP/PP/SP layers,
GroupSharded).  Populated incrementally — see paddle_tpu/distributed/fleet/
submodules.
"""
from .base import DistributedStrategy, Fleet, fleet  # noqa: F401
from .role_maker import (  # noqa: F401
    PaddleCloudRoleMaker, Role, RoleMakerBase, UserDefinedRoleMaker)
from .topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401
from . import layers  # noqa: F401
from . import utils  # noqa: F401
from .recompute import recompute, recompute_sequential  # noqa: F401
from .meta_parallel import (  # noqa: F401
    GroupShardedOptimizerStage2, GroupShardedStage2, GroupShardedStage3,
    HybridParallelOptimizer, PipelineParallel, TensorParallel,
)
from . import meta_optimizers  # noqa: F401

init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
