"""Hybrid-parallel topology.

Parity with /root/reference/python/paddle/distributed/fleet/base/topology.py
(CommunicateTopology :70, HybridCommunicateGroup :189): rank <-> (pp, mp,
sep, sharding, dp) coordinate mapping and per-axis groups.

TPU-native: the topology *is* a device mesh.  Axis order follows the
reference (pp outermost, then sep, then sharding/dp, mp innermost so TP rides
the fastest ICI links — same intent as NCCL ring placement).
"""
from __future__ import annotations

import collections
import itertools

import numpy as np

from ..collective import new_group

__all__ = ["CommunicateTopology", "HybridCommunicateGroup", "ParallelMode"]


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep", "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = collections.namedtuple("Coordinate", self._parallel_names)
        self.world_size = int(np.prod(self._dims))
        ranges = [range(d) for d in self._dims]
        all_coords = [self.coordinate(*c) for c in itertools.product(*ranges)]
        self._coord2rank = {c: i for i, c in enumerate(all_coords)}
        self._rank2coord = {i: c for c, i in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def get_rank(self, **kwargs):
        return self._coord2rank[self.coordinate(**kwargs)]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return sorted(r for c, r in self._coord2rank.items() if c[axis] == index)

    def get_comm_list(self, axis_name):
        """All groups along axis_name: list of rank-lists."""
        axis = self._parallel_names.index(axis_name)
        other_ranges = [range(d) for i, d in enumerate(self._dims) if i != axis]
        out = []
        for other in itertools.product(*other_ranges):
            ranks = []
            for v in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, v)
                ranks.append(self._coord2rank[self.coordinate(*coord)])
            out.append(ranks)
        return out

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)
        tf = coord._replace(**kwargs)._asdict()
        return self.get_rank(**tf)


class HybridCommunicateGroup:
    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        from ..parallel import get_rank
        self.global_rank = get_rank()
        self._dp_degree = self._topo.get_dim("data")
        self._mp_degree = self._topo.get_dim("model")
        self._pp_degree = self._topo.get_dim("pipe")
        self._sharding_degree = self._topo.get_dim("sharding")
        self._sep_degree = (self._topo.get_dim("sep")
                            if "sep" in self._topo.get_hybrid_group_names() else 1)

        self._dp_group, self._dp_comm_group = self._build("data")
        self._mp_group, self._mp_comm_group = self._build("model")
        self._pp_group, self._pp_comm_group = self._build("pipe")
        self._sharding_group, self._sharding_comm_group = self._build("sharding")
        if self._sep_degree > 1 or "sep" in self._topo.get_hybrid_group_names():
            self._sep_group, self._sep_comm_group = self._build("sep")
        else:
            self._sep_group, self._sep_comm_group = None, None

    def _build(self, axis_name):
        comm_lists = self._topo.get_comm_list(axis_name)
        my_group = None
        my_ranks = None
        axis_alias = {"data": "dp", "model": "mp", "pipe": "pp",
                      "sharding": "sharding", "sep": "sep"}[axis_name]
        for ranks in comm_lists:
            if self.global_rank in ranks:
                my_ranks = ranks
                my_group = new_group(ranks, axis_name=axis_alias)
        return my_ranks, my_group

    # topology info
    def topology(self):
        return self._topo

    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 and \
                self._sharding_degree == 1 and self._dp_degree > 1:
            return ParallelMode.DATA_PARALLEL
        if self._mp_degree > 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._pp_degree > 1:
            return ParallelMode.PIPELINE_PARALLEL
        if self._sharding_degree > 1:
            return ParallelMode.SHARDING_PARALLEL
        return ParallelMode.DATA_PARALLEL

    # data parallel
    def get_data_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).data

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_comm_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).model

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_comm_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group[0]

    # pipeline parallel
    def get_stage_id(self):
        return self._topo.get_coord(self.global_rank).pipe

    def get_pipe_parallel_rank(self):
        return self.get_stage_id()

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_comm_group

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_groups(self):
        return None

    # sharding
    def get_sharding_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).sharding

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_comm_group

    def get_sharding_parallel_group_src_rank(self):
        return self._sharding_group[0]

    # sep
    def get_sep_parallel_rank(self):
        c = self._topo.get_coord(self.global_rank)
        return getattr(c, "sep", 0)

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._sep_comm_group

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank, pipe=stage_id,
                                              **kwargs)

    def get_jax_mesh(self):
        """Materialize the hybrid topology as a jax Mesh with axes
        (pp, sep, sharding, dp, mp) — the TPU-native backing for TP/SP
        layers.  Returns None when the local device count can't host the
        topology (then layers degenerate to serial)."""
        if getattr(self, "_jax_mesh", None) is not None:
            return self._jax_mesh
        import jax

        alias = {"pipe": "pp", "sep": "sep", "sharding": "sharding",
                 "data": "dp", "model": "mp"}
        present = self._topo.get_hybrid_group_names()
        # mesh axis order pp > sep > sharding > dp > mp (TP innermost rides
        # the fastest ICI links), restricted to axes the topology declares
        order = [n for n in ("pipe", "sep", "sharding", "data", "model")
                 if n in present]
        world = self._topo.world_size
        if len(jax.devices()) < world:
            return None
        # rank r's coordinate in the reference topology maps to device r:
        # permute the row-major rank grid from topology order to mesh order
        topo_dims = [self._topo.get_dim(n) for n in present]
        grid = np.arange(world).reshape(topo_dims)
        perm = [present.index(n) for n in order]
        rank_grid = np.transpose(grid, perm)
        from ..auto_parallel.process_mesh import ProcessMesh
        self._jax_mesh = ProcessMesh(
            rank_grid, [alias[n] for n in order]).jax_mesh()
        return self._jax_mesh
