from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from .mp_ops import (  # noqa: F401
    _c_concat, _c_identity, _c_lookup_table, _c_softmax_with_cross_entropy,
    _c_split, _mp_allreduce, split,
)
from .random import (  # noqa: F401
    RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed,
)
