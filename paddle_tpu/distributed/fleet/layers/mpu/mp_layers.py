"""Megatron-style tensor-parallel layers.

Parity with /root/reference/python/paddle/distributed/fleet/layers/mpu/
mp_layers.py (VocabParallelEmbedding :49, ColumnParallelLinear :336,
RowParallelLinear :543, ParallelCrossEntropy :744).

TPU-native design: parameters keep their FULL logical shape and carry a
NamedSharding over the hybrid mesh's "mp" axis (vocab dim for embeddings,
out-dim for column, in-dim for row).  GSPMD then partitions the matmuls and
inserts the identity/allreduce/allgather collectives the reference issues
manually through NCCL — same math, compiler-placed comms on ICI.  With
mp_degree==1 (or no mesh) every layer degenerates to its serial form, which
matches the reference's fast path.  state_dicts hold full tensors, so
checkpoints are rank-count independent (an improvement over per-rank shard
files; distributed.checkpoint handles re-sharding).
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .....nn import functional as F
from .....nn.initializer import Constant, XavierNormal
from .....nn.initializer.attr import ParamAttr
from .....nn.layer.layers import Layer
from .mp_ops import _c_softmax_with_cross_entropy

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _mp_context(mp_group):
    """Resolve (mesh, mp_axis_name, nranks) for the current fleet topology.
    Returns (None, None, 1) when TP is degenerate."""
    from ...base import fleet as _fleet
    hcg = _fleet._hcg
    if mp_group is not None and mp_group.nranks <= 1:
        return None, None, 1
    if hcg is None:
        return None, None, 1
    n = hcg.get_model_parallel_world_size()
    if n <= 1:
        return None, None, 1
    mesh = hcg.get_jax_mesh()
    if mesh is None:
        return None, None, n
    return mesh, "mp", n


def _shard_param(param, mesh, spec):
    if mesh is None or param is None:
        return param
    param._data = jax.device_put(param._data, NamedSharding(mesh, spec))
    return param


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over the TP group
    (reference mp_layers.py:49)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.mesh, self.mp_axis, self.world_size = _mp_context(mp_group)
        if num_embeddings % self.world_size != 0:
            raise ValueError(
                f"vocab size {num_embeddings} must divide mp degree "
                f"{self.world_size}")
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.is_mp = self.world_size > 1
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim],
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=XavierNormal())
        _shard_param(self.weight, self.mesh, P(self.mp_axis, None))

    def forward(self, x):
        return F.embedding(x, self.weight)

    def extra_repr(self):
        return (f"{self.num_embeddings}, {self.embedding_dim}, "
                f"mp={self.world_size}")


class ColumnParallelLinear(Layer):
    """Linear with the OUT dim sharded over TP (reference mp_layers.py:336).
    gather_output=False leaves the activation out-dim mp-sharded for a
    following RowParallelLinear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.mesh, self.mp_axis, self.world_size = _mp_context(mp_group)
        if out_features % self.world_size != 0:
            raise ValueError(
                f"out_features {out_features} must divide mp degree "
                f"{self.world_size}")
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.is_mp = self.world_size > 1
        self.weight = self.create_parameter(
            [in_features, out_features], attr=ParamAttr._to_attr(weight_attr),
            default_initializer=XavierNormal())
        self.bias = (None if has_bias is False else self.create_parameter(
            [out_features],
            attr=None if isinstance(has_bias, (bool, type(None)))
            else ParamAttr._to_attr(has_bias),
            is_bias=True))
        _shard_param(self.weight, self.mesh, P(None, self.mp_axis))
        _shard_param(self.bias, self.mesh, P(self.mp_axis))

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.is_mp and self.mesh is not None:
            spec = ([None] * (out.ndim - 1)) + (
                [None] if self.gather_output else [self.mp_axis])
            out._data = jax.device_put(
                out._data, NamedSharding(self.mesh, P(*spec)))
        return out

    def extra_repr(self):
        return (f"in={self.in_features}, out={self.out_features}, "
                f"mp={self.world_size}, gather_output={self.gather_output}")


class RowParallelLinear(Layer):
    """Linear with the IN dim sharded over TP (reference mp_layers.py:543);
    the partial products are summed by the compiler-inserted allreduce that
    the reference issues as mp_allreduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.mesh, self.mp_axis, self.world_size = _mp_context(mp_group)
        if in_features % self.world_size != 0:
            raise ValueError(
                f"in_features {in_features} must divide mp degree "
                f"{self.world_size}")
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.is_mp = self.world_size > 1
        self.weight = self.create_parameter(
            [in_features, out_features], attr=ParamAttr._to_attr(weight_attr),
            default_initializer=XavierNormal())
        self.bias = (self.create_parameter(
            [out_features], is_bias=True) if has_bias else None)
        _shard_param(self.weight, self.mesh, P(self.mp_axis, None))
        # bias is applied AFTER the reduction -> replicated

    def forward(self, x):
        if self.is_mp and self.mesh is not None and not self.input_is_parallel:
            spec = ([None] * (x.ndim - 1)) + [self.mp_axis]
            x._data = jax.device_put(
                x._data, NamedSharding(self.mesh, P(*spec)))
        out = F.linear(x, self.weight, self.bias)
        if self.is_mp and self.mesh is not None:
            out._data = jax.device_put(
                out._data,
                NamedSharding(self.mesh, P(*([None] * out.ndim))))
        return out

    def extra_repr(self):
        return (f"in={self.in_features}, out={self.out_features}, "
                f"mp={self.world_size}, input_is_parallel="
                f"{self.input_is_parallel}")


class ParallelCrossEntropy(Layer):
    """Softmax cross entropy over class-dim-sharded logits
    (reference mp_layers.py:744)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.mp_group = mp_group
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return _c_softmax_with_cross_entropy(
            input, label, group=self.mp_group, ignore_index=self.ignore_index)
