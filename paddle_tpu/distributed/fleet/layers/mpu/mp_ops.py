"""Model-parallel communication ops.

Parity with /root/reference/python/paddle/distributed/fleet/layers/mpu/mp_ops.py
(_c_identity, _c_concat, _c_split, _mp_allreduce, _c_lookup_table,
_c_softmax_with_cross_entropy, split).

TPU-native semantics: in the single-controller model a "TP-sharded" tensor is
a jax.Array whose last (or vocab) dim carries a NamedSharding over the mp
mesh axis; GSPMD materialises the collectives.  Two execution regimes:

- traced (inside shard_map over a mesh that has the group's axis name):
  emit explicit lax collectives — identical to the reference's NCCL calls
  but compiled onto ICI;
- eager: the group degenerates (nranks==1 fast path, matching the reference)
  or the arrays are mesh-sharded and resharding is a device_put.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .....core.tensor import Tensor
from .... import collective as C

__all__ = ["_c_identity", "_c_concat", "_c_split", "_mp_allreduce",
           "_c_lookup_table", "_c_softmax_with_cross_entropy", "split"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else x


def _traced(x) -> bool:
    return isinstance(_arr(x), jax.core.Tracer)


def _axis_of(group):
    g = group or C.get_group(0)
    return g.axis_name if g is not None else None


def _nranks(group):
    g = group or C.get_group(0)
    return g.nranks if g is not None else 1


def _c_identity(tensor, group=None, skip_c_identity_dynamic=False):
    """Forward identity; backward allreduce over the mp group (the entry
    point of a column-parallel region)."""
    if _nranks(group) <= 1:
        return tensor
    axis = _axis_of(group)
    if _traced(tensor) and axis is not None:
        arr = _arr(tensor)

        @jax.custom_vjp
        def ident(x):
            return x

        def fwd(x):
            return x, None

        def bwd(_, g):
            return (lax.psum(g, axis),)

        ident.defvjp(fwd, bwd)
        out = ident(arr)
        return Tensor(out) if isinstance(tensor, Tensor) else out
    return tensor


def _mp_allreduce(tensor, op=C.ReduceOp.SUM, group=None, use_calc_stream=True,
                  use_model_parallel=True):
    """Forward allreduce; backward identity (the exit of a row-parallel
    region)."""
    if _nranks(group) <= 1:
        return tensor
    axis = _axis_of(group)
    if _traced(tensor) and axis is not None:
        arr = _arr(tensor)

        @jax.custom_vjp
        def ar(x):
            return lax.psum(x, axis)

        def fwd(x):
            return lax.psum(x, axis), None

        def bwd(_, g):
            return (g,)

        ar.defvjp(fwd, bwd)
        out = ar(arr)
        return Tensor(out) if isinstance(tensor, Tensor) else out
    return C.all_reduce(tensor, op=op, group=group)


def _c_concat(tensor, group=None):
    """All-gather along the LAST dim (column-parallel gather_output)."""
    n = _nranks(group)
    if n <= 1:
        return tensor
    axis = _axis_of(group)
    if _traced(tensor) and axis is not None:
        arr = _arr(tensor)
        out = lax.all_gather(arr, axis, axis=arr.ndim - 1, tiled=True)
        return Tensor(out) if isinstance(tensor, Tensor) else out
    raise RuntimeError("eager cross-device _c_concat requires captured mode")


def _c_split(tensor, group=None):
    """Split along the LAST dim, keep the local rank's slice (inverse of
    _c_concat)."""
    n = _nranks(group)
    if n <= 1:
        return tensor
    axis = _axis_of(group)
    if _traced(tensor) and axis is not None:
        arr = _arr(tensor)
        size = arr.shape[-1] // n
        idx = lax.axis_index(axis)
        out = lax.dynamic_slice_in_dim(arr, idx * size, size, axis=arr.ndim - 1)
        return Tensor(out) if isinstance(tensor, Tensor) else out
    raise RuntimeError("eager cross-device _c_split requires captured mode")


def _c_lookup_table(table, index, start_index=0, group=None, name=None):
    """Vocab-parallel embedding lookup: `table` is the LOCAL vocab shard
    starting at `start_index`; out-of-range ids contribute zeros and the
    caller completes the lookup with _mp_allreduce."""
    t, ids = _arr(table), _arr(index)
    v_local = t.shape[0]
    local_ids = ids - start_index
    in_range = (local_ids >= 0) & (local_ids < v_local)
    safe = jnp.clip(local_ids, 0, v_local - 1)
    out = jnp.take(t, safe, axis=0)
    out = jnp.where(in_range[..., None], out, jnp.zeros((), out.dtype))
    return Tensor(out) if isinstance(table, Tensor) else out


def _c_softmax_with_cross_entropy(logits, label, group=None,
                                  return_softmax=False, ignore_index=-100):
    """Cross entropy with the class dim sharded over the mp group.

    Traced: the reference's ParallelCrossEntropy — pmax for the global max,
    psum for the partition function and the picked logit.  Degenerate:
    ordinary stable softmax cross entropy.
    """
    lg, lb = _arr(logits), _arr(label)
    squeeze = False
    if lb.ndim == lg.ndim and lb.shape[-1] == 1:
        lb = lb[..., 0]
        squeeze = True
    n = _nranks(group)
    axis = _axis_of(group)
    lf = lg.astype(jnp.float32)
    if n > 1 and _traced(logits) and axis is not None:
        v_local = lf.shape[-1]
        lo = lax.axis_index(axis) * v_local
        local_max = jnp.max(lf, axis=-1)
        gmax = lax.stop_gradient(lax.pmax(lax.stop_gradient(local_max), axis))
        z = jnp.exp(lf - gmax[..., None])
        denom = lax.psum(jnp.sum(z, axis=-1), axis)
        local_label = lb - lo
        in_range = (local_label >= 0) & (local_label < v_local)
        safe = jnp.clip(local_label, 0, v_local - 1)
        picked = jnp.take_along_axis(lf, safe[..., None], axis=-1)[..., 0]
        picked = jnp.where(in_range, picked, 0.0)
        correct = lax.psum(picked, axis)
        loss = gmax + jnp.log(denom) - correct
        softmax = z / denom[..., None]
    else:
        gmax = jnp.max(lf, axis=-1, keepdims=True)
        z = jnp.exp(lf - gmax)
        denom = jnp.sum(z, axis=-1)
        picked = jnp.take_along_axis(lf, jnp.clip(lb, 0, lf.shape[-1] - 1)[..., None],
                                     axis=-1)[..., 0]
        loss = gmax[..., 0] + jnp.log(denom) - picked
        softmax = z / denom[..., None]
    if squeeze:
        loss = loss[..., None]
    loss_t = Tensor(loss) if isinstance(logits, Tensor) else loss
    if return_softmax:
        sm = Tensor(softmax) if isinstance(logits, Tensor) else softmax
        return loss_t, sm
    return loss_t


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """paddle.distributed.split parity
    (/root/reference/python/paddle/distributed/collective.py split API):
    build a TP-partitioned linear/embedding layer and apply it."""
    from .mp_layers import (ColumnParallelLinear, RowParallelLinear,
                            VocabParallelEmbedding)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1], weight_attr=weight_attr)
        return layer(x)
    if operation == "linear":
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                      has_bias=bias_attr is not False,
                                      input_is_parallel=False)
        else:
            layer = ColumnParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        return layer(x)
    raise ValueError(f"unsupported operation {operation}")
