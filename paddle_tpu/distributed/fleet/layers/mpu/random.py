"""Tensor-parallel RNG state tracking.

Parity with /root/reference/python/paddle/distributed/fleet/layers/mpu/random.py
(RNGStatesTracker): some random ops must agree across the TP group (e.g.
dropout on sequence-parallel activations) while others must differ per rank
(dropout on TP-sharded activations).  The tracker keeps named seeded streams
and swaps the global generator while a stream is active.

TPU-native: streams are independent JAX PRNG key chains (core.random_state),
so "swap the state" is exact and cheap — no device RNG state copies.
"""
from __future__ import annotations

import contextlib

from .....core import random_state

__all__ = ["RNGStatesTracker", "get_rng_state_tracker",
           "model_parallel_random_seed", "MODEL_PARALLEL_RNG"]

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        outer = random_state.get_rng_state()
        random_state.seed(seed)
        self.states_[name] = random_state.get_rng_state()
        random_state.set_rng_state(outer)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        outer = random_state.get_rng_state()
        random_state.set_rng_state(self.states_[name])
        try:
            yield
        finally:
            self.states_[name] = random_state.get_rng_state()
            random_state.set_rng_state(outer)


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import random as _pyrandom

    from ...base import fleet as _fleet_singleton
    hcg = _fleet_singleton._hcg
    rank = hcg.get_model_parallel_rank() if hcg is not None else 0
    if seed:
        global_seed = seed
        local_seed = seed * 1024 + rank * 100
    else:
        global_seed = _pyrandom.randint(0, 655350)
        local_seed = _pyrandom.randint(rank * 10000, (rank + 1) * 10000 - 1)
    _RNG_STATE_TRACKER.reset()
    _RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, local_seed)
    random_state.seed(global_seed)
