"""Rendezvous store (native TCPStore).

TPU-native equivalent of the reference store layer
(/root/reference/paddle/phi/core/distributed/store/tcp_store.h:121, python
binding paddle/fluid/pybind/communication.cc:91): a key-value store with a
master daemon on rank 0 used for control-plane rendezvous (launch
coordination, barriers, elastic membership).  Device collectives ride XLA
over ICI/DCN and never touch this store.

Backed by the native C++ core (csrc/tcp_store.cc) via ctypes.
"""
from __future__ import annotations

import os

from ..core._native import NativeError, TCPStore  # noqa: F401

__all__ = ["TCPStore", "create_default_store", "barrier_via_store"]

_default_store = None


def create_default_store(timeout: float = 90.0):
    """Build the process-wide store from the launch env contract
    (MASTER_ADDR/MASTER_PORT + rank), mirroring
    core.create_or_get_global_tcp_store (parallel.py:1134)."""
    global _default_store
    if _default_store is not None:
        return _default_store
    host = os.environ.get("MASTER_ADDR", "127.0.0.1")
    port = int(os.environ.get("MASTER_PORT", "0") or 0)
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    _default_store = TCPStore(host, port, is_master=(rank == 0),
                              timeout=timeout)
    return _default_store


def barrier_via_store(store: TCPStore, prefix: str, rank: int,
                      world_size: int, timeout: float = 90.0):
    """Store-based host barrier: every rank bumps a counter then waits for
    the release key written when all arrived (reference barrier-over-store
    pattern in ProcessGroup init).

    Reusable with the same prefix: the shared arrival counter derives a
    generation number, and each generation gets its own release key.
    """
    n = store.add(f"{prefix}/count", 1)
    gen = (n - 1) // world_size
    if n == (gen + 1) * world_size:
        store.set(f"{prefix}/release/{gen}", b"1")
    store.wait([f"{prefix}/release/{gen}"], timeout=timeout)
