"""Single-controller process launcher with elastic restarts.

Usage (mirrors the reference CLI):
    python -m paddle_tpu.distributed.launch \
        --nproc_per_node 4 --log_dir log train.py --arg1 ...

Reference behavior replicated (launch/main.py, controllers/collective.py,
fleet/elastic/manager.py:125):
  - per-rank env: PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM,
    PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINER_ENDPOINTS, PADDLE_MASTER,
    PADDLE_LOCAL_RANK, PADDLE_NNODES
  - per-rank log files under --log_dir (rank 0 tees to stdout)
  - on worker failure: kill the peer group and, while --max_restart isn't
    exhausted (elastic level >= 1), relaunch the whole job
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch a collective job (reference launch/main.py)")
    p.add_argument("--master", default=None,
                   help="master endpoint ip:port (default: local auto)")
    p.add_argument("--rank", type=int, default=0, help="node rank")
    p.add_argument("--nnodes", default="1",
                   help="node count, or elastic range 'lo:hi'")
    p.add_argument("--nproc_per_node", type=int, default=None)
    p.add_argument("--log_dir", default="log")
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--job_id", default="default")
    p.add_argument("--devices", "--gpus", default=None,
                   help="device ids for this node")
    p.add_argument("--ips", default=None, help="legacy node ip list")
    p.add_argument("--elastic_level", type=int, default=-1)
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--auto_tuner_json", default=None,
                   help="hybrid-parallel auto-tuner config (reference "
                        "launch --auto_tuner_json): search+score candidate "
                        "configs before launching; best config is exported "
                        "to workers as PADDLE_AUTO_TUNER_BEST")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _worker_env(rank, nprocs, ports, master, nnodes, device_ids=None):
    env = dict(os.environ)
    endpoints = ",".join(f"127.0.0.1:{p}" for p in ports)
    dev = device_ids[rank] if device_ids else str(rank)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_LOCAL_RANK": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_CURRENT_ENDPOINT": f"127.0.0.1:{ports[rank]}",
        "PADDLE_TRAINER_ENDPOINTS": endpoints,
        "PADDLE_MASTER": master,
        "PADDLE_NNODES": str(nnodes),
        "FLAGS_selected_tpus": dev,
    })
    return env


def _spawn(args, nprocs):
    os.makedirs(args.log_dir, exist_ok=True)
    ports = [_free_port() for _ in range(nprocs)]
    master = args.master or f"127.0.0.1:{ports[0]}"
    device_ids = ([d.strip() for d in args.devices.split(",")]
                  if args.devices else None)
    procs = []
    logs = []
    for rank in range(nprocs):
        env = _worker_env(rank, nprocs, ports, master, args.nnodes,
                          device_ids)
        cmd = [sys.executable, "-u", args.training_script,
               *args.training_script_args]
        logf = open(os.path.join(args.log_dir,
                                 f"workerlog.{rank}"), "ab", buffering=0)
        logs.append(logf)
        procs.append(subprocess.Popen(
            cmd, env=env, stdout=logf, stderr=subprocess.STDOUT))
    return procs, logs


def _wait(procs):
    """Wait for all workers; on any nonzero exit, kill the rest and return
    that code.  Returns 0 when every worker succeeds."""
    while True:
        alive = False
        for p in procs:
            rc = p.poll()
            if rc is None:
                alive = True
            elif rc != 0:
                for q in procs:
                    if q.poll() is None:
                        q.send_signal(signal.SIGTERM)
                deadline = time.time() + 10
                for q in procs:
                    try:
                        q.wait(timeout=max(0.1, deadline - time.time()))
                    except subprocess.TimeoutExpired:
                        q.kill()
                return rc
        if not alive:
            return 0
        time.sleep(0.2)


def _run_auto_tuner(args) -> dict | None:
    """Search+score hybrid configs before launching (reference
    launch/main.py auto-tuner mode, which runs a trial JOB per candidate;
    here candidates are scored by AOT compile probes — tuner.py
    measure_cfg — so tuning happens in-process in seconds)."""
    import json

    # honor the caller's platform pin BEFORE any backend init: environment
    # sitecustomize may re-pin JAX_PLATFORMS to a hardware plugin whose
    # init can hang when the device service is unreachable (the
    # tests/conftest.py pattern — env var alone is not enough)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat.split(",")[0])
        except Exception:
            pass

    from ..auto_tuner import AutoTuner

    with open(args.auto_tuner_json) as f:
        tuner_cfg = json.load(f)
    max_trials = int(tuner_cfg.pop("max_trials", 8))
    tuner = AutoTuner(tuner_cfg)
    os.makedirs(args.log_dir, exist_ok=True)
    hist = os.path.join(args.log_dir, "auto_tuner_history.csv")
    best, err = tuner.tune(max_trials=max_trials, history_path=hist)
    if err or best is None:
        print(f"[launch] auto-tuner: no feasible config found "
              f"(history: {hist})", file=sys.stderr)
        return None
    best = {k: v for k, v in best.items() if not k.startswith("_")}
    print(f"[launch] auto-tuner best config: {best} (history: {hist})",
          file=sys.stderr)
    return best


def launch(argv=None) -> int:
    args = _parse_args(argv)
    if args.auto_tuner_json:
        import json
        best = _run_auto_tuner(args)
        if best is not None:
            os.environ["PADDLE_AUTO_TUNER_BEST"] = json.dumps(best)
    nprocs = args.nproc_per_node
    if nprocs is None:
        devs = args.devices
        nprocs = len(devs.split(",")) if devs else 1
    elastic = args.elastic_level >= 1 or ":" in str(args.nnodes)
    restarts = 0
    while True:
        procs, logs = _spawn(args, nprocs)
        rc = _wait(procs)
        for f in logs:
            f.close()
        if rc == 0:
            return 0
        if elastic and restarts < args.max_restart:
            restarts += 1
            print(f"[launch] workers failed (exit {rc}); restart "
                  f"{restarts}/{args.max_restart}", file=sys.stderr)
            continue
        return rc


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
