"""Single-controller process launcher with elastic restarts.

Usage (mirrors the reference CLI):
    python -m paddle_tpu.distributed.launch \
        --nproc_per_node 4 --log_dir log train.py --arg1 ...

Reference behavior replicated (launch/main.py, controllers/collective.py,
fleet/elastic/manager.py:125):
  - per-rank env: PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM,
    PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINER_ENDPOINTS, PADDLE_MASTER,
    PADDLE_LOCAL_RANK, PADDLE_NNODES
  - per-rank log files under --log_dir (rank 0 tees to stdout)
  - on worker failure: kill the peer group and, while --max_restart isn't
    exhausted (elastic level >= 1), relaunch the whole job
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch a collective job (reference launch/main.py)")
    p.add_argument("--master", default=None,
                   help="master endpoint ip:port (default: local auto)")
    p.add_argument("--host", default=None,
                   help="routable address this node advertises to peers "
                        "(default: auto-detected from the route to "
                        "--master; loopback single-node)")
    p.add_argument("--rank", type=int, default=0, help="node rank")
    p.add_argument("--nnodes", default="1",
                   help="node count, or elastic range 'lo:hi'")
    p.add_argument("--nproc_per_node", type=int, default=None)
    p.add_argument("--log_dir", default="log")
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--job_id", default="default")
    p.add_argument("--devices", "--gpus", default=None,
                   help="device ids for this node")
    p.add_argument("--ips", default=None, help="legacy node ip list")
    p.add_argument("--elastic_level", type=int, default=-1)
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--auto_tuner_json", default=None,
                   help="hybrid-parallel auto-tuner config (reference "
                        "launch --auto_tuner_json): search+score candidate "
                        "configs before launching; best config is exported "
                        "to workers as PADDLE_AUTO_TUNER_BEST")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _worker_env(local_rank, global_rank, world, endpoints, master, nnodes,
                node_rank, device_ids=None):
    env = dict(os.environ)
    dev = device_ids[local_rank] if device_ids else str(local_rank)
    env.update({
        "PADDLE_TRAINER_ID": str(global_rank),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_CURRENT_ENDPOINT": endpoints[global_rank],
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_MASTER": master,
        "PADDLE_NNODES": str(nnodes),
        "PADDLE_NODE_RANK": str(node_rank),
        "FLAGS_selected_tpus": dev,
    })
    return env


def _advertise_host(args):
    """The address peers can reach this node's workers on: --host, else the
    local address of the route to --master, else loopback."""
    if args.host:
        return args.host
    mhost = args.master.split(":")[0]
    if mhost in ("127.0.0.1", "localhost"):
        return "127.0.0.1"
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect((mhost, 1))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def _open_rendezvous_store(args, node_rank):
    """One TCPStore for the whole job (node 0 hosts it); reused across
    elastic restart generations."""
    from ..store import TCPStore

    host, port = args.master.split(":")
    return TCPStore(host, int(port), is_master=(node_rank == 0),
                    timeout=120.0)


def _rendezvous_endpoints(store, gen, n_min, node_rank, adv_host,
                          local_ports):
    """Multi-node rendezvous (reference launch/controllers/master.py
    ETCDMaster/HTTPMaster role): every node registers its worker endpoints
    under the current restart generation; returns the global ordered
    endpoint list."""
    mine = ",".join(f"{adv_host}:{p}" for p in local_ports)
    store.set(f"g{gen}/node/{node_rank}/endpoints", mine.encode())
    eps = []
    for n in range(n_min):
        store.wait([f"g{gen}/node/{n}/endpoints"], timeout=120.0)
        val = store.get(f"g{gen}/node/{n}/endpoints")
        eps.extend(val.decode().split(","))
    return eps


def _spawn(args, nprocs, store=None, gen=0):
    os.makedirs(args.log_dir, exist_ok=True)
    ports = [_free_port() for _ in range(nprocs)]
    device_ids = ([d.strip() for d in args.devices.split(",")]
                  if args.devices else None)
    nnodes = int(str(args.nnodes).split(":")[0])
    node_rank = args.rank
    if nnodes > 1:
        endpoints = _rendezvous_endpoints(store, gen, nnodes, node_rank,
                                          _advertise_host(args), ports)
        master = args.master
        world = nnodes * nprocs
    else:
        endpoints = [f"127.0.0.1:{p}" for p in ports]
        master = args.master or f"127.0.0.1:{ports[0]}"
        world = nprocs
    procs = []
    logs = []
    for rank in range(nprocs):
        grank = node_rank * nprocs + rank
        env = _worker_env(rank, grank, world, endpoints, master,
                          nnodes, node_rank, device_ids)
        cmd = [sys.executable, "-u", args.training_script,
               *args.training_script_args]
        logf = open(os.path.join(args.log_dir,
                                 f"workerlog.{rank}"), "ab", buffering=0)
        logs.append(logf)
        procs.append(subprocess.Popen(
            cmd, env=env, stdout=logf, stderr=subprocess.STDOUT))
    return procs, logs


def _kill_all(procs):
    for q in procs:
        if q.poll() is None:
            q.send_signal(signal.SIGTERM)
    deadline = time.time() + 10
    for q in procs:
        try:
            q.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            q.kill()


PEER_ABORT = 250


def _store_has(store, key):
    try:
        store.wait([key], timeout=0.05)
        return True
    except Exception:
        return False


def _wait(procs, store=None, gen=0):
    """Wait for all workers; on any nonzero exit, kill the rest and return
    that code.  Returns 0 when every worker succeeds.

    Multi-node (store given): a failing node broadcasts an abort key for
    this restart generation so EVERY node's launcher tears down and
    re-enters rendezvous together (cross-node restart coordination —
    reference fleet/elastic/manager.py watch loop)."""
    last_peer_check = 0.0
    while True:
        alive = False
        for p in procs:
            rc = p.poll()
            if rc is None:
                alive = True
            elif rc != 0:
                if store is not None:
                    try:
                        store.set(f"g{gen}/abort", b"1")
                    except Exception:
                        pass
                _kill_all(procs)
                return rc
        if store is not None and time.time() - last_peer_check > 1.0:
            last_peer_check = time.time()
            if _store_has(store, f"g{gen}/abort"):
                _kill_all(procs)
                return PEER_ABORT
        if not alive:
            return 0
        time.sleep(0.2)


def _run_auto_tuner(args) -> dict | None:
    """Search+score hybrid configs before launching (reference
    launch/main.py auto-tuner mode, which runs a trial JOB per candidate;
    here candidates are scored by AOT compile probes — tuner.py
    measure_cfg — so tuning happens in-process in seconds)."""
    import json

    # honor the caller's platform pin BEFORE any backend init: environment
    # sitecustomize may re-pin JAX_PLATFORMS to a hardware plugin whose
    # init can hang when the device service is unreachable (the
    # tests/conftest.py pattern — env var alone is not enough)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass

    from ..auto_tuner import AutoTuner

    with open(args.auto_tuner_json) as f:
        tuner_cfg = json.load(f)
    max_trials = int(tuner_cfg.pop("max_trials", 8))
    tuner = AutoTuner(tuner_cfg)
    os.makedirs(args.log_dir, exist_ok=True)
    hist = os.path.join(args.log_dir, "auto_tuner_history.csv")
    best, err = tuner.tune(max_trials=max_trials, history_path=hist)
    if err or best is None:
        print(f"[launch] auto-tuner: no feasible config found "
              f"(history: {hist})", file=sys.stderr)
        return None
    best = {k: v for k, v in best.items() if not k.startswith("_")}
    print(f"[launch] auto-tuner best config: {best} (history: {hist})",
          file=sys.stderr)
    return best


def launch(argv=None) -> int:
    args = _parse_args(argv)
    if args.auto_tuner_json:
        import json
        best = _run_auto_tuner(args)
        if best is not None:
            os.environ["PADDLE_AUTO_TUNER_BEST"] = json.dumps(best)
    nprocs = args.nproc_per_node
    if nprocs is None:
        devs = args.devices
        nprocs = len(devs.split(",")) if devs else 1
    elastic = args.elastic_level >= 1 or ":" in str(args.nnodes)
    nnodes = int(str(args.nnodes).split(":")[0])
    store = None
    if nnodes > 1:
        if not args.master:
            raise SystemExit("--master ip:port is required for nnodes > 1")
        if args.rank >= nnodes:
            raise SystemExit(
                f"--rank {args.rank} >= nnodes minimum {nnodes}: standby "
                "nodes beyond the minimum world are not part of the static "
                "rendezvous; start them after a membership change")
        store = _open_rendezvous_store(args, args.rank)
    restarts = 0
    gen = 0
    while True:
        procs, logs = _spawn(args, nprocs, store, gen)
        rc = _wait(procs, store, gen)
        for f in logs:
            f.close()
        if rc == 0:
            # multi-node: success only when EVERY node finished this
            # generation (a peer may still abort and force a joint restart)
            if store is not None:
                try:
                    store.add(f"g{gen}/done", 1)
                    while True:
                        done = int(store.add(f"g{gen}/done", 0))
                        if done >= nnodes:
                            break
                        if _store_has(store, f"g{gen}/abort"):
                            rc = PEER_ABORT
                            break
                        time.sleep(0.5)
                except Exception:
                    # store master (node 0) gone: it only exits cleanly
                    # after all dones, or non-zero after broadcasting an
                    # abort we would have seen — treat closure as success
                    pass
                if rc == 0 and args.rank == 0:
                    time.sleep(1.0)   # grace: let peers read the final state
            if rc == 0:
                return 0
        if elastic and restarts < args.max_restart:
            restarts += 1
            gen += 1
            print(f"[launch] workers failed (exit {rc}); restart "
                  f"{restarts}/{args.max_restart}", file=sys.stderr)
            continue
        return rc


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
