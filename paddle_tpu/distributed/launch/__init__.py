"""paddle.distributed.launch equivalent.

Parity with /root/reference/python/paddle/distributed/launch/main.py:23
(collective controller + elastic restarts), TPU-shaped: the env contract
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_CURRENT_ENDPOINT /
PADDLE_TRAINER_ENDPOINTS / PADDLE_MASTER) is preserved so fleet code reads
ranks identically, and the same variables seed jax.distributed
(coordinator address/process id) instead of NCCL rendezvous.
"""
from .main import launch, main  # noqa: F401

__all__ = ["launch", "main"]
