"""Parallel environment + DataParallel.

Parity with /root/reference/python/paddle/distributed/parallel.py
(init_parallel_env :978, DataParallel :219).

TPU-native: rendezvous is jax.distributed (replacing TCPStore); the "world"
is the set of JAX processes x their local devices.  In the common
single-controller case (one process driving all chips) world_size is the
process count (1) and data parallelism is expressed through sharded meshes,
matching how the reference's fleet maps onto GSPMD here.
"""
from __future__ import annotations

import os

import jax

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["ParallelEnv", "init_parallel_env", "get_rank", "get_world_size",
           "is_initialized", "DataParallel", "spawn"]

_initialized = False


class ParallelEnv:
    """Reads the launch env contract (PADDLE_TRAINER_ID & friends), falling
    back to JAX process topology."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID",
                                        os.environ.get("RANK", jax.process_index())))
        self._world_size = int(os.environ.get(
            "PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", jax.process_count())))
        self._device_id = int(os.environ.get("FLAGS_selected_tpus",
                                             os.environ.get("LOCAL_RANK", 0)))

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def dev_id(self):
        return self._device_id

    local_rank = rank
    nranks = world_size

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else [self.current_endpoint]


def init_parallel_env():
    """Bring up the distributed runtime.

    Multi-host: initialize jax.distributed from the launch env (coordinator =
    rank-0 endpoint) so all hosts join one global XLA world — the analog of
    ProcessGroupNCCL's TCPStore uid exchange + ncclCommInitRank
    (/root/reference/paddle/fluid/distributed/collective/process_group_nccl.cc:732).
    """
    global _initialized
    if _initialized:
        return
    env = ParallelEnv()
    if env.world_size > 1 and jax.process_count() == 1:
        coordinator = os.environ.get("PADDLE_MASTER",
                                     env.trainer_endpoints[0])
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=env.world_size,
                process_id=env.rank)
        except Exception as e:  # already initialized or single-host testing
            import logging
            logging.getLogger(__name__).warning(
                "jax.distributed.initialize failed (%s); continuing "
                "single-host", e)
    _initialized = True
    from .collective import _world_group
    _world_group()
    return ParallelEnv()


def is_initialized():
    return _initialized


def get_rank(group=None):
    if group is not None:
        return group.rank
    return ParallelEnv().rank


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return ParallelEnv().world_size


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Reference-parity process spawner.  On TPU the single-controller model
    drives all chips from one process, so spawn simply runs func for the
    1-process case and defers multi-host to `paddle_tpu.distributed.launch`."""
    if nprocs in (-1, 0, 1):
        func(*args)
        return None
    raise NotImplementedError(
        "multi-process spawn: use python -m paddle_tpu.distributed.launch "
        "(one process per host) — single-controller JAX drives all local "
        "chips from one process")


class DataParallel(Layer):
    """Eager data-parallel wrapper (reference: parallel.py:219 + EagerReducer).

    Under the single-controller TPU model, cross-chip gradient averaging is
    performed by the compiled train step over the 'dp' mesh axis; this wrapper
    exists for API parity and multi-host eager mode, where it registers
    grad hooks that all-reduce over the world group.
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.find_unused_parameters = find_unused_parameters
        world = get_world_size(group)
        if world > 1:
            from .collective import ReduceOp, all_reduce

            def make_hook(p):
                def hook(grad):
                    out = all_reduce(grad, ReduceOp.SUM, self.group)
                    from ..ops.math import scale
                    return scale(out, 1.0 / world)
                return hook
            for p in layers.parameters():
                if not p.stop_gradient:
                    p.register_hook(make_hook(p))

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def no_sync(self):
        import contextlib
        return contextlib.nullcontext()
