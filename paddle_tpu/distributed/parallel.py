"""Parallel environment + DataParallel.

Parity with /root/reference/python/paddle/distributed/parallel.py
(init_parallel_env :978, DataParallel :219).

TPU-native: rendezvous is jax.distributed (replacing TCPStore); the "world"
is the set of JAX processes x their local devices.  In the common
single-controller case (one process driving all chips) world_size is the
process count (1) and data parallelism is expressed through sharded meshes,
matching how the reference's fleet maps onto GSPMD here.
"""
from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["ParallelEnv", "init_parallel_env", "get_rank", "get_world_size",
           "is_initialized", "DataParallel", "spawn"]

_initialized = False


class ParallelEnv:
    """Reads the launch env contract (PADDLE_TRAINER_ID & friends), falling
    back to JAX process topology."""

    def __init__(self):
        # env first; jax.process_index()/count() only as a LAST resort —
        # touching them initializes the XLA backend, which must not happen
        # before jax.distributed.initialize() in multi-process mode
        r = os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK"))
        w = os.environ.get("PADDLE_TRAINERS_NUM",
                           os.environ.get("WORLD_SIZE"))
        self._rank = int(r) if r is not None else jax.process_index()
        self._world_size = int(w) if w is not None else jax.process_count()
        self._device_id = int(os.environ.get("FLAGS_selected_tpus",
                                             os.environ.get("LOCAL_RANK", 0)))

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def dev_id(self):
        return self._device_id

    local_rank = rank
    nranks = world_size

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else [self.current_endpoint]


def init_parallel_env():
    """Bring up the distributed runtime.

    Multi-host: initialize jax.distributed from the launch env (coordinator =
    rank-0 endpoint) so all hosts join one global XLA world — the analog of
    ProcessGroupNCCL's TCPStore uid exchange + ncclCommInitRank
    (/root/reference/paddle/fluid/distributed/collective/process_group_nccl.cc:732).
    """
    global _initialized
    if _initialized:
        return
    env = ParallelEnv()
    # NB: do NOT call jax.process_count() here — it would initialize the
    # XLA backend and make jax.distributed.initialize impossible
    already_multi = jax.distributed.is_initialized() \
        if hasattr(jax.distributed, "is_initialized") else False
    if env.world_size > 1 and not already_multi:
        # Coordinator priority: explicit override; PADDLE_MASTER host at
        # port+1 (the master port itself is bound by the launch KV store,
        # and only PADDLE_MASTER is shared across nodes); single-node
        # fallback: rank-0's trainer endpoint.
        coordinator = os.environ.get("PADDLE_TPU_COORDINATOR")
        if coordinator is None:
            master = os.environ.get("PADDLE_MASTER")
            if master and ":" in master:
                host, port = master.rsplit(":", 1)
                coordinator = f"{host}:{int(port) + 1}"
            else:
                coordinator = env.trainer_endpoints[0]
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=env.world_size,
                process_id=env.rank)
        except Exception as e:  # already initialized or single-host testing
            import logging
            logging.getLogger(__name__).warning(
                "jax.distributed.initialize failed (%s); continuing "
                "single-host", e)
    _initialized = True
    from .collective import _world_group
    _world_group()
    return ParallelEnv()


def is_initialized():
    return _initialized


def get_rank(group=None):
    if group is not None:
        return group.rank
    return ParallelEnv().rank


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return ParallelEnv().world_size


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Reference-parity process spawner.  On TPU the single-controller model
    drives all chips from one process, so spawn simply runs func for the
    1-process case and defers multi-host to `paddle_tpu.distributed.launch`."""
    if nprocs in (-1, 0, 1):
        func(*args)
        return None
    raise NotImplementedError(
        "multi-process spawn: use python -m paddle_tpu.distributed.launch "
        "(one process per host) — single-controller JAX drives all local "
        "chips from one process")


class _BucketReducer:
    """EagerReducer analog (reference reducer.h:88): group parameters into
    ~comm_buffer_size-MB buckets in reverse creation order (the order grads
    become ready in backward); when every grad of a bucket has arrived,
    flatten-concat them and launch ONE fused all-reduce.  JAX dispatch is
    async, so the fused program for bucket k overlaps with the backward
    compute producing bucket k+1 — the same overlap the reference gets from
    comm streams."""

    def __init__(self, params, group, world, bucket_mb=25, last_bucket_mb=1):
        self.group = group
        self.world = world
        self.enabled = True
        self.buckets = []           # list[list[Parameter]]
        self._bucket_of = {}        # id(param) -> bucket index
        cap_last = last_bucket_mb * (1 << 20)
        cap = bucket_mb * (1 << 20)
        cur, cur_bytes, limit = [], 0, cap_last  # first (=last-ready) small
        for p in reversed(list(params)):
            nbytes = int(np.prod(p.shape)) * p.dtype.itemsize
            if cur and cur_bytes + nbytes > limit:
                self.buckets.append(cur)
                cur, cur_bytes, limit = [], 0, cap
            cur.append(p)
            cur_bytes += nbytes
        if cur:
            self.buckets.append(cur)
        for bi, bucket in enumerate(self.buckets):
            for p in bucket:
                self._bucket_of[id(p)] = bi
        self._pending = [dict() for _ in self.buckets]
        self._serial = -1
        # finalize unused-parameter buckets when backward completes (the
        # reference's backward-done reducer finalization, reducer.h:88)
        from ..core import tape as _tape
        self._remove_cb = _tape.register_post_backward_callback(
            self._on_backward_done)

    def _sync_serial(self):
        from ..core import tape as _tape
        s = _tape.backward_serial()
        if s != self._serial:
            # a new backward: stale pending grads from a backward that never
            # completed its buckets must not leak into this one
            self._pending = [dict() for _ in self.buckets]
            self._serial = s

    def on_grad(self, p, grad_arr):
        """Called from the param's leaf hook — which the tape fires ONCE per
        backward with the final accumulated grad (shared/tied params
        included).  Returns the array the hook should hand back (the fused
        reduced slice when this grad completes its bucket, the raw grad
        otherwise)."""
        if not self.enabled or self.world <= 1:
            return grad_arr
        self._sync_serial()
        bi = self._bucket_of[id(p)]
        pend = self._pending[bi]
        pend[id(p)] = grad_arr
        bucket = self.buckets[bi]
        if len(pend) < len(bucket):
            return grad_arr
        return self._flush(bi, ret_for=id(p))

    def _flush(self, bi, ret_for=None):
        from . import eager_comm
        bucket = self.buckets[bi]
        pend = self._pending[bi]
        flat = jnp.concatenate(
            [jnp.ravel(pend[id(p)].astype(jnp.float32)) for p in bucket])
        g = self.group
        ranks = tuple(g.ranks) if g is not None else tuple(range(self.world))
        reduced = eager_comm.all_reduce(flat, ranks, op=4)  # AVG
        ret = None
        off = 0
        for p in bucket:
            n = int(np.prod(p.shape))
            raw = pend[id(p)]
            piece = reduced[off:off + n].reshape(tuple(p.shape)) \
                .astype(raw.dtype)
            off += n
            if id(p) == ret_for:
                ret = piece   # tape accumulates it into p.grad
            elif p._grad is not None:
                # p.grad already holds prior-accumulation + this backward's
                # raw grad; swap raw for reduced WITHOUT touching earlier
                # accumulated steps
                p._grad._data = p._grad._data + (piece - raw).astype(
                    p._grad._data.dtype)
            else:
                p._grad = Tensor(piece, stop_gradient=True)
        self._pending[bi] = {}
        return ret

    def _on_backward_done(self):
        from ..core import tape as _tape
        if not self.enabled or self.world <= 1:
            return
        if self._serial != _tape.backward_serial():
            return  # this backward produced no grads for our params
        if any(self._pending[bi] for bi in range(len(self.buckets))):
            self.flush_incomplete()

    def flush_incomplete(self):
        """Reduce buckets whose params produced no grad this backward
        (unused parameters contribute zeros — every rank must still enter
        the collective)."""
        for bi, bucket in enumerate(self.buckets):
            pend = self._pending[bi]
            if not pend:
                continue
            for p in bucket:
                if id(p) not in pend:
                    pend[id(p)] = jnp.zeros(tuple(p.shape),
                                            jnp.dtype(p.dtype.np_dtype))
            self._flush(bi)


class DataParallel(Layer):
    """Eager data-parallel wrapper (reference: parallel.py:219 + EagerReducer
    reducer.h:88).

    Under the single-controller TPU model, cross-chip gradient averaging is
    performed by the compiled train step over the 'dp' mesh axis.  In
    multi-process eager mode (init_parallel_env under distributed.launch)
    grad hooks feed a bucketed reducer that launches fused all-reduces over
    the world group, overlapping with backward.
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.find_unused_parameters = find_unused_parameters
        world = get_world_size(group)
        self._reducer = None
        if world > 1:
            params = [p for p in layers.parameters() if not p.stop_gradient]
            self._reducer = _BucketReducer(params, group, world,
                                           comm_buffer_size,
                                           last_comm_buffer_size)

            def make_hook(p):
                def hook(grad):
                    out = self._reducer.on_grad(p, grad._data)
                    return Tensor(out) if out is not None else grad
                return hook
            for p in params:
                p.register_hook(make_hook(p))

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        if self._reducer is not None:
            self._reducer.flush_incomplete()

    def no_sync(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            if self._reducer is None:
                yield
                return
            self._reducer.enabled = False
            try:
                yield
            finally:
                self._reducer.enabled = True
        return ctx()
