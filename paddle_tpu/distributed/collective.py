"""Communication API: groups + collectives.

Parity with /root/reference/python/paddle/distributed/communication/ and the
ProcessGroup abstraction (/root/reference/paddle/phi/core/distributed/collective/
process_group.h:48).

TPU-native design (SURVEY.md §5.8): there is no NCCL — collectives are XLA
ops.  Inside a captured region (shard_map/pjit over a Mesh) these functions
lower to lax.psum/all_gather/ppermute over the group's mesh axis.  In eager
single-controller mode, a "group" is a set of devices of the current process
mesh; eager collectives execute as tiny compiled XLA programs over the
participating shards (world_size==1 degenerates to identity, matching the
reference's fast-path).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ReduceOp", "Group", "new_group", "get_group", "is_available",
           "all_reduce", "all_gather", "all_gather_object", "broadcast",
           "reduce", "scatter", "alltoall", "all_to_all", "send", "recv",
           "barrier", "reduce_scatter", "destroy_process_group", "irecv",
           "isend", "batch_isend_irecv", "P2POp", "get_backend",
           "gather", "stream"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communication group: an ordered set of global ranks, optionally bound
    to a mesh axis name (used when lowering collectives under shard_map)."""

    _next_id = 0

    def __init__(self, ranks, axis_name=None, pg_id=None):
        self.ranks = list(ranks)
        self.nranks = len(self.ranks)
        self.axis_name = axis_name
        if pg_id is None:
            Group._next_id += 1
            pg_id = Group._next_id
        self.id = pg_id

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def rank(self):
        from .parallel import get_rank
        return self.get_group_rank(get_rank())

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks}, axis={self.axis_name})"

    process_group = property(lambda self: self)


_groups: dict[int, Group] = {}
_default_group: Group | None = None


def _world_group() -> Group:
    global _default_group
    if _default_group is None:
        from .parallel import get_world_size
        _default_group = Group(list(range(get_world_size())), axis_name=None,
                               pg_id=0)
        _groups[0] = _default_group
    return _default_group


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    if ranks is None:
        from .parallel import get_world_size
        ranks = list(range(get_world_size()))
    g = Group(ranks, axis_name=axis_name)
    _groups[g.id] = g
    return g


def get_group(gid=0):
    if gid == 0:
        return _world_group()
    return _groups.get(gid)


def get_backend(group=None):
    return "xla"


def is_available():
    return True


def destroy_process_group(group=None):
    global _default_group
    if group is None:
        _groups.clear()
        _default_group = None
    else:
        _groups.pop(group.id, None)


def _in_trace(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _axis(group):
    g = group or _world_group()
    return g.axis_name


def _maybe_tensor(x):
    return x._data if isinstance(x, Tensor) else x


def _wrap_inplace(tensor, arr):
    if isinstance(tensor, Tensor):
        tensor._data = arr
        return tensor
    return Tensor(arr)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """AllReduce.  Under shard_map: psum/pmax/... over the group axis.
    Eager 1-rank: identity."""
    arr = _maybe_tensor(tensor)
    axis = _axis(group)
    if _in_trace(arr) and axis is not None:
        if op == ReduceOp.SUM:
            out = jax.lax.psum(arr, axis)
        elif op == ReduceOp.MAX:
            out = jax.lax.pmax(arr, axis)
        elif op == ReduceOp.MIN:
            out = jax.lax.pmin(arr, axis)
        elif op == ReduceOp.AVG:
            out = jax.lax.pmean(arr, axis)
        else:
            raise ValueError(f"unsupported op {op} under capture")
        return _wrap_inplace(tensor, out)
    g = group or _world_group()
    if g.nranks <= 1:
        return tensor
    from . import eager_comm
    if eager_comm.available():
        out = eager_comm.all_reduce(arr, tuple(g.ranks), int(op))
        return _wrap_inplace(tensor, out)
    raise RuntimeError(
        "eager cross-device all_reduce requires a multi-process runtime "
        "(init_parallel_env under distributed.launch) or captured mode")


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    arr = _maybe_tensor(tensor)
    ax = _axis(group)
    if _in_trace(arr) and ax is not None:
        out = jax.lax.all_gather(arr, ax)
        if isinstance(tensor_list, list):
            g = group or _world_group()
            for i in range(g.nranks):
                tensor_list.append(Tensor(out[i]))
            return tensor_list
        return Tensor(out)
    g = group or _world_group()
    if g.nranks <= 1:
        if isinstance(tensor_list, list):
            tensor_list.append(tensor)
            return tensor_list
        return tensor
    from . import eager_comm
    if eager_comm.available():
        out = eager_comm.all_gather(arr, tuple(g.ranks))
        if isinstance(tensor_list, list):
            for i in range(g.nranks):
                tensor_list.append(Tensor(out[i]))
            return tensor_list
        return Tensor(out)
    raise RuntimeError("eager cross-device all_gather requires a "
                       "multi-process runtime or captured mode")


def all_gather_object(object_list, obj, group=None):
    g = group or _world_group()
    if g.nranks <= 1:
        object_list.append(obj)
        return object_list
    from . import eager_comm
    if eager_comm.available():
        import pickle
        payload = np.frombuffer(pickle.dumps(obj), np.uint8)
        sizes = eager_comm.all_gather(
            jnp.asarray([payload.size], jnp.int32), tuple(g.ranks))
        cap = int(np.asarray(sizes).max())
        buf = np.zeros((cap,), np.uint8)
        buf[:payload.size] = payload
        got = np.asarray(eager_comm.all_gather(jnp.asarray(buf),
                                               tuple(g.ranks)))
        for i in range(g.nranks):
            n = int(np.asarray(sizes)[i, 0])
            object_list.append(pickle.loads(got[i, :n].tobytes()))
        return object_list
    raise RuntimeError("all_gather_object requires multi-host runtime")


def broadcast(tensor, src=0, group=None, sync_op=True):
    g = group or _world_group()
    arr = _maybe_tensor(tensor)
    ax = _axis(group)
    if _in_trace(arr) and ax is not None:
        # broadcast = select src's shard on every member
        idx = g.get_group_rank(src)
        out = jax.lax.all_gather(arr, ax)[idx]
        return _wrap_inplace(tensor, out)
    if g.nranks <= 1:
        return tensor
    from . import eager_comm
    if eager_comm.available():
        src_idx = g.get_group_rank(src)
        if src_idx < 0:
            raise ValueError(f"src rank {src} is not in group {g.ranks}")
        out = eager_comm.broadcast(arr, tuple(g.ranks), src_idx)
        return _wrap_inplace(tensor, out)
    raise RuntimeError("eager cross-device broadcast requires a "
                       "multi-process runtime or captured mode")


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # single-controller: reduce == all_reduce (every member sees the result;
    # only dst's value is defined by the reference API)
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = group or _world_group()
    if g.nranks <= 1:
        if tensor_list:
            tensor.set_value(tensor_list[0])
        return tensor
    arr = _maybe_tensor(tensor)
    ax = _axis(group)
    if _in_trace(arr) and ax is not None and tensor_list is not None:
        stacked = jnp.stack([_maybe_tensor(t) for t in tensor_list])
        idx = jax.lax.axis_index(ax)
        return _wrap_inplace(tensor, stacked[idx])
    from . import eager_comm
    if eager_comm.available():
        # scatter = alltoall taking only src's slots: every rank contributes
        # its (stacked) list — non-src ranks pass zeros — then broadcasts
        # src's row and picks its own slot
        me = g.get_group_rank(_my_rank())
        src_idx = g.get_group_rank(src)
        if me < 0 or src_idx < 0:
            raise ValueError(
                f"scatter: rank {_my_rank()} / src {src} must both be in "
                f"group {g.ranks}")
        if tensor_list is not None:
            stack = jnp.stack([jnp.asarray(_maybe_tensor(t))
                               for t in tensor_list])
        else:
            stack = jnp.stack([jnp.zeros_like(arr)] * g.nranks)
        row = eager_comm.broadcast(stack, tuple(g.ranks), src_idx)
        return _wrap_inplace(tensor, row[me])
    raise RuntimeError("eager cross-device scatter requires a "
                       "multi-process runtime or captured mode")


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    g = group or _world_group()
    ax = _axis(group)
    arrs = [_maybe_tensor(t) for t in (tensor_list or [])]
    if arrs and _in_trace(arrs[0]) and ax is not None:
        stacked = jnp.stack(arrs)
        summed = jax.lax.psum(stacked, ax)
        idx = jax.lax.axis_index(ax)
        return _wrap_inplace(tensor, summed[idx])
    if g.nranks <= 1:
        if tensor_list:
            tensor.set_value(tensor_list[0])
        return tensor
    from . import eager_comm
    if eager_comm.available():
        stack = jnp.stack([jnp.asarray(a) for a in arrs])
        out = eager_comm.reduce_scatter(stack, tuple(g.ranks), int(op))
        return _wrap_inplace(tensor, out)
    raise RuntimeError("eager cross-device reduce_scatter requires a "
                       "multi-process runtime or captured mode")


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    g = group or _world_group()
    ax = _axis(group)
    arrs = [_maybe_tensor(t) for t in in_tensor_list]
    if arrs and _in_trace(arrs[0]) and ax is not None:
        stacked = jnp.stack(arrs)  # [n, ...] destination-major
        out = jax.lax.all_to_all(stacked, ax, split_axis=0, concat_axis=0,
                                 tiled=False)
        for i in range(g.nranks):
            out_tensor_list.append(Tensor(out[i]))
        return out_tensor_list
    if g.nranks <= 1:
        out_tensor_list.extend(in_tensor_list)
        return out_tensor_list
    from . import eager_comm
    if eager_comm.available():
        stack = jnp.stack([jnp.asarray(a) for a in arrs])
        out = eager_comm.all_to_all(stack, tuple(g.ranks))
        for i in range(g.nranks):
            out_tensor_list.append(Tensor(out[i]))
        return out_tensor_list
    raise RuntimeError("eager cross-device alltoall requires a "
                       "multi-process runtime or captured mode")


all_to_all = alltoall


def send(tensor, dst=0, group=None, sync_op=True):
    ax = _axis(group)
    arr = _maybe_tensor(tensor)
    if _in_trace(arr) and ax is not None:
        g = group or _world_group()
        # point-to-point on TPU = collective_permute on the ring
        me = jax.lax.axis_index(ax)
        perm = [(g.get_group_rank(jax.process_index()), g.get_group_rank(dst))]
        return Tensor(jax.lax.ppermute(arr, ax, perm))
    g = group or _world_group()
    if g.nranks <= 1:
        _p2p_buffer.append(np.asarray(arr))
        return tensor
    from . import eager_comm
    if eager_comm.available():
        # 2-sided p2p: src and dst both enter the pair program (NCCL-style);
        # the pair group is (me, dst)
        me = _my_rank()
        eager_comm.p2p(arr, (min(me, dst), max(me, dst)),
                       src_index=(0 if me < dst else 1),
                       dst_index=(1 if me < dst else 0))
        return tensor
    raise RuntimeError("eager cross-device send requires a multi-process "
                       "runtime or captured mode")


_p2p_buffer: list = []


def recv(tensor, src=0, group=None, sync_op=True):
    g = group or _world_group()
    if g.nranks <= 1:
        if _p2p_buffer:
            tensor.set_value(_p2p_buffer.pop(0))
        return tensor
    from . import eager_comm
    if eager_comm.available():
        me = _my_rank()
        arr = _maybe_tensor(tensor)
        out = eager_comm.p2p(arr, (min(me, src), max(me, src)),
                             src_index=(0 if src < me else 1),
                             dst_index=(0 if me < src else 1))
        return _wrap_inplace(tensor, out)
    raise RuntimeError("eager cross-device recv requires a multi-process "
                       "runtime or captured mode")


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def isend(tensor, dst, group=None):
    return send(tensor, dst, group)


def irecv(tensor, src=None, group=None):
    return recv(tensor, src or 0, group)


def batch_isend_irecv(p2p_op_list):
    tasks = []
    for op in p2p_op_list:
        tasks.append(op.op(op.tensor, op.peer, op.group))
    return tasks


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    return all_gather(gather_list if gather_list is not None else [], tensor, group)


def _my_rank() -> int:
    from .parallel import get_rank
    return get_rank()


def barrier(group=None):
    g = group or _world_group()
    from . import eager_comm
    if g.nranks > 1 and eager_comm.available():
        eager_comm.barrier(tuple(g.ranks))
        return
    jnp.zeros(()).block_until_ready()


class stream:
    """paddle.distributed.stream namespace shim (sync_op/use_calc_stream knobs
    are no-ops under XLA's ordered async dispatch)."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)
    scatter = staticmethod(scatter)
    alltoall = staticmethod(alltoall)
    reduce_scatter = staticmethod(reduce_scatter)
    send = staticmethod(send)
    recv = staticmethod(recv)
