"""Distributed RPC (reference python/paddle/distributed/rpc/rpc.py:
init_rpc/rpc_sync/rpc_async/shutdown/get_worker_info over a brpc agent,
paddle/fluid/distributed/rpc/ C++).

TPU-native design: the control plane stays host-side.  Rendezvous rides the
native TCPStore (csrc/tcp_store.cc — the same store the collective layer
uses); each worker runs a threaded socket server executing pickled callables;
``rpc_async`` returns a ``concurrent.futures.Future`` (the reference returns
a bound C++ future with the same ``wait()`` contract).  No brpc, no protobuf:
length-prefixed pickle frames between cooperating trainer processes.

Trust model is the reference's: RPC peers are the job's own trainers
(deserializing a frame executes arbitrary code, exactly like the reference's
pickled python UDFs) — never expose the port beyond the training cluster.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
from collections import namedtuple
from concurrent.futures import ThreadPoolExecutor

from ..store import TCPStore, barrier_via_store

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos",
           "get_current_worker_info", "WorkerInfo"]

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_state = threading.local()          # not process-global: tests reinit freely


class _Agent:
    def __init__(self, self_info, infos, store, world_size):
        self.self_info = self_info
        self.infos = infos           # name -> WorkerInfo
        self.store = store
        self.world_size = world_size
        self.server = None
        self.pool = ThreadPoolExecutor(max_workers=8,
                                       thread_name_prefix="rpc-client")
        self.stop = threading.Event()


_agent: _Agent | None = None


def _recv_exact(conn, n):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return buf


def _send_frame(conn, obj):
    data = pickle.dumps(obj, protocol=4)
    conn.sendall(struct.pack("<Q", len(data)) + data)


def _recv_frame(conn):
    (n,) = struct.unpack("<Q", _recv_exact(conn, 8))
    return pickle.loads(_recv_exact(conn, n))


def _serve(agent: _Agent, sock: socket.socket):
    exec_pool = ThreadPoolExecutor(max_workers=8,
                                   thread_name_prefix="rpc-server")

    def handle(conn):
        try:
            with conn:
                fn, args, kwargs = _recv_frame(conn)
                try:
                    _send_frame(conn, ("ok", fn(*args, **kwargs)))
                except Exception as e:       # ship the failure to the caller
                    _send_frame(conn, ("err", e))
        except Exception:
            pass                             # peer went away mid-call

    sock.settimeout(0.2)
    while not agent.stop.is_set():
        try:
            conn, _ = sock.accept()
        except socket.timeout:
            continue
        except OSError:
            break
        exec_pool.submit(handle, conn)
    exec_pool.shutdown(wait=False)


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this worker's RPC agent and rendezvous with the others
    (reference rpc.py:85)."""
    global _agent
    if _agent is not None:
        raise RuntimeError("RPC already initialized; call shutdown() first")
    rank = int(os.environ["PADDLE_TRAINER_ID"]) if rank is None else rank
    world_size = int(os.environ["PADDLE_TRAINERS_NUM"]) \
        if world_size is None else world_size
    master_endpoint = master_endpoint or \
        os.environ.get("PADDLE_MASTER_ENDPOINT") or \
        os.environ.get("PADDLE_MASTER", "127.0.0.1:0")
    host, port = master_endpoint.rsplit(":", 1)
    store = TCPStore(host, int(port), is_master=(rank == 0),
                     timeout=float(os.environ.get("FLAGS_stop_check_timeout",
                                                  "900")))

    # bind the service socket on all interfaces, advertise a ROUTABLE
    # address (multi-host peers must be able to dial it — reference
    # rpc.py:85 uses PADDLE_WORKER_ENDPOINT): prefer the launch env's
    # endpoint host, else this host's resolved address.
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("0.0.0.0", 0))
    srv.listen(128)
    my_port = srv.getsockname()[1]
    ip = os.environ.get("PADDLE_CURRENT_ENDPOINT", "").rsplit(":", 1)[0]
    if not ip:
        try:
            ip = socket.gethostbyname(socket.gethostname())
        except OSError:
            ip = "127.0.0.1"

    self_info = WorkerInfo(name, rank, ip, my_port)
    store.set(f"rpc/worker/{rank}",
              pickle.dumps((name, rank, ip, my_port), protocol=4))
    store.wait([f"rpc/worker/{r}" for r in range(world_size)])
    infos = {}
    for r in range(world_size):
        w = WorkerInfo(*pickle.loads(store.get(f"rpc/worker/{r}")))
        infos[w.name] = w

    _agent = _Agent(self_info, infos, store, world_size)
    _agent.server = threading.Thread(target=_serve, args=(_agent, srv),
                                     daemon=True, name="rpc-server")
    _agent.server.start()
    # all workers serving before anyone calls out (reference
    # _barrier_never_timeout after rpc_start_worker)
    barrier_via_store(store, "rpc/init", rank, world_size)


def _require_agent() -> _Agent:
    if _agent is None:
        raise RuntimeError("call init_rpc() first")
    return _agent


def _invoke(to, fn, args, kwargs, timeout):
    agent = _require_agent()
    try:
        info = agent.infos[to]
    except KeyError:
        raise ValueError(f"unknown RPC worker {to!r}; known: "
                         f"{sorted(agent.infos)}") from None
    with socket.create_connection((info.ip, info.port),
                                  timeout=timeout if timeout and timeout > 0
                                  else None) as conn:
        _send_frame(conn, (fn, args or (), kwargs or {}))
        status, payload = _recv_frame(conn)
    if status == "err":
        raise payload
    return payload


def rpc_sync(to, fn, args=None, kwargs=None, timeout=-1):
    """Blocking remote call; returns fn's result (reference rpc.py:160)."""
    return _invoke(to, fn, args, kwargs, timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=-1):
    """Non-blocking remote call; returns a Future with .wait()/.result()
    (reference rpc.py:206 FutureWrapper)."""
    agent = _require_agent()
    fut = agent.pool.submit(_invoke, to, fn, args, kwargs, timeout)
    fut.wait = fut.result            # reference future spells it wait()
    return fut


def get_worker_info(name):
    return _require_agent().infos[name]


def get_all_worker_infos():
    return sorted(_require_agent().infos.values(), key=lambda w: w.rank)


def get_current_worker_info():
    return _require_agent().self_info


def shutdown():
    """Barrier, then stop serving (reference rpc.py:305)."""
    global _agent
    if _agent is None:
        return
    agent = _agent
    barrier_via_store(agent.store, "rpc/shutdown", agent.self_info.rank,
                      agent.world_size)
    agent.stop.set()
    agent.pool.shutdown(wait=False)
    if agent.server is not None:
        agent.server.join(timeout=2.0)
    _agent = None
