"""Paged KV-cache block management for the serving engine.

The device caches are a fixed pool of ``num_blocks`` pages of
``block_size`` token slots each (layout ``[L, num_blocks, H_kv, bs, D]``,
the blha cache layout per layer).  This module owns the HOST side of that
pool: which pages belong to which sequence, in order — the per-sequence
block table the paged-attention kernel walks via scalar prefetch
(ops/pallas/paged_attention.py).  Mirrors the reference serving stack's
block manager around block_multi_head_attention (and vLLM's BlockManager
shape): alloc on admission, grow one page at a time during decode, free on
retirement, and report occupancy/fragmentation so the scheduler can decide
when to stop admitting and when to preempt.

Prefix caching (``enable_prefix_caching=True``) turns the pool into a
content-addressed cache: every FULL page is identified by a rolling chain
hash of all prompt/generated tokens up to and including that page, and a
hash → block map lets a new sequence whose token prefix matches reuse the
page instead of recomputing its KV.  Reuse is refcounted — a page may back
several live sequences at once — and any write into a page with
refcount > 1 first COPIES it (copy-on-write), so divergence after a shared
partial page never corrupts a neighbour.  Freed pages whose content is
registered are not returned to the free list; they park in an LRU of
refcount-0 "cached" pages and are only evicted (unregistered) when the
free list is empty — eviction is the last resort, so a hot system prompt
stays resident.  Page lifecycle:

    free → allocated (refcount 1) → shared (refcount n)
                  │                      │
                  └──── freed, hashed ───┘
                            ↓
                    cached (refcount 0, LRU) ── evicted ──→ free

Block id 0 is reserved as the NULL page: padded scheduler slots point
every block-table entry at it, so their (masked) cache writes land in a
page no live sequence owns.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

__all__ = ["BlockManager", "BlockPoolExhausted", "NULL_BLOCK",
           "prefix_chain_hashes"]

NULL_BLOCK = 0


class BlockPoolExhausted(RuntimeError):
    """No free or evictable page is left — the caller must preempt."""


def _page_hash(prev, tokens):
    """Rolling chain hash: a page's identity is its OWN tokens plus the
    hash chain of every page before it, so identical pages at different
    prefix positions never alias."""
    return hash((prev, tuple(tokens)))


class BlockManager:
    """Fixed-size page pool with per-sequence block tables.

    Invariants (asserted by tests/test_llm_engine.py and
    tests/test_prefix_cache.py via ``check_invariants``):
    - block 0 (the null page) is never handed out;
    - every block is exactly one of: free, cached (refcount 0, hashed),
      or live (refcount >= 1);
    - a live block's refcount equals the number of block tables holding
      it (sharing only via the prefix cache);
    - num_used + num_free + num_cached == num_blocks - 1 at all times;
    - free() of an unknown/already-freed sequence raises instead of
      corrupting the free list.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 enable_prefix_caching: bool = False):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (one is the reserved null page)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.enable_prefix_caching = bool(enable_prefix_caching)
        # LIFO free list (ids 1..num_blocks-1); id 0 stays reserved
        self._free = list(range(self.num_blocks - 1, NULL_BLOCK, -1))
        self._tables: dict = {}          # seq id -> [block ids, in order]
        self._tokens: dict = {}          # seq id -> token count covered
        self._ref: dict = {}             # block id -> refcount (>= 1)
        # prefix-cache state
        self._cached: OrderedDict = OrderedDict()   # refcount-0 LRU
        self._hash_to_block: dict = {}   # chain hash -> block id
        self._block_hashes: dict = {}    # block id -> set of chain hashes
        self._ids: dict = {}             # seq id -> token ids (or None)
        self._valid: dict = {}           # seq id -> positions with valid KV
        self._chain: dict = {}           # seq id -> per-full-page chain hashes
        self._version: dict = {}         # seq id -> table mutation counter
        self._freed: set = set()         # for clear double-free errors
        # pages handed out since the last drain_fresh(): their previous
        # content (and, in int8 mode, their quantization scales) is dead.
        # The quantized engine drains this each step and resets the scale
        # rows device-side before any new write lands.
        self._fresh: set = set()
        # hierarchical-KV spill quarantine: when a host tier is attached
        # (spill_on_evict=True, set by the engine), evict_parked moves
        # registered LRU pages here instead of freeing them — the device
        # bytes must survive until the engine's step-boundary drain
        # copies them host-side.  block id -> tuple of chain hashes.
        self.spill_on_evict = False
        self._spill_pending: dict = {}
        # counters for the scheduler stats surface
        self.alloc_count = 0
        self.free_count = 0
        self.peak_used = 0
        self.cache_hit_tokens = 0
        self.cache_miss_tokens = 0
        self.cow_count = 0
        self.eviction_count = 0
        self.parked_evicted = 0
        self.spill_quarantined = 0    # pages routed to the spill drain
        self.spill_restored = 0       # pages adopted back from the tier
        # fault-injection seam: a nullary callable returning True while a
        # FaultPlan simulates pool exhaustion (allocation pressure without
        # shrinking the pool); None -> zero cost
        self._fault_hook = None

    # -- capacity queries ---------------------------------------------------

    def blocks_for(self, n_tokens: int) -> int:
        """Pages needed to hold n_tokens."""
        return max(0, -(-int(n_tokens) // self.block_size))

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_cached(self) -> int:
        return len(self._cached)

    @property
    def num_spill_pending(self) -> int:
        """Pages quarantined for the host-tier spill drain.  They free at
        the next step boundary, so pressure accounting may credit them as
        reclaimable headroom — but the allocator must NOT hand them out
        (their device bytes are still awaited by the drain)."""
        return len(self._spill_pending)

    @property
    def num_used(self) -> int:
        return (self.num_blocks - 1) - len(self._free) \
            - len(self._cached) - len(self._spill_pending)

    def can_allocate(self, n_blocks: int) -> bool:
        if self._fault_hook is not None and self._fault_hook():
            return False
        # cached pages are evictable, so they count as available
        return n_blocks <= len(self._free) + len(self._cached)

    # -- pool primitives ----------------------------------------------------

    def _take_block(self) -> int:
        """One fresh page: free list first, else evict the LRU cached page
        (the only moment a cached page loses its registered content)."""
        if self._fault_hook is not None and self._fault_hook():
            raise BlockPoolExhausted("injected pool exhaustion")
        if self._free:
            blk = self._free.pop()
            self._fresh.add(blk)
            return blk
        if self._cached:
            blk, _ = self._cached.popitem(last=False)     # oldest first
            self._unregister(blk)
            self.eviction_count += 1
            self._fresh.add(blk)
            return blk
        raise BlockPoolExhausted("no free or evictable page left")

    def _unregister(self, blk: int) -> None:
        for h in self._block_hashes.pop(blk, ()):
            if self._hash_to_block.get(h) == blk:
                del self._hash_to_block[h]

    def _register(self, blk: int, h) -> None:
        # first content wins: a hash already mapping to another live/cached
        # block keeps pointing there (dedup happens at match time)
        if self._hash_to_block.setdefault(h, blk) == blk:
            self._block_hashes.setdefault(blk, set()).add(h)

    def _incref(self, blk: int) -> None:
        self._ref[blk] = self._ref.get(blk, 0) + 1
        self._cached.pop(blk, None)

    def _decref(self, blk: int) -> None:
        r = self._ref.get(blk, 0)
        if r <= 0:
            raise AssertionError(
                f"refcount underflow on block {blk} (double free?)")
        if r == 1:
            del self._ref[blk]
            if self._block_hashes.get(blk):
                self._cached[blk] = None      # park, content stays valid
            else:
                self._free.append(blk)
        else:
            self._ref[blk] = r - 1

    # -- alloc / grow / free ------------------------------------------------

    def allocate(self, seq_id, n_tokens: int) -> bool:
        """Claim fresh pages covering n_tokens for a new sequence (no
        prefix matching — token ids unknown).  False (and no state change)
        when the pool cannot cover the request."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already has a block table")
        need = self.blocks_for(n_tokens)
        if not self.can_allocate(need):
            return False
        table = [self._take_block() for _ in range(need)]
        for b in table:
            self._incref(b)
        self._tables[seq_id] = table
        self._tokens[seq_id] = int(n_tokens)
        self._ids[seq_id] = None
        self._valid[seq_id] = 0
        self._chain[seq_id] = []
        self._version[seq_id] = 0
        self._freed.discard(seq_id)
        self.alloc_count += need
        self.peak_used = max(self.peak_used, self.num_used)
        return True

    def match_prefix(self, token_ids) -> int:
        """Longest cached prefix (in tokens) for token_ids, capped at
        len(token_ids) - 1 so at least one token is always (re)computed
        for logits.  Read-only: no refcounts change."""
        hits, partial, n_hit = self._match(list(token_ids))
        return n_hit

    def _match(self, ids):
        """(full_hit_blocks, partial_hit_block_or_None, n_hit_tokens)."""
        if not self.enable_prefix_caching:
            return [], None, 0
        bs = self.block_size
        n = len(ids)
        hits, prev = [], None
        for p in range(n // bs):
            h = _page_hash(prev, ids[p * bs:(p + 1) * bs])
            blk = self._hash_to_block.get(h)
            if blk is None or blk in hits:
                break
            hits.append(blk)
            prev = h
        while len(hits) * bs >= n:        # keep >= 1 token to compute
            hits.pop()
            prev = None if not hits else _page_hash_chain(ids, len(hits), bs)
        n_hit = len(hits) * bs
        partial = None
        rem = ids[n_hit:]
        for k in range(min(bs - 1, n - 1 - n_hit), 0, -1):
            h = _page_hash(prev, rem[:k])
            blk = self._hash_to_block.get(h)
            if blk is not None and blk not in hits:
                partial = blk
                n_hit += k
                break
        return hits, partial, n_hit

    def acquire(self, seq_id, token_ids):
        """Prefix-cached admission: match token_ids against the cache,
        take refcounted references on every hit page, claim fresh pages
        for the miss suffix.  Returns the number of prefix tokens whose
        KV is already valid (0 on a clean miss), or None when the pool
        cannot cover the miss suffix."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already has a block table")
        ids = [int(t) for t in token_ids]
        if not ids:
            raise ValueError("empty token_ids")
        if not self.enable_prefix_caching:
            return 0 if self.allocate(seq_id, len(ids)) else None
        hits, partial, n_hit = self._match(ids)
        hit_blocks = hits + ([partial] if partial is not None else [])
        fresh = self.blocks_for(len(ids)) - len(hit_blocks)
        evictable_hits = sum(1 for b in hit_blocks if b in self._cached)
        if fresh > len(self._free) + len(self._cached) - evictable_hits \
                or (fresh > 0 and self._fault_hook is not None
                    and self._fault_hook()):
            return None
        for b in hit_blocks:
            self._incref(b)
        table = hit_blocks + [self._take_block() for _ in range(fresh)]
        for b in table[len(hit_blocks):]:
            self._incref(b)
        self._tables[seq_id] = table
        self._tokens[seq_id] = len(ids)
        self._ids[seq_id] = ids
        self._valid[seq_id] = n_hit
        # chain hashes for the full hit pages (prefix of the table)
        chain, prev = [], None
        for p in range(len(hits)):
            prev = _page_hash(prev, ids[p * self.block_size:
                                        (p + 1) * self.block_size])
            chain.append(prev)
        self._chain[seq_id] = chain
        self._version[seq_id] = 0
        self._freed.discard(seq_id)
        self.alloc_count += fresh
        self.cache_hit_tokens += n_hit
        self.cache_miss_tokens += len(ids) - n_hit
        self.peak_used = max(self.peak_used, self.num_used)
        return n_hit

    def ensure(self, seq_id, n_tokens: int) -> bool:
        """Grow seq_id's table until it covers n_tokens (decode appends one
        token per step; this allocates the next page on a boundary).  False
        when the pool is exhausted — the scheduler's preemption trigger."""
        table = self._tables[seq_id]
        need = self.blocks_for(n_tokens)
        grow = need - len(table)
        if grow > 0:
            if not self.can_allocate(grow):
                return False
            for _ in range(grow):
                b = self._take_block()
                self._incref(b)
                table.append(b)
            self.alloc_count += grow
            self._version[seq_id] += 1
            self.peak_used = max(self.peak_used, self.num_used)
        self._tokens[seq_id] = max(self._tokens.get(seq_id, 0), int(n_tokens))
        return True

    def reserve_window(self, rows):
        """All-or-nothing page-slack reservation for a K-step decode window.

        ``rows`` is an iterable of ``(seq_id, n_tokens)`` targets.  Every
        sequence is grown (``ensure``) to its target; if ANY row cannot be
        covered, every grow this call performed is rolled back (``truncate``
        to the recorded prior token count — a no-op truncate drops no pages
        and does not bump the table version) and ``None`` is returned with
        the pool exactly as found.  On success returns the list of prior
        token counts, one per row, in input order: the rollback targets a
        caller must truncate back to if IT later abandons the window (e.g.
        a copy-on-write resolution fails mid-reservation).
        """
        done = []
        for seq_id, n_tokens in rows:
            prior = self._tokens.get(seq_id, 0)
            try:
                grown = self.ensure(seq_id, int(n_tokens))
            except BlockPoolExhausted:
                grown = False
            if not grown:
                for sid, tok in reversed(done):
                    self.truncate(sid, tok)
                return None
            done.append((seq_id, prior))
        return [tok for _, tok in done]

    def cow_if_shared(self, seq_id, pos: int):
        """Call before writing token position ``pos``: when the page
        holding pos is shared (refcount > 1) the writer gets a private
        copy — the table entry is swapped and (src, dst) returned so the
        engine can copy the page device-side.  None when the page is
        already private.  Raises BlockPoolExhausted when no page is
        available for the copy (preemption trigger)."""
        table = self._tables[seq_id]
        idx = int(pos) // self.block_size
        src = table[idx]
        if self._ref.get(src, 0) <= 1:
            return None
        dst = self._take_block()          # may raise BlockPoolExhausted
        # the engine's CoW program copies the page's quantization scale
        # rows along with its data, so the dst page is NOT fresh — a
        # scale reset here would corrupt the copied int8 content
        self._fresh.discard(dst)
        self._incref(dst)
        table[idx] = dst
        self._decref(src)                 # others keep the original
        self._version[seq_id] += 1
        self.cow_count += 1
        self.alloc_count += 1
        self.peak_used = max(self.peak_used, self.num_used)
        return src, dst

    def commit_prefill(self, seq_id, n_new: int) -> None:
        """Mark n_new more positions as device-valid (their KV writes are
        dispatched) and register every page this fills in the hash map."""
        if self._ids.get(seq_id) is None:
            self._valid[seq_id] = self._valid.get(seq_id, 0) + int(n_new)
            return
        v = self._valid[seq_id] + int(n_new)
        if v > len(self._ids[seq_id]):
            raise AssertionError(
                f"commit past known tokens for {seq_id!r}: {v} > "
                f"{len(self._ids[seq_id])}")
        self._valid[seq_id] = v
        self._register_full_pages(seq_id)

    def commit_decode_token(self, seq_id, token) -> None:
        """One decode step wrote `token`'s KV at the next position."""
        ids = self._ids.get(seq_id)
        if ids is None:
            self._valid[seq_id] = self._valid.get(seq_id, 0) + 1
            return
        if len(ids) != self._valid[seq_id]:
            raise AssertionError(
                f"decode commit for {seq_id!r} before prefill finished "
                f"({self._valid[seq_id]}/{len(ids)} valid)")
        ids.append(int(token))
        self._tokens[seq_id] = max(self._tokens.get(seq_id, 0), len(ids))
        self._valid[seq_id] = len(ids)
        self._register_full_pages(seq_id)

    def _register_full_pages(self, seq_id) -> None:
        if not self.enable_prefix_caching:
            return
        bs = self.block_size
        ids = self._ids[seq_id]
        chain = self._chain[seq_id]
        table = self._tables[seq_id]
        full = self._valid[seq_id] // bs
        while len(chain) < full:
            p = len(chain)
            prev = chain[-1] if chain else None
            h = _page_hash(prev, ids[p * bs:(p + 1) * bs])
            chain.append(h)
            self._register(table[p], h)

    def truncate(self, seq_id, n_tokens: int) -> int:
        """Roll seq_id back to its first ``n_tokens`` tokens (speculative-
        decode rejection: the verify step wrote K/V for draft tokens that
        were not accepted).  Three effects:

        - tail pages no longer needed by n_tokens are decommitted and
          released (refcount drop: shared pages stay live for their other
          owners, registered refcount-0 pages park in the cached LRU,
          the rest rejoin the free list);
        - content hashes registered by THIS sequence for pages at or past
          the new boundary are un-registered when the page is private
          (refcount 1): future writes will overwrite those slots, and the
          prefix cache must never serve rolled-back K/V.  Shared pages
          keep their registration — their content is still valid for the
          other owners, and this sequence's future writes copy-on-write
          first, so the registered bytes are never clobbered;
        - the sequence's id/valid/chain bookkeeping shrinks to n_tokens.

        Returns the number of pages released.  Truncating to a count the
        table already satisfies (no page drop, no hash past the boundary)
        is a cheap no-op that does not bump the table version.
        """
        if seq_id not in self._tables:
            raise ValueError(f"truncate of unknown sequence {seq_id!r}")
        n = int(n_tokens)
        if n < 0:
            raise ValueError(f"n_tokens must be >= 0, got {n}")
        table = self._tables[seq_id]
        bs = self.block_size
        need = self.blocks_for(n)
        if need > len(table):
            raise ValueError(
                f"truncate({seq_id!r}, {n}) needs {need} pages but the "
                f"table holds {len(table)}")
        dropped = len(table) - need
        # un-register full-page hashes this sequence registered beyond the
        # new boundary: those slots will be rewritten with different
        # tokens, so a prefix match on the old content would serve
        # rolled-back K/V.  Only private pages are scrubbed — a shared
        # page's content survives (CoW guards future writes).
        chain = self._chain.get(seq_id, [])
        full_keep = n // bs
        for p in range(full_keep, len(chain)):
            if p < len(table):
                blk = table[p]
                if self._ref.get(blk, 0) == 1 \
                        and self._hash_to_block.get(chain[p]) == blk:
                    del self._hash_to_block[chain[p]]
                    hs = self._block_hashes.get(blk)
                    if hs is not None:
                        hs.discard(chain[p])
                        if not hs:
                            del self._block_hashes[blk]
        del chain[full_keep:]
        if n % bs and full_keep < len(table) \
                and self._ref.get(table[full_keep], 0) == 1:
            # partial boundary page: slots >= n % bs will be rewritten, so
            # partial-prefix hashes registered by earlier owners (free()
            # registers written tails) could also serve rolled-back K/V.
            # Conservatively scrub every hash on the private page.
            self._unregister(table[full_keep])
        # release the tail pages themselves
        for blk in reversed(table[need:]):
            self._decref(blk)
        del table[need:]
        ids = self._ids.get(seq_id)
        if ids is not None and len(ids) > n:
            del ids[n:]
        if self._valid.get(seq_id, 0) > n:
            self._valid[seq_id] = n
        if self._tokens.get(seq_id, 0) > n:
            self._tokens[seq_id] = n
        if dropped:
            self._version[seq_id] += 1
            self.free_count += dropped
        return dropped

    def free(self, seq_id) -> None:
        """Return every page of seq_id (retirement/preemption): refcounts
        drop by one; pages with registered content park in the cached LRU,
        the rest rejoin the free list.  A written partial tail page is
        registered on the way out so a recompute/follow-up can hit it.
        Double-free raises a clear error instead of corrupting the pool."""
        self._drop(seq_id, register_tail=True, op="free")

    def release(self, seq_id) -> None:
        """Abort-path free: retire a sequence that may be MID-prefill,
        mid-decode, or mid-spec-verify.  Differences from ``free``:

        - the written partial tail page is NOT registered in the prefix
          cache — an aborted request's trailing positions are the ones
          the engine may have been about to overwrite, and an abort must
          never widen the cache's reachable content;
        - assertion-hardened for the shared-prefix case: a page this
          sequence shares with live neighbours must only DECREF — its
          chain-hash registrations stay exactly as they were (scrubbing
          them would make a hot system prompt vanish from the cache the
          moment one of its readers is cancelled), and the page itself
          must remain live for the surviving owners.

        Raises the same clear double-free/unknown errors as ``free``.
        """
        # snapshot shared pages + their registrations BEFORE the drop
        table = self._tables.get(seq_id, ())
        shared = {b: set(self._block_hashes.get(b, ()))
                  for b in table if self._ref.get(b, 0) > 1}
        self._drop(seq_id, register_tail=False, op="release")
        for b, hashes in shared.items():
            assert b in self._ref, (
                f"abort of {seq_id!r} killed shared page {b} "
                f"(refcount reached 0 with other owners alive)")
            assert self._block_hashes.get(b, set()) == hashes, (
                f"abort of {seq_id!r} scrubbed live chain hashes on "
                f"shared page {b}")
            for h in hashes:
                assert self._hash_to_block.get(h) == b, \
                    f"abort of {seq_id!r} redirected hash {h} off page {b}"

    def _drop(self, seq_id, *, register_tail: bool, op: str) -> None:
        if seq_id not in self._tables:
            if seq_id in self._freed:
                raise ValueError(
                    f"double {op}: sequence {seq_id!r} was already freed")
            raise ValueError(f"{op} of unknown sequence {seq_id!r}")
        table = self._tables.pop(seq_id)
        ids = self._ids.pop(seq_id, None)
        valid = self._valid.pop(seq_id, 0)
        chain = self._chain.pop(seq_id, [])
        self._tokens.pop(seq_id, None)
        self._version.pop(seq_id, None)
        if register_tail and self.enable_prefix_caching and ids is not None:
            bs = self.block_size
            p, k = valid // bs, valid % bs
            if k and len(chain) >= p:
                prev = chain[p - 1] if p else None
                self._register(table[p],
                               _page_hash(prev, ids[p * bs:p * bs + k]))
        self.free_count += len(table)
        for b in reversed(table):
            self._decref(b)
        self._freed.add(seq_id)

    def evict_parked(self, n: int) -> int:
        """Proactively evict up to ``n`` LRU parked (refcount-0 cached)
        pages — the degradation controller's tier-3 lever: trade future
        prefix-cache hits for immediate allocation headroom.  Counted
        separately from demand evictions (``eviction_count`` is
        _take_block's last-resort path).

        With a host spill tier attached (``spill_on_evict``) this is
        spill-first instead of kill: a registered page is quarantined in
        ``_spill_pending`` with its chain hashes — unregistered from the
        hash maps (it can no longer serve HBM hits) but NOT freed, since
        its device bytes must survive until the engine's step-boundary
        drain copies them into the host pool and calls
        ``take_spill_pending``.  Hashless pages free immediately either
        way.  Returns the number of pages evicted (spilled or freed)."""
        done = 0
        while done < int(n) and self._cached:
            blk, _ = self._cached.popitem(last=False)     # oldest first
            hashes = tuple(sorted(self._block_hashes.get(blk, ())))
            self._unregister(blk)
            if self.spill_on_evict and hashes:
                self._spill_pending[blk] = hashes
                self.spill_quarantined += 1
            else:
                self._free.append(blk)
            done += 1
        self.parked_evicted += done
        return done

    def take_spill_pending(self) -> list:
        """Engine step-boundary drain: pop every quarantined page as
        ``(block, chain_hashes)`` and return the blocks to the free
        list.  The CALLER must materialize the pages' device bytes
        host-side before issuing any new device write — freed blocks can
        be handed out again the same step.  Sorted for determinism."""
        if not self._spill_pending:
            return []
        out = sorted(self._spill_pending.items())
        self._spill_pending.clear()
        for blk, _ in out:
            self._free.append(blk)
        return out

    def adopt_restored(self, hashes):
        """Re-register one page restored from the host tier: claim a page
        from the FREE list only (a restore is opportunistic — it must
        never evict parked HBM content to make room), register it under
        every chain hash in ``hashes``, and park it refcount-0 in the
        cached LRU as most-recent, exactly as if a sequence had just
        retired it.  From here the normal content-addressed machinery —
        refcounted sharing, CoW, parking, eviction (or re-spill) — applies
        untouched.  Returns the block id, or None when no free page or no
        unclaimed hash is available (the caller keeps the host copy).

        The block is explicitly discarded from the fresh set: the caller
        restores the page's quantization scale rows along with its data
        (int8 mode), and the engine's fresh-mask scale reset would zero
        those freshly restored scales."""
        if not self._free:
            return None
        hashes = [h for h in hashes if h not in self._hash_to_block]
        if not hashes:
            return None
        blk = self._free.pop()
        self._fresh.discard(blk)
        for h in hashes:
            self._register(blk, h)
        self._cached[blk] = None          # park as most-recently-used
        self.spill_restored += 1
        return blk

    def has_hash(self, h) -> bool:
        """True when a chain hash is servable from the HBM prefix cache
        (live or parked) — the spill tier need not restore it."""
        return h in self._hash_to_block

    def chain_hashes(self, seq_id) -> list:
        """Chain hashes of seq_id's full hit/registered prefix pages, in
        order (prefetch-hit attribution reads these)."""
        return list(self._chain.get(seq_id, ()))

    def drain_fresh(self) -> list:
        """Pages handed out (via ``_take_block``) since the last drain,
        excluding CoW destinations (their content is a live copy).  The
        quantized engine calls this once per step and zeroes the returned
        pages' scale-pool rows before the step's writes commit; the
        float32 engine never needs it (stale page content is masked by
        ``kv_lens`` at read time, but a stale SCALE would rescale freshly
        written int8 values).  Sorted for determinism; clears the set."""
        out = sorted(self._fresh)
        self._fresh.clear()
        return out

    def has(self, seq_id) -> bool:
        return seq_id in self._tables

    # -- table export -------------------------------------------------------

    def block_table(self, seq_id) -> list:
        return list(self._tables[seq_id])

    def table_version(self, seq_id) -> int:
        """Bumped on every table mutation (grow / CoW swap) — lets the
        engine cache padded host rows and rebuild only on change."""
        return self._version[seq_id]

    def padded_table(self, seq_id, width: int) -> np.ndarray:
        """int32 [width] block table padded with the null page (the kernel
        clamps/never reads past `lengths`, and padded entries DMA the null
        page rather than a live one)."""
        table = self._tables[seq_id]
        if len(table) > width:
            raise ValueError(
                f"sequence {seq_id!r} holds {len(table)} pages > table "
                f"width {width}")
        out = np.full((width,), NULL_BLOCK, np.int32)
        out[:len(table)] = table
        return out

    # -- stats --------------------------------------------------------------

    def occupancy(self) -> float:
        """Fraction of the usable pool currently owned by sequences."""
        usable = self.num_blocks - 1
        return self.num_used / usable if usable else 0.0

    def fragmentation(self) -> float:
        """Internal fragmentation: fraction of allocated slots not backing
        a token (tail-of-last-page waste; paging trades this bounded waste
        for the dense [B, max_len] cache's unbounded padding waste)."""
        slots = self.num_used * self.block_size
        if slots == 0:
            return 0.0
        used_tokens = sum(min(self._tokens.get(s, 0),
                              len(t) * self.block_size)
                          for s, t in self._tables.items())
        return max(0.0, 1.0 - used_tokens / slots)

    def stats(self) -> dict:
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "used_blocks": self.num_used,
            "free_blocks": self.num_free,
            "cached_blocks": self.num_cached,
            "peak_used_blocks": self.peak_used,
            "occupancy": round(self.occupancy(), 4),
            "fragmentation": round(self.fragmentation(), 4),
            "alloc_count": self.alloc_count,
            "free_count": self.free_count,
            "prefix_caching": self.enable_prefix_caching,
            "cache_hit_tokens": self.cache_hit_tokens,
            "cache_miss_tokens": self.cache_miss_tokens,
            "cow_count": self.cow_count,
            "eviction_count": self.eviction_count,
            "parked_evicted": self.parked_evicted,
            "spill_pending": self.num_spill_pending,
            "spill_quarantined": self.spill_quarantined,
            "spill_restored": self.spill_restored,
        }

    # -- invariants (test surface) ------------------------------------------

    def check_invariants(self) -> None:
        """Raise AssertionError on any pool-accounting violation."""
        usable = self.num_blocks - 1
        free, cached, live = set(self._free), set(self._cached), \
            set(self._ref)
        spill = set(self._spill_pending)
        assert len(self._free) == len(free), "duplicate ids on free list"
        assert not (free & cached), "block both free and cached"
        assert not (free & live), "block both free and live"
        assert not (cached & live), "block both cached and live"
        assert not (spill & (free | cached | live)), \
            "spill-pending block also free/cached/live"
        assert len(free) + len(cached) + len(live) + len(spill) \
            == usable, (
            f"pool accounting broken: {len(free)} free + {len(cached)} "
            f"cached + {len(live)} live + {len(spill)} spill-pending "
            f"!= {usable}")
        assert NULL_BLOCK not in free | cached | live | spill, \
            "null page leaked"
        for blk in spill:
            assert blk not in self._block_hashes, \
                f"spill-pending block {blk} still registered"
        counts: dict = {}
        for seq, table in self._tables.items():
            assert len(table) == len(set(table)), \
                f"sequence {seq!r} holds a page twice"
            for b in table:
                counts[b] = counts.get(b, 0) + 1
        assert counts.keys() == live, "live set != union of tables"
        for b, n in counts.items():
            assert self._ref[b] == n, (
                f"block {b} refcount {self._ref[b]} != {n} table refs")
            assert self._ref[b] >= 1, f"block {b} refcount < 1"
        for h, b in self._hash_to_block.items():
            assert b in live or b in cached, \
                f"hash map points at free block {b}"
            assert h in self._block_hashes.get(b, ()), \
                f"hash map / block hash mismatch on {b}"


def _page_hash_chain(ids, n_pages, bs):
    """Chain hash after n_pages full pages of ids."""
    prev = None
    for p in range(n_pages):
        prev = _page_hash(prev, ids[p * bs:(p + 1) * bs])
    return prev


def prefix_chain_hashes(token_ids, block_size: int) -> list:
    """Chain hash of EVERY full page prefix of ``token_ids``, in order.

    ``result[i]`` identifies pages 0..i of the sequence — exactly the
    hashes ``BlockManager`` registers for a prompt's full pages, computed
    WITHOUT touching any pool.  The replica router uses this to predict
    which engine's prefix cache already holds a prompt's leading pages
    (frontend/router.py): two prompts share cached pages iff their chain
    hashes match, so matching hashes host-side is exactly the cache's own
    sharing criterion."""
    ids = [int(t) for t in token_ids]
    bs = int(block_size)
    out, prev = [], None
    for p in range(len(ids) // bs):
        prev = _page_hash(prev, ids[p * bs:(p + 1) * bs])
        out.append(prev)
    return out
