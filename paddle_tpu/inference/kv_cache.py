"""Paged KV-cache block management for the serving engine.

The device caches are a fixed pool of ``num_blocks`` pages of
``block_size`` token slots each (layout ``[L, num_blocks, H_kv, bs, D]``,
the blha cache layout per layer).  This module owns the HOST side of that
pool: which pages belong to which sequence, in order — the per-sequence
block table the paged-attention kernel walks via scalar prefetch
(ops/pallas/paged_attention.py).  Mirrors the reference serving stack's
block manager around block_multi_head_attention (and vLLM's BlockManager
shape): alloc on admission, grow one page at a time during decode, free on
retirement, and report occupancy/fragmentation so the scheduler can decide
when to stop admitting and when to preempt.

Block id 0 is reserved as the NULL page: padded scheduler slots point
every block-table entry at it, so their (masked) cache writes land in a
page no live sequence owns.
"""
from __future__ import annotations

import numpy as np

__all__ = ["BlockManager", "NULL_BLOCK"]

NULL_BLOCK = 0


class BlockManager:
    """Fixed-size page pool with per-sequence block tables.

    Invariants (asserted by tests/test_llm_engine.py):
    - a block is owned by at most one sequence at a time;
    - block 0 (the null page) is never handed out;
    - free() returns every block of a sequence to the pool;
    - num_free + num_allocated == num_blocks - 1 at all times.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (one is the reserved null page)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list (ids 1..num_blocks-1); id 0 stays reserved
        self._free = list(range(self.num_blocks - 1, NULL_BLOCK, -1))
        self._tables: dict = {}          # seq id -> [block ids, in order]
        self._tokens: dict = {}          # seq id -> token count covered
        # counters for the scheduler stats surface
        self.alloc_count = 0
        self.free_count = 0
        self.peak_used = 0

    # -- capacity queries ---------------------------------------------------

    def blocks_for(self, n_tokens: int) -> int:
        """Pages needed to hold n_tokens."""
        return max(0, -(-int(n_tokens) // self.block_size))

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def can_allocate(self, n_blocks: int) -> bool:
        return n_blocks <= len(self._free)

    # -- alloc / grow / free ------------------------------------------------

    def allocate(self, seq_id, n_tokens: int) -> bool:
        """Claim pages covering n_tokens for a new sequence.  False (and no
        state change) when the pool cannot cover the request."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already has a block table")
        need = self.blocks_for(n_tokens)
        if not self.can_allocate(need):
            return False
        self._tables[seq_id] = [self._free.pop() for _ in range(need)]
        self._tokens[seq_id] = int(n_tokens)
        self.alloc_count += need
        self.peak_used = max(self.peak_used, self.num_used)
        return True

    def ensure(self, seq_id, n_tokens: int) -> bool:
        """Grow seq_id's table until it covers n_tokens (decode appends one
        token per step; this allocates the next page on a boundary).  False
        when the pool is exhausted — the scheduler's preemption trigger."""
        table = self._tables[seq_id]
        need = self.blocks_for(n_tokens)
        grow = need - len(table)
        if grow > 0:
            if not self.can_allocate(grow):
                return False
            table.extend(self._free.pop() for _ in range(grow))
            self.alloc_count += grow
            self.peak_used = max(self.peak_used, self.num_used)
        self._tokens[seq_id] = max(self._tokens.get(seq_id, 0), int(n_tokens))
        return True

    def free(self, seq_id) -> None:
        """Return every page of seq_id to the pool (retirement/preemption)."""
        table = self._tables.pop(seq_id)
        self._tokens.pop(seq_id, None)
        self.free_count += len(table)
        self._free.extend(reversed(table))

    def has(self, seq_id) -> bool:
        return seq_id in self._tables

    # -- table export -------------------------------------------------------

    def block_table(self, seq_id) -> list:
        return list(self._tables[seq_id])

    def padded_table(self, seq_id, width: int) -> np.ndarray:
        """int32 [width] block table padded with the null page (the kernel
        clamps/never reads past `lengths`, and padded entries DMA the null
        page rather than a live one)."""
        table = self._tables[seq_id]
        if len(table) > width:
            raise ValueError(
                f"sequence {seq_id!r} holds {len(table)} pages > table "
                f"width {width}")
        out = np.full((width,), NULL_BLOCK, np.int32)
        out[:len(table)] = table
        return out

    # -- stats --------------------------------------------------------------

    def occupancy(self) -> float:
        """Fraction of the usable pool currently owned by sequences."""
        usable = self.num_blocks - 1
        return self.num_used / usable if usable else 0.0

    def fragmentation(self) -> float:
        """Internal fragmentation: fraction of allocated slots not backing
        a token (tail-of-last-page waste; paging trades this bounded waste
        for the dense [B, max_len] cache's unbounded padding waste)."""
        slots = self.num_used * self.block_size
        if slots == 0:
            return 0.0
        used_tokens = sum(min(self._tokens.get(s, 0),
                              len(t) * self.block_size)
                          for s, t in self._tables.items())
        return 1.0 - used_tokens / slots

    def stats(self) -> dict:
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "used_blocks": self.num_used,
            "free_blocks": self.num_free,
            "peak_used_blocks": self.peak_used,
            "occupancy": round(self.occupancy(), 4),
            "fragmentation": round(self.fragmentation(), 4),
            "alloc_count": self.alloc_count,
            "free_count": self.free_count,
        }
