"""Per-request flight recorder: a bounded LRU of request forensics.

``ServingStats`` tells you the stream was slow; the flight recorder
tells you what happened to request ``r2-req-5`` specifically: when it
arrived, how long it queued, how its prompt prefilled (chunks, tokens
the prefix cache already held), how speculation treated it (accepted
vs rolled-back drafts), whether it was preempted or quarantined, what
degradation tier the engine was in when it was admitted vs when it
finished, which replica ran it, why it ended, and how much deadline
slack it had left at each phase.  The frontend serves individual
records at ``GET /debug/requests/<id>`` and ranked lists at
``GET /debug/requests?finished=slowest``.

Design rules, inherited from the tracer (profiler/trace.py):

* **Disabled means free.**  The engine holds ``self.flight = None``
  unless a recorder is installed; every seam guards on that FIRST, so
  an engine without one executes no line of this file (pinned by
  tracemalloc test, like the tracer's).
* **Bounded forever.**  Records live in an insertion-ordered dict
  capped at ``capacity``; opening a record past the cap evicts the
  OLDEST and counts it in ``evicted`` — a server fielding millions of
  requests holds the most recent window and says how much it shed.
* **Engine-keyed, frontend-joined.**  The engine keys records by rid
  (all it knows); the runner ``annotate()``s the frontend request id,
  replica name, and deadline onto the record at admission — the same
  cross-tier join the tracer's ``runner.deliver`` instants carry.
  After a crash recovery the runner re-admits live requests into a
  fresh engine whose rids restart at 0, so a re-opened rid replaces
  the older record: the recorder describes the LATEST attempt.

All timestamps are ``time.perf_counter()`` seconds (monotonic, never
wall clock); only durations and slacks are exposed.
"""
from __future__ import annotations

import threading
import time

__all__ = ["FlightRecorder", "FlightRecord"]


class FlightRecord:
    """One request's structured forensic record.  Plain attributes +
    ``to_dict()``; mutated only under the owning recorder's lock."""

    __slots__ = (
        "rid", "request_id", "replica",
        "t_submit", "t_admit", "t_first_token", "t_finish",
        "prompt_tokens", "generated_tokens",
        "queue_wait_s", "cache_hit_tokens", "prefill_chunks",
        "spec_rounds", "spec_accepted", "spec_rollback",
        "preemptions", "quarantined",
        "tier_admit", "tier_finish",
        "finish_reason", "deadline_s",
        "slack_admit_s", "slack_first_token_s", "slack_finish_s",
        "ttft_s", "latency_s",
    )

    def __init__(self, rid: int, prompt_tokens: int, t_submit: float):
        self.rid = rid
        self.request_id = None
        self.replica = None
        self.t_submit = t_submit
        self.t_admit = None
        self.t_first_token = None
        self.t_finish = None
        self.prompt_tokens = prompt_tokens
        self.generated_tokens = 0
        self.queue_wait_s = None
        self.cache_hit_tokens = 0
        self.prefill_chunks = 0
        self.spec_rounds = 0
        self.spec_accepted = 0
        self.spec_rollback = 0
        self.preemptions = 0
        self.quarantined = False
        self.tier_admit = None
        self.tier_finish = None
        self.finish_reason = None
        self.deadline_s = None
        self.slack_admit_s = None
        self.slack_first_token_s = None
        self.slack_finish_s = None
        self.ttft_s = None
        self.latency_s = None

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None

    def _slack(self, t: float):
        """Deadline slack at elapsed time ``t - t_submit``: positive
        means budget remained, negative means the phase happened past
        the deadline.  None when the request carried no deadline."""
        if self.deadline_s is None:
            return None
        return round(self.deadline_s - (t - self.t_submit), 6)

    def to_dict(self) -> dict:
        r6 = lambda v: None if v is None else round(v, 6)  # noqa: E731
        return {
            "rid": self.rid,
            "request_id": self.request_id,
            "replica": self.replica,
            "prompt_tokens": self.prompt_tokens,
            "generated_tokens": self.generated_tokens,
            "queue_wait_s": r6(self.queue_wait_s),
            "cache_hit_tokens": self.cache_hit_tokens,
            "prefill_chunks": self.prefill_chunks,
            "spec_rounds": self.spec_rounds,
            "spec_accepted": self.spec_accepted,
            "spec_rollback": self.spec_rollback,
            "preemptions": self.preemptions,
            "quarantined": self.quarantined,
            "tier_admit": self.tier_admit,
            "tier_finish": self.tier_finish,
            "finished": self.finished,
            "finish_reason": self.finish_reason,
            "deadline_s": r6(self.deadline_s),
            "slack_admit_s": r6(self.slack_admit_s),
            "slack_first_token_s": r6(self.slack_first_token_s),
            "slack_finish_s": r6(self.slack_finish_s),
            "ttft_s": r6(self.ttft_s),
            "latency_s": r6(self.latency_s),
        }


class FlightRecorder:
    """Bounded LRU of :class:`FlightRecord`, keyed by engine rid with
    a frontend request-id join index.  Every mutator is a dict lookup
    plus attribute writes under one small lock; a seam called with an
    evicted/unknown rid is a silent no-op (the record was shed, the
    request must not pay for forensics)."""

    def __init__(self, capacity: int = 512):
        self.capacity = max(1, int(capacity))
        self._records: dict = {}          # rid -> record, insertion order
        self._by_request_id: dict = {}    # request_id -> rid
        self.evicted = 0                  # records shed by the LRU bound
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    # -- engine seams -------------------------------------------------------

    def open(self, rid: int, *, prompt_tokens: int,
             t_submit: float | None = None) -> None:
        """One request entered the engine queue (add_request)."""
        rec = FlightRecord(int(rid), int(prompt_tokens),
                           time.perf_counter()
                           if t_submit is None else float(t_submit))
        with self._lock:
            old = self._records.pop(rid, None)   # recovery re-admit
            if old is not None and old.request_id is not None:
                self._by_request_id.pop(old.request_id, None)
            while len(self._records) >= self.capacity:
                oldest = next(iter(self._records))
                victim = self._records.pop(oldest)
                if victim.request_id is not None:
                    self._by_request_id.pop(victim.request_id, None)
                self.evicted += 1
            self._records[rid] = rec

    def admitted(self, rid: int, *, queue_wait_s: float,
                 cache_hit_tokens: int = 0, tier: int = 0) -> None:
        with self._lock:
            rec = self._records.get(rid)
            if rec is None:
                return
            rec.t_admit = rec.t_submit + queue_wait_s
            rec.queue_wait_s = queue_wait_s
            rec.cache_hit_tokens = int(cache_hit_tokens)
            rec.tier_admit = int(tier)
            rec.slack_admit_s = rec._slack(rec.t_admit)

    def annotate(self, rid: int, *, request_id=None, replica=None,
                 deadline_s=None) -> None:
        """Runner-tier join: frontend request id, replica name, and
        the deadline budget (seconds from submit) if any."""
        with self._lock:
            rec = self._records.get(rid)
            if rec is None:
                return
            if request_id is not None:
                rec.request_id = str(request_id)
                self._by_request_id[rec.request_id] = rid
            if replica is not None:
                rec.replica = str(replica)
            if deadline_s is not None:
                rec.deadline_s = float(deadline_s)

    def prefill_chunk(self, rid: int, n_tokens: int) -> None:
        with self._lock:
            rec = self._records.get(rid)
            if rec is not None:
                rec.prefill_chunks += 1

    def first_token(self, rid: int, ttft_s: float) -> None:
        with self._lock:
            rec = self._records.get(rid)
            if rec is None:
                return
            rec.ttft_s = ttft_s
            rec.t_first_token = rec.t_submit + ttft_s
            rec.slack_first_token_s = rec._slack(rec.t_first_token)

    def spec_round(self, rid: int, accepted: int, rollback: int) -> None:
        with self._lock:
            rec = self._records.get(rid)
            if rec is None:
                return
            rec.spec_rounds += 1
            rec.spec_accepted += int(accepted)
            rec.spec_rollback += int(rollback)

    def preempted(self, rid: int) -> None:
        with self._lock:
            rec = self._records.get(rid)
            if rec is not None:
                rec.preemptions += 1

    def finished(self, rid: int, *, reason: str, generated: int,
                 tier: int = 0, quarantined: bool = False) -> None:
        t = time.perf_counter()
        with self._lock:
            rec = self._records.get(rid)
            if rec is None:
                return
            rec.t_finish = t
            rec.finish_reason = str(reason)
            rec.generated_tokens = int(generated)
            rec.tier_finish = int(tier)
            rec.quarantined = bool(quarantined)
            rec.latency_s = t - rec.t_submit
            rec.slack_finish_s = rec._slack(t)

    # -- read surface (frontend /debug/requests) ----------------------------

    def get(self, key) -> dict | None:
        """Record by frontend request id (string) or engine rid."""
        with self._lock:
            rid = self._by_request_id.get(key, key)
            rec = self._records.get(rid)
            return rec.to_dict() if rec is not None else None

    def list(self, *, finished: bool | None = None,
             sort: str = "slowest", limit: int = 32) -> list:
        """Ranked records: ``sort="slowest"`` by total latency (live
        requests rank by elapsed-so-far), ``"recent"`` by insertion."""
        t = time.perf_counter()
        with self._lock:
            recs = list(self._records.values())
        if finished is not None:
            recs = [r for r in recs if r.finished == finished]
        if sort == "slowest":
            recs.sort(key=lambda r: (r.latency_s if r.latency_s is not None
                                     else t - r.t_submit),
                      reverse=True)
        else:
            recs.reverse()                # newest (insertion order) first
        out = []
        for r in recs[:max(0, int(limit))]:
            d = r.to_dict()
            # total latency for finished records, elapsed-so-far for
            # live ones — the cross-replica merge key for "slowest"
            d["elapsed_s"] = round(r.latency_s if r.latency_s is not None
                                   else t - r.t_submit, 6)
            out.append(d)
        return out
