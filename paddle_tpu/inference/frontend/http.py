"""Hand-rolled asyncio HTTP/1.1: exactly what a streaming LLM endpoint
needs, nothing else.

The stdlib's ``http.server`` is thread-per-connection and can't stream
from an asyncio loop; aiohttp/fastapi are not in the image.  A serving
frontend needs a small, auditable subset of HTTP/1.1 — parse a request
(line + headers + Content-Length body), write a response, and stream
Server-Sent Events with chunked transfer-encoding so curl and any
OpenAI-style client can consume token streams over keep-alive
connections.  That subset lives here, over plain
``asyncio.StreamReader/StreamWriter``.

Limits are explicit DoS guards: header lines are capped (asyncio's
readline limit), header count and body size are bounded, and a
malformed request maps to a 400 close rather than an exception escaping
the connection handler.
"""
from __future__ import annotations

from dataclasses import dataclass
from urllib.parse import parse_qs, urlsplit

__all__ = ["HTTPError", "HTTPRequest", "read_request", "response_bytes",
           "SSEWriter", "STATUS_TEXT"]

MAX_HEADERS = 64
MAX_BODY = 4 << 20                    # 4 MiB of JSON prompt is plenty

STATUS_TEXT = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 408: "Request Timeout",
    413: "Payload Too Large", 429: "Too Many Requests",
    500: "Internal Server Error", 503: "Service Unavailable",
}


class HTTPError(Exception):
    """Protocol-level rejection → one response, then close."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = int(status)
        self.message = str(message)


@dataclass
class HTTPRequest:
    method: str
    path: str                          # path only, query stripped
    query: dict                        # parsed query string (first values)
    headers: dict                      # lower-cased names
    body: bytes = b""

    def header(self, name: str, default=None):
        return self.headers.get(name.lower(), default)


async def read_request(reader, *, max_body: int = MAX_BODY):
    """Parse one HTTP/1.1 request from the stream.  Returns None on a
    clean EOF before any bytes (client closed between requests); raises
    HTTPError on a malformed/oversized request."""
    try:
        line = await reader.readline()
    except (ConnectionError, ValueError) as e:
        raise HTTPError(400, f"bad request line: {e}") from e
    if not line:
        return None
    try:
        method, target, version = line.decode("latin-1").split(None, 2)
    except ValueError as e:
        raise HTTPError(400, "malformed request line") from e
    if not version.strip().startswith("HTTP/1."):
        raise HTTPError(400, f"unsupported version {version.strip()!r}")

    headers = {}
    for _ in range(MAX_HEADERS + 1):
        try:
            line = await reader.readline()
        except (ConnectionError, ValueError) as e:
            raise HTTPError(400, f"bad header line: {e}") from e
        if line in (b"\r\n", b"\n", b""):
            break
        if len(headers) >= MAX_HEADERS:
            raise HTTPError(400, "too many headers")
        name, sep, value = line.decode("latin-1").partition(":")
        if not sep:
            raise HTTPError(400, f"malformed header {line[:40]!r}")
        headers[name.strip().lower()] = value.strip()

    body = b""
    if "content-length" in headers:
        try:
            n = int(headers["content-length"])
        except ValueError as e:
            raise HTTPError(400, "bad content-length") from e
        if n < 0 or n > max_body:
            raise HTTPError(413, f"body of {n} bytes > {max_body}")
        if n:
            try:
                body = await reader.readexactly(n)
            except Exception as e:
                raise HTTPError(400, f"truncated body: {e}") from e
    elif headers.get("transfer-encoding", "").lower() == "chunked":
        raise HTTPError(400, "chunked request bodies not supported")

    parts = urlsplit(target)
    query = {k: v[0] for k, v in parse_qs(parts.query).items()}
    return HTTPRequest(method=method.upper(), path=parts.path or "/",
                       query=query, headers=headers, body=body)


def response_bytes(status: int, body: bytes, *,
                   content_type: str = "application/json",
                   extra_headers: dict | None = None,
                   keep_alive: bool = True) -> bytes:
    """One complete non-streaming response, Content-Length framed."""
    reason = STATUS_TEXT.get(status, "Unknown")
    lines = [f"HTTP/1.1 {status} {reason}",
             f"Content-Type: {content_type}",
             f"Content-Length: {len(body)}",
             f"Connection: {'keep-alive' if keep_alive else 'close'}"]
    for k, v in (extra_headers or {}).items():
        lines.append(f"{k}: {v}")
    return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body


class SSEWriter:
    """Server-Sent Events over chunked transfer-encoding.

    ``start()`` commits the 200 + streaming headers; each ``event(data)``
    is one ``data: ...\\n\\n`` frame in its own HTTP chunk (flushed —
    token latency IS the product); ``done()`` sends the OpenAI-style
    ``data: [DONE]`` sentinel and the zero-length terminal chunk, which
    keeps the connection reusable.  Write failures surface as
    ConnectionError so the route handler can abort the request.
    """

    def __init__(self, writer):
        self._w = writer
        self.started = False

    async def start(self) -> None:
        self._w.write(b"HTTP/1.1 200 OK\r\n"
                      b"Content-Type: text/event-stream\r\n"
                      b"Cache-Control: no-cache\r\n"
                      b"Connection: keep-alive\r\n"
                      b"Transfer-Encoding: chunked\r\n\r\n")
        await self._w.drain()
        self.started = True

    async def _chunk(self, payload: bytes) -> None:
        self._w.write(f"{len(payload):x}\r\n".encode("latin-1")
                      + payload + b"\r\n")
        await self._w.drain()

    async def event(self, data: str) -> None:
        await self._chunk(f"data: {data}\n\n".encode("utf-8"))

    async def done(self) -> None:
        await self.event("[DONE]")
        self._w.write(b"0\r\n\r\n")
        await self._w.drain()
