"""ReplicaRouter: load-aware request routing over D engine replicas.

One ``LLMEngine`` (even TP-sharded) is one continuous batch; scaling a
serving deployment past one batch means DATA parallelism — D independent
engine replicas, each with its own ``EngineRunner`` thread, its own page
pool, and its own prefix cache.  The router is the seam: it presents the
EngineRunner surface the asyncio frontend already speaks (submit / abort
/ inflight / draining / drain / abort_all / close), so
``ServingFrontend`` and the CLI's drain path work unchanged whether
``self.runner`` is one runner or this fan-out.

Routing policies (``policy=``):

    least      least-outstanding-tokens: each replica's load is the sum
               of ``len(prompt) + max_new_tokens`` over its unfinished
               requests (the page/compute cost a request can still
               incur); ties break to the LOWEST replica index, so a
               drained fleet fills deterministically.
    affinity   (default) prefix-affinity first, least-outstanding as the
               fallback: the incoming prompt is chain-hashed page by
               page with the SAME rolling hash ``BlockManager`` uses
               (kv_cache.prefix_chain_hashes), and each replica keeps a
               bounded registry of the page hashes routed to it.  The
               replica matching the LONGEST leading run of the prompt's
               page hashes already holds those pages in its prefix
               cache — landing there turns the prompt's shared prefix
               into cache hits instead of recomputed prefill.  No match
               anywhere -> least-outstanding.
    random     seeded uniform choice — the control arm serve_bench's
               router A/B measures against.

The router tracks affinity with its OWN per-replica hash registries
rather than reading engine pool state: ``BlockManager`` belongs to the
engine thread and is lock-free by design, so the router predicts cache
residency from what it routed (an upper bound that decays with
evictions — the registry is LRU-capped to stay honest about recency).
Outstanding-token accounting is exact: credited at submit, released by a
wrapped ``deliver`` when the terminal ("finish", out) event passes
through.

Per-replica counters (``router_counters()``): ``outstanding_tokens``,
``routed_requests``, ``affinity_hits`` — surfaced as labeled gauges on
``/metrics`` and in ``serve_bench --replicas`` records.
"""
from __future__ import annotations

import random
import threading
from collections import OrderedDict

from ...analysis.lock_check import install as _install_lock_check
from ..kv_cache import prefix_chain_hashes
from ..policy import pick_replica
from .runner import EngineRunner

__all__ = ["ReplicaRouter", "build_replicas"]

_POLICIES = ("affinity", "least", "random")


@_install_lock_check
class ReplicaRouter:
    """EngineRunner-shaped facade over D replica runners.

    Parameters
    ----------
    runners: list of started-or-startable ``EngineRunner``s, one per
        replica, each constructed with ``name="r{i}"`` matching its
        index (request ids then self-describe their owner: "r2-req-5").
    policy: "affinity" (default) | "least" | "random".
    registry_cap: per-replica bound on remembered page hashes (LRU) —
        keeps the affinity memory aligned with what a replica's pool
        can actually still hold.
    seed: RNG seed for the random policy (deterministic benches).
    """

    def __init__(self, runners, *, policy: str = "affinity",
                 registry_cap: int = 8192, seed: int = 0, tracer=None):
        if not runners:
            raise ValueError("need at least one replica runner")
        if policy not in _POLICIES:
            raise ValueError(
                f"policy must be one of {_POLICIES}, got {policy!r}")
        for i, r in enumerate(runners):
            if r.name != f"r{i}":
                raise ValueError(
                    f"runner {i} must be named 'r{i}' (got {r.name!r}) "
                    "so request ids route aborts back to it")
        self.runners = list(runners)
        self.policy = policy
        self.registry_cap = int(registry_cap)
        self._rng = random.Random(0xB10C ^ int(seed))
        self._lock = threading.Lock()
        n = len(self.runners)
        self._outstanding = [0] * n       # tokens credited, not yet done
        self._routed = [0] * n            # requests landed per replica
        self._affinity_hits = [0] * n     # routed by a registry match
        # per-replica LRU of page chain hashes routed there
        self._registry = [OrderedDict() for _ in range(n)]
        self._block_size = self.runners[0].engine.block_size
        # step-timeline hook: pick latency + affinity outcome per route
        self.tracer = tracer
        self._trace_track = tracer.register("router") \
            if tracer is not None else "router"

    # ------------------------------------------------------------------
    # EngineRunner surface
    # ------------------------------------------------------------------

    @property
    def engine(self):
        """Replica 0's live engine — the representative the frontend
        reads config/pressure/fault surfaces from.  Per-replica engines
        are reachable via ``engines``."""
        return self.runners[0].engine

    @property
    def engines(self) -> list:
        return [r.engine for r in self.runners]

    @property
    def max_pending(self) -> int:
        return sum(r.max_pending for r in self.runners)

    @property
    def draining(self) -> bool:
        return any(r.draining for r in self.runners)

    @property
    def restarts(self) -> int:
        return sum(r.restarts for r in self.runners)

    def start(self) -> "ReplicaRouter":
        for r in self.runners:
            r.start()
        return self

    def submit(self, prompt, *, deliver, deadline_s: float | None = None,
               **params) -> str:
        """Route one request to a replica and submit it there.  The
        terminal event passing through ``deliver`` releases the
        replica's outstanding-token credit.  Raises whatever the chosen
        replica's submit raises (RunnerSaturated / RunnerDraining)."""
        toks = [int(t) for t in prompt]
        cost = len(toks) + int(params.get("max_new_tokens", 32))
        hashes = prefix_chain_hashes(toks, self._block_size) \
            if self.policy == "affinity" else []
        tr = self.tracer
        with self._lock:
            t_pick = tr.now() if tr is not None else 0
            idx, hit = self._pick(hashes)
            if tr is not None:
                tr.complete("router.pick", t_pick, track=self._trace_track,
                            args={"replica": idx, "policy": self.policy,
                                  "prefix_pages": len(hashes)})
            # credit BEFORE the replica's submit: the engine thread can
            # deliver the terminal event (and settle) before submit
            # returns, and later _pick calls must see this request's
            # load either way
            self._outstanding[idx] += cost
            self._routed[idx] += 1
            if hit:
                self._affinity_hits[idx] += 1
            reg = self._registry[idx]
            for h in hashes:
                reg.pop(h, None)              # refresh recency
                reg[h] = None
            while len(reg) > self.registry_cap:
                reg.popitem(last=False)

        if hit:
            # a registry match means this replica served the prefix
            # before — if pressure has since spilled those pages to its
            # host tier, the hint lets the engine pre-stage them at the
            # next step boundary, ahead of this request's admission
            hint = getattr(self.runners[idx].engine, "prefetch_hint", None)
            if hint is not None:
                hint(hashes)

        settled = [False]

        def deliver_wrapped(ev, _deliver=deliver):
            # runners deliver exactly one terminal event per request
            # (generation-guarded), so this one-shot is belt-and-braces
            if ev[0] == "finish" and not settled[0]:
                settled[0] = True
                with self._lock:
                    self._outstanding[idx] -= cost
            _deliver(ev)

        try:
            rid = self.runners[idx].submit(
                toks, deliver=deliver_wrapped, deadline_s=deadline_s,
                **params)
            if tr is not None:
                tr.instant(
                    "router.affinity_hit" if hit
                    else "router.affinity_miss",
                    track=self._trace_track,
                    args={"replica": idx, "request_id": rid})
                tr.instant("router.routed", track=self._trace_track,
                           args={"replica": idx, "request_id": rid,
                                 "cost_tokens": cost})
            return rid
        except Exception:
            with self._lock:
                self._outstanding[idx] -= cost
                self._routed[idx] -= 1
                if hit:
                    self._affinity_hits[idx] -= 1
            raise

    def abort(self, request_id: str, reason: str = "aborted") -> None:
        idx = self._owner(request_id)
        if idx is not None:
            self.runners[idx].abort(request_id, reason)

    def inflight(self) -> int:
        return sum(r.inflight() for r in self.runners)

    def drain(self, timeout_s: float | None = None) -> bool:
        """Drain every replica concurrently (each runner's drain is a
        blocking wait; serializing them would stack timeouts)."""
        results = [False] * len(self.runners)

        def one(i, r):
            results[i] = r.drain(timeout_s=timeout_s)

        threads = [threading.Thread(target=one, args=(i, r), daemon=True)
                   for i, r in enumerate(self.runners)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return all(results)

    def abort_all(self, reason: str = "shutdown") -> int:
        return sum(r.abort_all(reason) for r in self.runners)

    def close(self, *, abort_inflight: bool = True) -> None:
        threads = [threading.Thread(
            target=r.close, kwargs={"abort_inflight": abort_inflight},
            daemon=True) for r in self.runners]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    # ------------------------------------------------------------------
    # routing internals
    # ------------------------------------------------------------------

    def _pick(self, hashes) -> tuple:  # guarded-by: _lock
        """(replica index, was-affinity-hit).  Caller holds the lock.
        The decision itself is ``policy.pick_replica`` — pure, shared
        with the fleet simulator so simulated routing uses the SAME
        leading-run/tie-break semantics as the live router."""
        return pick_replica(self.policy, hashes, self._registry,
                            self._outstanding, rng=self._rng)

    def _owner(self, request_id: str):
        """Replica index encoded in the id ("r3-req-7" -> 3)."""
        if request_id.startswith("r"):
            head = request_id.split("-", 1)[0][1:]
            if head.isdigit() and int(head) < len(self.runners):
                return int(head)
        return None

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def router_counters(self) -> dict:
        with self._lock:
            return {
                "policy": self.policy,
                "replicas": len(self.runners),
                "outstanding_tokens": list(self._outstanding),
                "routed_requests": list(self._routed),
                "affinity_hits": list(self._affinity_hits),
                "affinity_hit_total": sum(self._affinity_hits),
                "routed_total": sum(self._routed),
            }

    def affinity_hit_rate(self) -> float:
        with self._lock:
            total = sum(self._routed)
            return sum(self._affinity_hits) / total if total else 0.0

    def load_imbalance(self) -> float:
        """max/mean outstanding tokens across replicas (1.0 = perfectly
        even; 0.0 when the fleet is idle)."""
        with self._lock:
            vals = list(self._outstanding)
        mean = sum(vals) / len(vals)
        return max(vals) / mean if mean > 0 else 0.0

    def stats_snapshot(self) -> dict:
        """Aggregated ServingStats snapshot across every replica.
        Snapshots carry their reservoir samples so the fleet's latency
        percentiles are recomputed over the pooled union rather than
        reported as a max-of-quantiles bound."""
        from ...profiler import ServingStats
        return ServingStats.aggregate(
            [r.engine.stats.snapshot(include_samples=True)
             for r in self.runners])


def build_replicas(engine, engine_factory, n: int, *,
                   max_pending: int | None = None,
                   step_deadline_s: float | None = None) -> list:
    """Construct n replica runners: replica 0 wraps ``engine`` (the one
    the caller already built), replicas 1..n-1 come fresh from
    ``engine_factory`` — the same factory contract supervised recovery
    uses, so every replica shares model weights and recovery works per
    replica."""
    if n > 1 and engine_factory is None:
        raise ValueError(
            f"replicas={n} needs an engine_factory to build the extra "
            "engine replicas")
    engines = [engine] + [engine_factory() for _ in range(n - 1)]
    return [EngineRunner(e, max_pending=max_pending,
                         engine_factory=engine_factory,
                         step_deadline_s=step_deadline_s, name=f"r{i}")
            for i, e in enumerate(engines)]
