"""ServingFrontend: the asyncio HTTP tier over one EngineRunner.

These routes own the whole serving surface:

    POST /v1/completions       generate (JSON body; SSE stream or one JSON)
    GET  /healthz              liveness + drain state
    GET  /metrics              Prometheus text (ServingStats + pool gauges)
    GET  /slo                  windowed percentiles + SLO burn-rate state
    GET  /debug/requests       flight-recorder list (?finished=&sort=&limit=)
    GET  /debug/requests/<id>  one request's flight record
    GET  /debug/trace          Chrome trace JSON (404 unless tracing on)

The request lifecycle the frontend guarantees, end to end:

    queued ──▶ prefilling ──▶ running ──▶ finished
      │            │             │
      └────────────┴─────────────┴─────▶ aborted   (disconnect, deadline,
      │                                             shutdown)
      └▶ shed (429)                      — admission queue full

* Backpressure: the runner bounds submitted-but-unfinished work; past
  the bound a request is SHED with 429 before it costs any engine state.
  While draining, new work gets 503.
* Deadlines: ``deadline_ms`` in the body (or the server-wide default)
  covers queue wait AND generation; the runner's stepping thread aborts
  expired requests with finish_reason "deadline" — the stream still gets
  its terminal frame.
* Disconnects: while streaming, the handler watches the socket for EOF
  concurrently with the token queue; a client that goes away mid-stream
  aborts its request in the engine, which retires the sequence and
  releases its KV pages at the next step boundary.
* Drain: ``shutdown()`` stops admissions (503), lets in-flight streams
  run to completion (or their deadlines), then stops the engine thread
  and closes lingering keep-alive sockets.

Token flow: the engine thread calls each request's deliver closure,
which trampolines events onto the asyncio loop via
``loop.call_soon_threadsafe`` into a per-request asyncio.Queue; the
route coroutine consumes the queue and writes SSE frames.  The HTTP
thread never touches engine state directly — snapshots and pool gauges
are the only cross-thread reads, and those surfaces lock internally.
"""
from __future__ import annotations

import asyncio
import json
import math
import threading

from .http import (HTTPError, SSEWriter, read_request, response_bytes)
from .metrics import render_metrics
from .protocol import (ProtocolError, completion_response, error_body,
                       parse_completion_request, stream_finish_frame,
                       stream_token_frame)
from .router import ReplicaRouter, build_replicas
from .runner import EngineRunner, RunnerDraining, RunnerSaturated

__all__ = ["ServingFrontend", "BackgroundServer", "serve_background"]

_ABORT_REASONS = ("aborted", "deadline", "shutdown")


class ServingFrontend:
    """One engine, one runner, one asyncio server.

    Parameters
    ----------
    engine: LLMEngine (build with ``retain_outputs=False`` for a
        long-running server; ``__main__`` does).
    model_name: echoed in response bodies as ``model``.
    host/port: bind address; port 0 picks a free port (``self.port``
        holds the real one after ``start()``).
    max_pending: admission bound forwarded to EngineRunner (per replica
        when ``replicas > 1``).
    default_deadline_s: applied when a request carries no deadline_ms;
        None means no deadline.
    engine_factory/step_deadline_s: forwarded to EngineRunner; together
        they arm the supervised-recovery watchdog (see runner docs).
    replicas: data-parallel engine replicas behind one listener.  1 (the
        default) keeps the single EngineRunner.  D > 1 builds D engines
        — the passed ``engine`` plus D-1 from ``engine_factory`` (then
        REQUIRED) — each with its own stepping thread, and routes
        requests across them with a ReplicaRouter; ``self.runner`` keeps
        the same surface either way.
    router_policy: "affinity" (default) | "least" | "random" — see
        router.py.  Ignored when replicas == 1.
    tracer: optional ``profiler.Tracer`` for the step timeline; falls
        back to the engine's own tracer so one ``set_tracer()`` on the
        engine lights up all four tiers.  When set, ``GET /debug/trace``
        serves the Chrome trace-event JSON.
    slo_config: optional ``profiler.SLOConfig`` (or dict of its fields)
        evaluated by the windowed-telemetry layer; None uses defaults.
        The frontend always enables windowed telemetry on its engines —
        ``GET /slo`` serves the rolling percentiles and burn-rate state.
    flight_capacity: per-replica flight-recorder bound (records kept for
        ``GET /debug/requests``); 0 disables the recorder entirely and
        the debug routes 404.
    anomaly_spool: directory for anomaly-triggered trace captures.  When
        set, slow-step/slow-request outliers snapshot the trace window
        plus the slowest flight records to bounded JSON files there; if
        no tracer was passed a small always-on ring is armed so there is
        a window to snapshot.
    """

    def __init__(self, engine, *, model_name: str = "model",
                 host: str = "127.0.0.1", port: int = 8000,
                 max_pending: int | None = None,
                 default_deadline_s: float | None = None,
                 engine_factory=None, step_deadline_s: float | None = None,
                 replicas: int = 1, router_policy: str = "affinity",
                 tracer=None, slo_config=None, flight_capacity: int = 512,
                 anomaly_spool: str | None = None):
        self.model_name = str(model_name)
        self.host = host
        self.port = int(port)
        self.default_deadline_s = default_deadline_s
        self.tracer = tracer if tracer is not None \
            else getattr(engine, "tracer", None)
        if anomaly_spool is not None and self.tracer is None:
            # anomaly capture needs a window to snapshot: arm a small
            # always-on ring (bounded; evicts itself) when the operator
            # asked for a spool but not for full tracing
            from ...profiler.trace import Tracer
            self.tracer = Tracer(capacity=4096)
        self._http_track = self.tracer.register("http") \
            if self.tracer is not None else "http"
        if int(replicas) > 1:
            self.runner = ReplicaRouter(
                build_replicas(engine, engine_factory, int(replicas),
                               max_pending=max_pending,
                               step_deadline_s=step_deadline_s),
                policy=router_policy, tracer=self.tracer)
        else:
            self.runner = EngineRunner(engine, max_pending=max_pending,
                                       engine_factory=engine_factory,
                                       step_deadline_s=step_deadline_s)
        if self.tracer is not None:
            # every replica engine records onto the SAME ring so one
            # trace shows a request crossing http -> router -> runner ->
            # engine with correlated ids
            for e in getattr(self.runner, "engines", [self.runner.engine]):
                if getattr(e, "tracer", None) is None:
                    e.set_tracer(self.tracer)
        # SLO observatory: windowed telemetry on every replica engine
        # (the per-engine ``enable_windows`` is what makes /slo render),
        # a bounded flight recorder per replica, and — when a spool
        # directory is given — anomaly-triggered trace capture.
        self.anomaly_spool = None
        if anomaly_spool is not None:
            from ...profiler.slo import AnomalySpool
            self.anomaly_spool = AnomalySpool(anomaly_spool)
        for e in getattr(self.runner, "engines", [self.runner.engine]):
            e.stats.enable_windows(slo_config, tracer=self.tracer)
            if int(flight_capacity) > 0 and getattr(e, "flight", None) is None:
                from ..flight import FlightRecorder
                e.set_flight(FlightRecorder(int(flight_capacity)))
            if self.anomaly_spool is not None:
                e.stats.windows.arm_anomaly(
                    spool=self.anomaly_spool, tracer=self.tracer,
                    flight=getattr(e, "flight", None))
        self._server = None
        self._writers: set = set()        # open connections, for shutdown
        self._lock = threading.Lock()
        self._closing = False
        # frontend-owned counters for /metrics
        self._requests_total: dict = {}   # (route, code) -> n
        self._shed_total = 0
        self._active_streams = 0

    @property
    def engine(self):
        # always the LIVE engine: supervised recovery may have replaced
        # the one this frontend was constructed with
        return self.runner.engine

    def _retry_after(self) -> str:
        """Retry-After seconds for 429s, from the live free-page trend
        when a DegradationController is attached (else a flat 1)."""
        pressure = getattr(self.engine, "pressure", None)
        if pressure is None:
            return "1"
        try:
            return str(max(1, int(math.ceil(pressure.retry_after_s()))))
        except Exception:
            return "1"

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        self.runner.start()
        self._server = await asyncio.start_server(
            self._handle_conn, host=self.host, port=self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._server.serve_forever()

    async def shutdown(self, *, drain_timeout_s: float = 30.0,
                       abort_inflight: bool = False) -> bool:
        """Graceful drain: refuse new work, finish what's running, stop.
        With ``abort_inflight`` every running request is aborted (reason
        "shutdown") instead of finished — the impatient variant.  True
        when the engine drained fully inside the timeout."""
        self._closing = True
        if self._server is not None:
            self._server.close()          # stop accepting sockets
        loop = asyncio.get_running_loop()
        if abort_inflight:
            drained = await loop.run_in_executor(
                None, lambda: (self.runner.close(abort_inflight=True), True)[1])
        else:
            drained = await loop.run_in_executor(
                None, lambda: self.runner.drain(timeout_s=drain_timeout_s))
        # in-flight streams have now written their terminal frames; close
        # whatever keep-alive sockets are still parked in read_request
        with self._lock:
            writers = list(self._writers)
        for w in writers:
            try:
                w.close()
            except Exception:
                pass
        if self._server is not None:
            await self._server.wait_closed()
        return drained

    # ------------------------------------------------------------------
    # connection handling
    # ------------------------------------------------------------------

    def _count(self, route: str, code: int) -> None:
        with self._lock:
            key = (route, int(code))
            self._requests_total[key] = self._requests_total.get(key, 0) + 1

    async def _handle_conn(self, reader, writer) -> None:
        with self._lock:
            self._writers.add(writer)
        try:
            while not self._closing:
                try:
                    req = await read_request(reader)
                except HTTPError as e:
                    self._count("bad", e.status)
                    writer.write(response_bytes(
                        e.status, error_body(e.status, e.message),
                        keep_alive=False))
                    await writer.drain()
                    return
                if req is None:
                    return                # clean EOF between requests
                keep = await self._dispatch(req, reader, writer)
                if not keep:
                    return
        except (ConnectionError, asyncio.IncompleteReadError):
            pass                          # client went away; nothing to do
        finally:
            with self._lock:
                self._writers.discard(writer)
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _dispatch(self, req, reader, writer) -> bool:
        """Route one request.  Returns False to close the connection."""
        route = (req.method, req.path)
        if route == ("POST", "/v1/completions"):
            return await self._completions(req, reader, writer)
        if route == ("GET", "/healthz"):
            body = (b'{"status": "draining"}'
                    if self._closing or self.runner.draining
                    else b'{"status": "ok"}')
            self._count("/healthz", 200)
            writer.write(response_bytes(200, body))
            await writer.drain()
            return True
        if route == ("GET", "/metrics"):
            # a ReplicaRouter aggregates stats across its fleet and adds
            # per-replica routing gauges; a plain runner reads one engine
            if hasattr(self.runner, "stats_snapshot"):
                snap = self.runner.stats_snapshot()
                router = self.runner.router_counters()
            else:
                snap = self.engine.stats.snapshot()
                router = None
            text = render_metrics(
                snap, engine=self.engine,
                frontend=self._frontend_counters(), router=router)
            self._count("/metrics", 200)
            writer.write(response_bytes(
                200, text.encode("utf-8"),
                content_type="text/plain; version=0.0.4; charset=utf-8"))
            await writer.drain()
            return True
        if route == ("GET", "/slo"):
            # same snapshot surface as /metrics: fleet-pooled when a
            # router is in front, single-engine otherwise
            if hasattr(self.runner, "stats_snapshot"):
                snap = self.runner.stats_snapshot()
            else:
                snap = self.engine.stats.snapshot()
            if "windows" not in snap:
                self._count("/slo", 404)
                writer.write(response_bytes(404, error_body(
                    404, "windowed telemetry is not enabled")))
                await writer.drain()
                return True
            out = {k: snap.get(k) for k in (
                "slo_state", "slo_state_name", "ttft_p95_w60s",
                "itl_p99_w60s", "queue_wait_p95_w60s",
                "anomalies_detected", "anomalies_captured",
                "anomaly_spool_dropped")}
            out["slo"] = snap.get("slo")
            out["windows"] = snap["windows"]
            self._count("/slo", 200)
            writer.write(response_bytes(
                200, json.dumps(out).encode("utf-8"),
                content_type="application/json"))
            await writer.drain()
            return True
        if req.method == "GET" and (req.path == "/debug/requests"
                                    or req.path.startswith(
                                        "/debug/requests/")):
            return await self._debug_requests(req, writer)
        if route == ("GET", "/debug/trace"):
            tr = self.tracer
            if tr is None:
                self._count("/debug/trace", 404)
                writer.write(response_bytes(404, error_body(
                    404, "tracing is not enabled on this server")))
                await writer.drain()
                return True
            body = json.dumps(tr.chrome_trace()).encode("utf-8")
            self._count("/debug/trace", 200)
            writer.write(response_bytes(
                200, body, content_type="application/json"))
            await writer.drain()
            return True
        status = 405 if req.path in ("/v1/completions", "/healthz",
                                     "/metrics", "/debug/trace", "/slo",
                                     "/debug/requests") else 404
        self._count(req.path, status)
        writer.write(response_bytes(
            status, error_body(status, f"no route {req.method} {req.path}"),
            keep_alive=False))
        await writer.drain()
        return False

    def _flight_recorders(self) -> list:
        return [fl for fl in (
            getattr(e, "flight", None)
            for e in getattr(self.runner, "engines", [self.runner.engine]))
            if fl is not None]

    async def _debug_requests(self, req, writer) -> bool:
        """GET /debug/requests (ranked list) and /debug/requests/<id>
        (one flight record).  404 when flight recording is disabled."""
        recorders = self._flight_recorders()
        if not recorders:
            self._count("/debug/requests", 404)
            writer.write(response_bytes(404, error_body(
                404, "flight recording is not enabled")))
            await writer.drain()
            return True
        rest = req.path[len("/debug/requests"):].strip("/")
        if rest:                          # one record, by frontend id
            rec = None
            for fl in recorders:
                rec = fl.get(rest)
                if rec is None and rest.isdigit():
                    rec = fl.get(int(rest))   # raw engine rid fallback
                if rec is not None:
                    break
            if rec is None:
                self._count("/debug/requests", 404)
                writer.write(response_bytes(404, error_body(
                    404, f"no flight record for {rest!r} (evicted or "
                    "never admitted)")))
                await writer.drain()
                return True
            self._count("/debug/requests", 200)
            writer.write(response_bytes(
                200, json.dumps(rec).encode("utf-8"),
                content_type="application/json"))
            await writer.drain()
            return True
        fq = req.query.get("finished")
        sort = req.query.get("sort", "slowest")
        finished = None
        if fq in ("true", "1", "yes"):
            finished = True
        elif fq in ("false", "0", "no"):
            finished = False
        elif fq == "slowest":             # ?finished=slowest shorthand
            finished, sort = True, "slowest"
        try:
            limit = max(1, min(512, int(req.query.get("limit", 32))))
        except ValueError:
            limit = 32
        merged: list = []
        for fl in recorders:
            merged.extend(fl.list(finished=finished, sort=sort,
                                  limit=limit))
        if sort == "slowest":             # re-rank across replicas
            merged.sort(key=lambda r: r.get("elapsed_s") or 0.0,
                        reverse=True)
        merged = merged[:limit]
        body = {"count": len(merged),
                "evicted": sum(fl.evicted for fl in recorders),
                "requests": merged}
        self._count("/debug/requests", 200)
        writer.write(response_bytes(
            200, json.dumps(body).encode("utf-8"),
            content_type="application/json"))
        await writer.drain()
        return True

    def _frontend_counters(self) -> dict:
        with self._lock:
            return {
                "requests_total": dict(self._requests_total),
                "shed_total": self._shed_total,
                "active_streams": self._active_streams,
                "queue_depth": self.runner.inflight(),
                "draining": self._closing or self.runner.draining,
            }

    # ------------------------------------------------------------------
    # POST /v1/completions
    # ------------------------------------------------------------------

    async def _completions(self, req, reader, writer) -> bool:
        route = "/v1/completions"
        # stackless now()/complete() here and below: an asyncio handler
        # must never hold a span() across an await (coroutines interleave
        # on one thread and would corrupt the per-thread span stack)
        tr = self.tracer
        try:
            t_parse = tr.now() if tr is not None else 0
            kwargs, stream, deadline_ms = parse_completion_request(req.body)
            if tr is not None:
                tr.complete("http.parse", t_parse, track=self._http_track,
                            args={"bytes": len(req.body or b"")})
        except ProtocolError as e:
            self._count(route, 400)
            writer.write(response_bytes(400, error_body(400, str(e))))
            await writer.drain()
            return True

        pressure = getattr(self.engine, "pressure", None)
        if pressure is not None and pressure.admission_paused:
            # graceful degradation: shed before the request costs any
            # runner/engine state; Retry-After from the free-page trend
            with self._lock:
                self._shed_total += 1
            self._count(route, 429)
            writer.write(response_bytes(
                429, error_body(429, "admission paused under memory "
                                "pressure", kind="overloaded"),
                extra_headers={"Retry-After": self._retry_after()}))
            await writer.drain()
            return True

        deadline_s = (deadline_ms / 1e3 if deadline_ms is not None
                      else self.default_deadline_s)
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def deliver(ev, _loop=loop, _q=q):
            # engine thread -> event loop; a loop torn down mid-flight
            # (server stopped) must not kill the engine thread
            try:
                _loop.call_soon_threadsafe(_q.put_nowait, ev)
            except RuntimeError:
                pass

        prompt = kwargs.pop("prompt")
        try:
            request_id = self.runner.submit(
                prompt, deliver=deliver, deadline_s=deadline_s, **kwargs)
        except RunnerSaturated as e:
            with self._lock:
                self._shed_total += 1
            self._count(route, 429)
            writer.write(response_bytes(
                429, error_body(429, str(e), kind="overloaded"),
                extra_headers={"Retry-After": self._retry_after()}))
            await writer.drain()
            return True
        except RunnerDraining as e:
            self._count(route, 503)
            writer.write(response_bytes(
                503, error_body(503, str(e), kind="shutting_down"),
                keep_alive=False))
            await writer.drain()
            return False

        if tr is not None:
            tr.instant("http.request", track=self._http_track,
                       args={"request_id": request_id, "stream": stream})
        if stream:
            plan = getattr(self.engine, "fault_plan", None)
            inject_drop = plan is not None and plan.take_conn_drop()
            return await self._stream_response(
                request_id, q, reader, writer, inject_drop=inject_drop)
        return await self._unary_response(request_id, q, reader, writer)

    @staticmethod
    async def _reap(task) -> None:
        """Cancel a pending read/get task and WAIT for it to unwind —
        returning to the keep-alive loop while a cancelled read is still
        registered on the stream trips asyncio's one-reader guard."""
        if task is None or task.done():
            return
        task.cancel()
        try:
            await task
        except (asyncio.CancelledError, Exception):
            pass

    async def _watch_eof(self, reader):
        """Resolves when the client half-closes or drops the socket.
        Pipelined garbage before EOF also lands here — treating it as a
        disconnect is the safe reading for a streaming endpoint."""
        try:
            await reader.read(1)
        except Exception:
            pass

    async def _stream_response(self, request_id, q, reader, writer,
                               inject_drop: bool = False) -> bool:
        route = "/v1/completions"
        tr = self.tracer
        sse = SSEWriter(writer)
        with self._lock:
            self._active_streams += 1
        eof = asyncio.ensure_future(self._watch_eof(reader))
        getter = None
        try:
            await sse.start()
            self._count(route, 200)
            while True:
                if q.empty():
                    getter = asyncio.ensure_future(q.get())
                    done, _ = await asyncio.wait(
                        {getter, eof}, return_when=asyncio.FIRST_COMPLETED)
                    if getter not in done:
                        await self._reap(getter)
                        self.runner.abort(request_id, reason="aborted")
                        return False      # socket is gone; just close
                    kind, payload = getter.result()
                else:
                    kind, payload = q.get_nowait()
                if kind == "token":
                    t_w = tr.now() if tr is not None else 0
                    await sse.event(stream_token_frame(
                        request_id, self.model_name, payload))
                    if tr is not None:
                        tr.complete("http.sse_write", t_w,
                                    track=self._http_track,
                                    args={"request_id": request_id,
                                          "kind": "token"})
                    if inject_drop:
                        # injected mid-stream disconnect: behave exactly
                        # like the client vanished after this frame
                        self.engine.stats.record_fault("conn")
                        self.runner.abort(request_id, reason="aborted")
                        return False
                else:
                    t_w = tr.now() if tr is not None else 0
                    await sse.event(stream_finish_frame(
                        request_id, self.model_name, payload))
                    await sse.done()
                    if tr is not None:
                        tr.complete("http.sse_write", t_w,
                                    track=self._http_track,
                                    args={"request_id": request_id,
                                          "kind": "finish"})
                    return True
        except (ConnectionError, asyncio.IncompleteReadError):
            self.runner.abort(request_id, reason="aborted")
            return False
        finally:
            await self._reap(eof)
            await self._reap(getter)
            with self._lock:
                self._active_streams -= 1

    async def _unary_response(self, request_id, q, reader, writer) -> bool:
        route = "/v1/completions"
        eof = asyncio.ensure_future(self._watch_eof(reader))
        getter = None
        try:
            while True:
                if q.empty():
                    getter = asyncio.ensure_future(q.get())
                    done, _ = await asyncio.wait(
                        {getter, eof}, return_when=asyncio.FIRST_COMPLETED)
                    if getter not in done:
                        await self._reap(getter)
                        self.runner.abort(request_id, reason="aborted")
                        return False
                    kind, payload = getter.result()
                else:
                    kind, payload = q.get_nowait()
                if kind != "finish":
                    continue              # tokens accumulate engine-side
                self._count(route, 200)
                writer.write(response_bytes(200, completion_response(
                    request_id, self.model_name, payload)))
                await writer.drain()
                return True
        except (ConnectionError, asyncio.IncompleteReadError):
            self.runner.abort(request_id, reason="aborted")
            return False
        finally:
            await self._reap(eof)
            await self._reap(getter)


# ----------------------------------------------------------------------
# background server: the handle tests and serve_bench drive
# ----------------------------------------------------------------------

class BackgroundServer:
    """A ServingFrontend running its own event loop in a daemon thread.

    ``port`` is live after construction returns; ``stop()`` performs the
    graceful drain and joins the thread.  Usable as a context manager.
    """

    def __init__(self, frontend: ServingFrontend):
        self.frontend = frontend
        self.port = None
        self._ready = threading.Event()
        self._stop_ev = None              # asyncio.Event on the loop
        self._loop = None
        self._error = None
        self._stop_kwargs = {}
        self.drained = None
        self._thread = threading.Thread(target=self._run, name="llm-http",
                                        daemon=True)
        self._thread.start()
        self._ready.wait(timeout=30.0)
        if self._error is not None:
            raise self._error

    def _run(self) -> None:
        async def main():
            self._loop = asyncio.get_running_loop()
            self._stop_ev = asyncio.Event()
            try:
                await self.frontend.start()
                self.port = self.frontend.port
            except Exception as e:
                self._error = e
                self._ready.set()
                return
            self._ready.set()
            await self._stop_ev.wait()
            self.drained = await self.frontend.shutdown(**self._stop_kwargs)
        asyncio.run(main())

    def stop(self, *, drain_timeout_s: float = 30.0,
             abort_inflight: bool = False):
        """Drain + stop; returns whether the drain completed cleanly."""
        if self._loop is not None and self._thread.is_alive():
            self._stop_kwargs = {"drain_timeout_s": drain_timeout_s,
                                 "abort_inflight": abort_inflight}
            self._loop.call_soon_threadsafe(self._stop_ev.set)
        self._thread.join(timeout=drain_timeout_s + 30.0)
        return self.drained

    def __enter__(self) -> "BackgroundServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def serve_background(engine, **frontend_kwargs) -> BackgroundServer:
    """Spin up a frontend on a free localhost port in a background
    thread.  The one-liner tests and serve_bench use:

        srv = serve_background(engine, model_name="tiny")
        ... http.client against 127.0.0.1:srv.port ...
        srv.stop()
    """
    frontend_kwargs.setdefault("port", 0)
    return BackgroundServer(ServingFrontend(engine, **frontend_kwargs))
