"""HTTP serving frontend for the LLM engine (stdlib-only).

The package that turns ``LLMEngine`` into a server:

- ``app.ServingFrontend`` — asyncio HTTP/1.1 tier: POST /v1/completions
  (SSE token streaming), GET /healthz, GET /metrics (Prometheus text),
  backpressure (429 shed / 503 drain), per-request deadlines,
  disconnect-abort, graceful drain.
- ``runner.EngineRunner`` — the thread bridge: one dedicated thread
  steps the single-threaded engine; submit/abort cross over via queues
  drained at step boundaries; tokens stream out through per-request
  deliver callbacks.
- ``router.ReplicaRouter`` — data-parallel fan-out: D engine replicas
  (each its own runner thread) behind one EngineRunner-shaped facade,
  with prefix-affinity / least-outstanding-tokens / random routing.
- ``protocol`` — the OpenAI-completions-shaped wire schema (token-id
  native), ``http`` — the minimal hand-rolled HTTP/1.1 + SSE layer,
  ``metrics`` — Prometheus rendering of ``ServingStats.snapshot()``.

Run a server:  ``python -m paddle_tpu.inference.frontend --model llama-sm``

Everything is stdlib (asyncio + sockets); there is no web-framework
dependency anywhere under this package.
"""
from .app import BackgroundServer, ServingFrontend, serve_background
from .router import ReplicaRouter, build_replicas
from .runner import (EngineRunner, RunnerDraining, RunnerSaturated,
                     StreamHandle)

__all__ = ["ServingFrontend", "BackgroundServer", "serve_background",
           "EngineRunner", "RunnerSaturated", "RunnerDraining",
           "StreamHandle", "ReplicaRouter", "build_replicas"]
