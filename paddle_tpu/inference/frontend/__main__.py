"""CLI: serve a model over HTTP.

    python -m paddle_tpu.inference.frontend --model llama-sm
    curl -N http://127.0.0.1:8000/v1/completions \\
      -d '{"prompt": [1, 17, 29], "max_tokens": 32, "stream": true}'

Model presets (randomly-initialised weights — this CLI demonstrates and
load-tests the serving stack; checkpoint loading arrives with the HF
bridge):

    tiny       2-layer toy (vocab 256) — starts in seconds, CPU-friendly
    llama-sm   ~8-layer small config — a realistic serving shape
    llama-7b   the full 7B config — TPU-sized

SIGINT/SIGTERM trigger a graceful drain: admissions stop (503),
in-flight streams finish, the engine thread parks, then the process
exits.  A second SIGINT aborts in-flight work instead of finishing it.
"""
from __future__ import annotations

import argparse
import asyncio
import os
import signal
import sys


def _ensure_host_devices(n: int) -> None:
    """Make sure XLA exposes >= n host devices for --tp on CPU.  Must
    run BEFORE the first jax import (which is why every jax import in
    this module is function-local)."""
    if n <= 1:
        return
    flag = f"--xla_force_host_platform_device_count={n}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = f"{flags} {flag}".strip()


def _build_engine(args):
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from ..serving import LLMEngine

    if args.model == "tiny":
        cfg = LlamaConfig.tiny(vocab=256, hidden=64, layers=2, heads=4,
                               ffn=128, seq=args.max_model_len or 256)
    elif args.model == "llama-sm":
        cfg = LlamaConfig(vocab_size=8192, hidden_size=512,
                          intermediate_size=1408, num_hidden_layers=8,
                          num_attention_heads=8, num_key_value_heads=8,
                          max_position_embeddings=args.max_model_len or 2048)
    elif args.model == "llama-7b":
        cfg = LlamaConfig.llama_7b()
        if args.max_model_len:
            cfg.max_position_embeddings = args.max_model_len
    else:
        raise SystemExit(f"unknown --model {args.model!r}")

    model = LlamaForCausalLM(cfg)
    drafter = "ngram" if args.spec_k > 0 else None

    def make_engine():
        # shares the model (same weights!) so supervised recovery can
        # rebuild the engine and replay journals byte-identically
        kv_tier = None
        if args.host_kv_bytes > 0:
            # per-engine tier: each replica spills to its own host pool
            # (chain hashes are replica-local residency claims).  A
            # supervised rebuild gets a fresh tier — spilled pages are
            # a cache, not state recovery depends on.
            from ..kv_tier import HostSpillPool
            kv_tier = HostSpillPool(args.host_kv_bytes)
        return LLMEngine(
            model, max_num_seqs=args.max_num_seqs,
            block_size=args.block_size,
            max_model_len=cfg.max_position_embeddings,
            max_prefill_tokens=args.max_prefill_tokens,
            enable_prefix_caching=not args.no_prefix_caching,
            drafter=drafter, spec_k=args.spec_k,
            kv_dtype=args.kv_dtype, weight_dtype=args.weight_dtype,
            tp=args.tp, retain_outputs=False, kv_tier=kv_tier)

    return make_engine


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.inference.frontend",
        description="Serve an LLM over HTTP (OpenAI-style /v1/completions "
                    "with SSE streaming, /healthz, /metrics).")
    ap.add_argument("--model", default="tiny",
                    choices=["tiny", "llama-sm", "llama-7b"])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-num-seqs", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--max-model-len", type=int, default=0,
                    help="0 = the preset's max_position_embeddings")
    ap.add_argument("--max-prefill-tokens", type=int, default=512)
    ap.add_argument("--no-prefix-caching", action="store_true")
    ap.add_argument("--kv-dtype", default="float32",
                    choices=["float32", "int8"],
                    help="KV page storage dtype; int8 quarters the page "
                         "pool's HBM cost (per-page scales, in-kernel "
                         "dequant) for 2x+ resident sequences")
    ap.add_argument("--weight-dtype", default="float32",
                    choices=["float32", "int8", "int4"],
                    help="weight pool storage dtype; int8/int4 cut "
                         "resident weight bytes 4x/8x (per-channel "
                         "scales, fused dequant-matmul kernel)")
    ap.add_argument("--host-kv-bytes", type=int, default=0,
                    help="host-DRAM KV spill tier capacity per engine "
                         "replica, in bytes: pressure-evicted parked "
                         "pages spill there instead of dying and are "
                         "restored HBM-side when their prefix returns "
                         "(0 disables the tier)")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative draft length (0 disables; >0 enables "
                         "the n-gram drafter)")
    ap.add_argument("--max-pending", type=int, default=0,
                    help="admission bound before shedding 429s "
                         "(0 = 4 x max-num-seqs)")
    ap.add_argument("--deadline-ms", type=float, default=0,
                    help="default per-request deadline (0 = none)")
    ap.add_argument("--drain-timeout-s", type=float, default=30.0)
    ap.add_argument("--step-deadline-s", type=float, default=0,
                    help="supervised recovery: rebuild the engine and "
                         "replay in-flight requests when a step crashes "
                         "or runs past this wall budget (0 = off)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel shards per engine: heads and KV "
                         "pages split over a tp-way mesh inside one "
                         "compiled step (byte-identical to --tp 1; on CPU "
                         "host devices are forced automatically)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="data-parallel engine replicas behind one "
                         "listener, fed by the replica router")
    ap.add_argument("--router-policy", default="affinity",
                    choices=["affinity", "least", "random"],
                    help="replica routing: prefix-affinity (shared "
                         "prompts land on the replica already holding "
                         "their KV pages), least-outstanding-tokens, or "
                         "random (ignored with --replicas 1)")
    ap.add_argument("--flight-capacity", type=int, default=512,
                    help="per-replica flight-recorder bound for "
                         "GET /debug/requests (0 disables)")
    ap.add_argument("--anomaly-spool", default=None, metavar="DIR",
                    help="directory for anomaly-triggered trace "
                         "captures: slow-step/slow-request outliers "
                         "snapshot the trace window + slowest flight "
                         "records there (bounded; drops are counted)")
    ap.add_argument("--slo-ttft-p95-ms", type=float, default=500.0,
                    help="SLO objective: 95%% of first tokens under "
                         "this many ms")
    ap.add_argument("--slo-itl-p99-ms", type=float, default=200.0,
                    help="SLO objective: 99%% of inter-token intervals "
                         "under this many ms")
    ap.add_argument("--slo-deadline-attainment", type=float, default=0.99,
                    help="SLO objective: fraction of deadline-carrying "
                         "requests that must finish in budget")
    ap.add_argument("--slo-availability", type=float, default=0.999,
                    help="SLO objective: fraction of requests that must "
                         "finish without error/quarantine")
    args = ap.parse_args(argv)

    _ensure_host_devices(args.tp)
    print(f"[frontend] building {args.model} engine"
          + (f" x{args.replicas}" if args.replicas > 1 else "")
          + (f" (tp={args.tp})" if args.tp > 1 else "")
          + " ...", flush=True)
    make_engine = _build_engine(args)
    engine = make_engine()

    from .app import ServingFrontend
    frontend = ServingFrontend(
        engine, model_name=args.model, host=args.host, port=args.port,
        max_pending=args.max_pending or None,
        default_deadline_s=(args.deadline_ms / 1e3
                            if args.deadline_ms else None),
        engine_factory=(make_engine if args.step_deadline_s
                        or args.replicas > 1 else None),
        step_deadline_s=args.step_deadline_s or None,
        replicas=args.replicas, router_policy=args.router_policy,
        slo_config={"ttft_p95_ms": args.slo_ttft_p95_ms,
                    "itl_p99_ms": args.slo_itl_p99_ms,
                    "deadline_attainment": args.slo_deadline_attainment,
                    "availability": args.slo_availability},
        flight_capacity=args.flight_capacity,
        anomaly_spool=args.anomaly_spool)

    async def run():
        await frontend.start()
        print(f"[frontend] listening on http://{frontend.host}:"
              f"{frontend.port}  (model={args.model}, "
              f"max_num_seqs={engine.max_num_seqs})", flush=True)
        stop = asyncio.Event()
        second = asyncio.Event()
        hits = {"n": 0}

        def on_signal():
            hits["n"] += 1
            stop.set()
            if hits["n"] > 1:
                second.set()

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, on_signal)
            except NotImplementedError:
                pass
        serve = asyncio.ensure_future(frontend.serve_forever())
        await stop.wait()
        impatient = hits["n"] > 1
        print("[frontend] draining "
              f"({frontend.runner.inflight()} in flight"
              f"{', aborting' if impatient else ''}) ...", flush=True)
        drain = asyncio.ensure_future(frontend.shutdown(
            drain_timeout_s=args.drain_timeout_s,
            abort_inflight=impatient))
        if not impatient:
            # a second signal at ANY point during the drain escalates:
            # abort the in-flight set so the drain completes now
            escalate = asyncio.ensure_future(second.wait())
            done, _ = await asyncio.wait(
                {drain, escalate}, return_when=asyncio.FIRST_COMPLETED)
            if drain not in done:
                n = frontend.runner.abort_all("shutdown")
                print(f"[frontend] second signal: aborting {n} in-flight "
                      "request(s) ...", flush=True)
            escalate.cancel()
        drained = await drain
        serve.cancel()
        print(f"[frontend] {'drained' if drained else 'DRAIN TIMED OUT'}; "
              "bye", flush=True)
        return 0 if drained else 1

    return asyncio.run(run())


if __name__ == "__main__":
    sys.exit(main())
