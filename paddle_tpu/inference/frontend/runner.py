"""EngineRunner: the thread bridge between the async frontend and the
single-threaded LLMEngine.

The engine (inference/serving.py) is deliberately single-threaded — its
scheduler, page pool, and host-side batch buffers are mutated with no
locks.  The frontend, meanwhile, is an asyncio event loop serving many
sockets.  This module owns the seam: ONE dedicated thread steps the
engine forever, and every cross-thread interaction goes through queues
that the stepping thread drains at step boundaries (the only moments the
engine's state is consistent):

    HTTP thread                     engine thread
    -----------                     -------------
    submit()  ──▶ inbox deque  ──▶  engine.add_request(...)
    abort()   ──▶ abort deque  ──▶  engine.abort(rid, reason)
                                    engine.step()
    deliver(ev) ◀── on_token/on_finish callbacks (engine thread) ◀──┘

Tokens flow OUT through each request's ``deliver`` callable — invoked on
the engine thread with ("token", tok) / ("finish", RequestOutput)
events; the HTTP layer passes a closure that trampolines onto its event
loop (``loop.call_soon_threadsafe``), a sync caller can pass
``queue.Queue.put_nowait`` directly.  Backpressure is enforced HERE (not
in the engine): ``submit`` refuses work past ``max_pending``
(RunnerSaturated → the HTTP layer's 429) and while draining
(RunnerDraining → 503).

Deadlines are runner-owned: each handle carries an absolute monotonic
deadline covering queue wait AND generation; the stepping thread sweeps
expired handles every iteration and aborts them with reason
``"deadline"`` — so a deadline fires even for a request still sitting in
the admission queue.

``drain()`` is the graceful-shutdown half: stop admitting (submit
refuses), let the engine finish or deadline-out everything in flight,
then park the thread.  ``close(abort_inflight=True)`` is the impatient
variant that aborts the in-flight set instead of finishing it.

Supervised recovery (``engine_factory`` + ``step_deadline_s``): the
runner journals every token a handle has been delivered
(``StreamHandle.emitted``).  When a step CRASHES, the stepping thread
rebuilds the engine via the factory and replays every admitted handle
as a continuation (``add_request(generated=journal)``) — the prefix
cache makes the re-prefill cheap, and because sampling keys derive from
(seed, position) the continuation is byte-identical to the
uninterrupted run.  When a step HANGS past ``step_deadline_s``, a
watchdog thread performs the same recovery and spawns a replacement
stepping thread; the wedged thread becomes a zombie that exits at its
next generation check.  Every token/finish callback is GENERATION-
guarded under the runner lock — a zombie's late deliveries are dropped
before they can duplicate or reorder what the client sees — and the
journal append + guard + delivery happen under that one lock, so the
recovery snapshot is race-free by construction.  The engine's
ServingStats object (and any FaultPlan / DegradationController) carries
over to the rebuilt engine, so uptime and counters describe the
SERVICE, not one engine incarnation.

The async engine pipeline (``LLMEngine(overlap=True)``) needs NOTHING
new here, by construction: ``engine.step()`` still contains the
blocking completion of whatever launch it materializes, so the
watchdog's per-call deadline naturally spans dispatch→completion of a
ticket, and ``on_token`` fires from ``step()``'s returned outputs —
i.e. only at COMPLETION boundaries, never for a launch still in
flight.  A crash mid-pipeline therefore leaves the journal holding
exactly the tokens of fully completed steps, which is precisely the
state the replay continuation rebuilds; the in-flight launch and any
speculatively pre-staged next step die with the old engine.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field

__all__ = ["EngineRunner", "RunnerSaturated", "RunnerDraining",
           "StreamHandle"]


class RunnerSaturated(RuntimeError):
    """Admission queue full — shed the request (HTTP 429)."""


class RunnerDraining(RuntimeError):
    """Server is draining — no new work (HTTP 503)."""


@dataclass
class StreamHandle:
    """One submitted request as the frontend sees it."""
    request_id: str                   # runner-scoped id (assigned here)
    deliver: object                   # callable(event) on the engine thread
    deadline: float | None            # absolute time.monotonic() deadline
    params: dict                      # add_request kwargs
    rid: int = -1                     # engine rid once admitted
    done: bool = False
    t_submit: float = field(default_factory=time.monotonic)
    # recovery journal: every token delivered so far.  Appended under
    # the runner lock by the generation-guarded on_token closure; a
    # rebuilt engine replays the request as a continuation of exactly
    # this list.
    emitted: list = field(default_factory=list)


class EngineRunner:
    """Owns the engine's stepping thread and the cross-thread queues.

    Parameters
    ----------
    engine: an LLMEngine (ideally built with ``retain_outputs=False`` so
        a long-running server does not accumulate finished outputs).
    max_pending: admission bound — submitted-but-unfinished requests the
        runner will hold before shedding (queued + running).  Sized a
        few times ``engine.max_num_seqs`` so a burst queues instead of
        shedding, but an overload sheds instead of growing without
        bound.
    idle_wait_s: how long the stepping thread parks when there is no
        work (woken early by submit/abort/drain).
    engine_factory: nullary callable building a replacement engine after
        a crashed or hung step.  None (the default) disables recovery —
        a step exception fails the in-flight set and stops the runner.
    step_deadline_s: watchdog per-step wall budget.  A step running
        longer is treated as hung: the watchdog thread rebuilds the
        engine and spawns a replacement stepping thread.  Must sit above
        the engine's worst-case honest step (first-step XLA compiles
        included).  Under the async pipeline one ``step()`` call spans
        the completion block of the in-flight launch plus the next
        dispatch, so the budget covers dispatch→completion of a ticket
        with no watchdog change.  None disables the watchdog (crash
        recovery still works when a factory is set).
    max_restarts: recovery budget; exceeding it fails the in-flight set
        instead of rebuilding again (a deterministic crash must not loop
        forever).
    name: optional runner name, prefixed onto every request id
        ("r0-req-3") — a replica router recovers the owning runner from
        the id alone, so aborts route without a shared table.
    """

    def __init__(self, engine, *, max_pending: int | None = None,
                 idle_wait_s: float = 0.05, engine_factory=None,
                 step_deadline_s: float | None = None,
                 max_restarts: int = 8, name: str = ""):
        self.engine = engine
        self.name = str(name)
        self._id_prefix = f"{self.name}-" if self.name else ""
        self.max_pending = int(max_pending
                               if max_pending is not None
                               else 4 * engine.max_num_seqs)
        self.idle_wait_s = float(idle_wait_s)
        self._engine_factory = engine_factory
        self.step_deadline_s = None if step_deadline_s is None \
            else float(step_deadline_s)
        self.max_restarts = int(max_restarts)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._inbox: deque = deque()          # StreamHandle, FIFO
        self._aborts: deque = deque()         # (request_id, reason)
        self._handles: dict = {}              # request_id -> StreamHandle
        self._by_rid: dict = {}               # engine rid -> StreamHandle
        self._inflight = 0                    # submitted, not yet finished
        self._draining = False
        self._stopped = False
        self._seq = itertools.count()
        # recovery generation: bumped (under _lock) on every engine
        # rebuild.  Callbacks and loop iterations carry the generation
        # they were created under; a mismatch means "your engine is
        # dead — drop everything and exit".
        self._gen = 0
        self._restarts = 0
        # (generation, t_start) of the step currently executing, or None
        # between steps.  Generation-tagged so a zombie's cleanup cannot
        # clear the replacement thread's timer.
        self._step_started = None
        tname = f"llm-engine-{self.name}" if self.name else "llm-engine"
        self._thread = threading.Thread(target=self._loop, args=(0,),
                                        name=tname, daemon=True)
        self._watchdog = None
        self._started = False
        # step-timeline track, registered lazily on first traced event
        # (the engine owns the Tracer; a rebuilt engine keeps it via the
        # factory, so delivery/restart events survive recovery)
        self._trace_track = None

    def _tracer(self):
        """The live engine's Tracer, or None (the zero-cost default)."""
        tr = getattr(self.engine, "tracer", None)
        if tr is not None and self._trace_track is None:
            base = f"runner-{self.name}" if self.name else "runner"
            self._trace_track = tr.register(base)
        return tr

    @property
    def restarts(self) -> int:
        with self._lock:
            return self._restarts

    # ------------------------------------------------------------------
    # any-thread API
    # ------------------------------------------------------------------

    def start(self) -> "EngineRunner":
        if not self._started:
            self._started = True
            self._thread.start()
            if self.step_deadline_s is not None \
                    and self._engine_factory is not None:
                self._watchdog = threading.Thread(
                    target=self._watch, name="llm-watchdog", daemon=True)
                self._watchdog.start()
        return self

    def submit(self, prompt, *, deliver, deadline_s: float | None = None,
               **params) -> str:
        """Queue one generation request.  ``deliver`` receives
        ("token", int) events and exactly one terminal
        ("finish", RequestOutput) event, all on the engine thread.
        ``deadline_s`` is a relative budget from now (queue wait
        included).  Returns the runner request id (the abort() handle).
        Raises RunnerSaturated / RunnerDraining instead of queuing."""
        with self._lock:
            if self._draining or self._stopped:
                raise RunnerDraining("runner is draining")
            if self._inflight >= self.max_pending:
                raise RunnerSaturated(
                    f"{self._inflight} requests in flight >= max_pending "
                    f"{self.max_pending}")
            request_id = f"{self._id_prefix}req-{next(self._seq)}"
            deadline = None if deadline_s is None \
                else time.monotonic() + float(deadline_s)
            h = StreamHandle(request_id=request_id, deliver=deliver,
                             deadline=deadline, params=dict(params))
            h.params["prompt"] = prompt
            self._handles[request_id] = h
            self._inbox.append(h)
            self._inflight += 1
        self._wake.set()
        return request_id

    def abort(self, request_id: str, reason: str = "aborted") -> None:
        """Request cancellation; applied at the next step boundary.  The
        stream still receives its terminal ("finish", output) event (with
        the abort reason) unless it already finished — aborting a
        finished/unknown id is a no-op."""
        with self._lock:
            self._aborts.append((request_id, reason))
        self._wake.set()

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def drain(self, timeout_s: float | None = None) -> bool:
        """Graceful shutdown: stop admitting, finish (or deadline-out)
        everything in flight, park the thread.  True when the engine
        drained fully inside the timeout."""
        with self._lock:
            self._draining = True
        self._wake.set()
        t0 = time.monotonic()
        while True:
            with self._lock:
                if self._inflight == 0:
                    break
            if timeout_s is not None \
                    and time.monotonic() - t0 > float(timeout_s):
                break
            time.sleep(0.005)
        with self._lock:
            drained = self._inflight == 0
            self._stopped = True
        self._wake.set()
        if self._started:
            self._thread.join(timeout=5.0)
        return drained

    def abort_all(self, reason: str = "shutdown") -> int:
        """Queue an abort for every request still in flight (applied at
        the next step boundary); returns how many were queued.  The CLI's
        second-SIGINT escalation: a graceful drain already in progress
        completes as soon as these aborts land."""
        with self._lock:
            ids = [h.request_id for h in self._handles.values()
                   if not h.done]
        for request_id in ids:
            self.abort(request_id, reason)
        return len(ids)

    def close(self, *, abort_inflight: bool = True) -> None:
        """Impatient shutdown: abort whatever is still in flight (reason
        "shutdown"), then stop the thread."""
        if abort_inflight:
            with self._lock:
                self._draining = True
            self.abort_all("shutdown")
        self.drain(timeout_s=30.0)

    # ------------------------------------------------------------------
    # engine thread
    # ------------------------------------------------------------------

    def _finish_handle(self, h, out, gen: int | None = None) -> None:
        # engine thread only.  ``gen`` guards a stale engine's finish:
        # after a rebuild the replacement owns the handle, so the old
        # engine's terminal event must be dropped, not delivered.
        with self._lock:
            if gen is not None and gen != self._gen:
                return
            if h.done:
                return
            h.done = True
            self._handles.pop(h.request_id, None)
            if h.rid >= 0:
                self._by_rid.pop(h.rid, None)
            self._inflight -= 1
        if h.deadline is not None:
            # deadline attainment: only deadline-carrying requests vote
            self.engine.stats.record_deadline(
                getattr(out, "finish_reason", None) != "deadline"
                and time.monotonic() <= h.deadline)
        try:
            h.deliver(("finish", out))
        except Exception:
            pass                      # a dead consumer must not kill the loop
        tr = self._tracer()
        if tr is not None:
            tr.instant("runner.finish", track=self._trace_track,
                       args={"request_id": h.request_id, "rid": h.rid,
                             "finish_reason": getattr(
                                 out, "finish_reason", None)})

    def _admit_one(self, eng, h, gen: int, generated=None) -> bool:
        """Admit one handle into ``eng`` with generation-guarded
        callbacks.  ``generated`` is the recovery journal (continuation
        replay); None for a first admission."""

        def _on_token(rid, tok, h=h, g=gen):
            # guard + journal append + delivery under ONE lock hold:
            # the recovery snapshot (which bumps _gen under the same
            # lock before reading h.emitted) can therefore never miss a
            # delivered token or race a zombie into a duplicate
            with self._lock:
                if g != self._gen or h.done:
                    return
                h.emitted.append(tok)
                try:
                    h.deliver(("token", tok))
                except Exception:
                    pass
            tr = self._tracer()
            if tr is not None:
                # the cross-tier join point: engine rid <-> frontend id
                tr.instant("runner.deliver", track=self._trace_track,
                           args={"request_id": h.request_id, "rid": rid,
                                 "tokens": len(h.emitted)})

        def _on_finish(out, h=h, g=gen):
            self._finish_handle(h, out, gen=g)

        params = dict(h.params)
        prompt = params.pop("prompt")
        if generated is not None:
            params["generated"] = list(generated)
        try:
            rid = eng.add_request(prompt, on_token=_on_token,
                                  on_finish=_on_finish, **params)
        except Exception as e:
            from ..serving import RequestOutput
            self._finish_handle(h, RequestOutput(
                rid=-1, prompt=list(prompt), generated=list(h.emitted),
                finish_reason=f"error: {type(e).__name__}: {e}"))
            return False
        h.rid = rid
        fl = getattr(eng, "flight", None)
        if fl is not None:
            # the same cross-tier join the tracer instants carry:
            # engine rid <-> frontend request id, plus the remaining
            # deadline budget measured at engine admission (the flight
            # record's t_submit) so slack fields line up
            fl.annotate(rid, request_id=h.request_id,
                        replica=self.name or None,
                        deadline_s=None if h.deadline is None
                        else h.deadline - time.monotonic())
        with self._lock:
            self._by_rid[rid] = h
        return True

    def _admit_inbox(self, gen: int) -> None:
        eng = self.engine
        while True:
            with self._lock:
                if gen != self._gen or not self._inbox:
                    return
                h = self._inbox.popleft()
            if h.done:                # aborted while still queued
                continue
            self._admit_one(eng, h, gen)

    def _apply_aborts(self, gen: int) -> None:
        while True:
            with self._lock:
                if gen != self._gen or not self._aborts:
                    return
                request_id, reason = self._aborts.popleft()
                h = self._handles.get(request_id)
            if h is None or h.done:
                continue
            if h.rid >= 0:
                # engine.abort fires on_finish -> _finish_handle
                self.engine.abort(h.rid, finish_reason=reason)
            else:
                # never reached the engine: synthesize the terminal event
                from ..serving import RequestOutput
                self._finish_handle(h, RequestOutput(
                    rid=-1, prompt=[], generated=[], finish_reason=reason))
                self.engine.stats.record_abort(reason)

    def _sweep_deadlines(self, gen: int) -> None:
        now = time.monotonic()
        with self._lock:
            expired = [h.request_id for h in self._handles.values()
                       if h.deadline is not None and now > h.deadline
                       and not h.done]
        for request_id in expired:
            with self._lock:
                self._aborts.append((request_id, "deadline"))
        if expired:
            self._apply_aborts(gen)

    # -- supervised recovery -----------------------------------------------

    def _recover(self, gen: int):
        """Rebuild the engine after a crashed/hung step and replay the
        in-flight set from the journal.  Returns the new generation, or
        None when recovery is off/raced/exhausted (the runner stops).
        Called from the stepping thread (crash) or the watchdog (hang);
        the generation check under the lock makes the two racers safe —
        exactly one wins."""
        with self._lock:
            if gen != self._gen:
                return None           # someone else already recovered
            self._gen += 1
            newgen = self._gen
            self._restarts += 1
            restarts = self._restarts
            live = [h for h in self._handles.values() if not h.done]
            # the journal snapshot: taken AFTER the generation bump, so
            # no old-generation callback can append past this point
            replay = [(h, list(h.emitted)) for h in live if h.rid >= 0]
            requeue = [h for h in live
                       if h.rid < 0 and h not in self._inbox]
        old = self.engine
        tr = self._tracer()
        if tr is not None:
            t_rec = tr.now()
        if self._engine_factory is None or restarts > self.max_restarts:
            from ..serving import RequestOutput
            for h in live:
                self._finish_handle(h, RequestOutput(
                    rid=-1, prompt=list(h.params.get("prompt", [])),
                    generated=list(h.emitted),
                    finish_reason="engine_error"))
            with self._lock:
                self._stopped = True
            self._wake.set()
            return None
        # detach the shared fault plan / pressure controller from the
        # dead engine FIRST: a hung step finishing on the zombie thread
        # must not consume scheduled faults or feed the controller stale
        # pool readings while the replacement runs
        plan = getattr(old, "fault_plan", None)
        pressure = getattr(old, "pressure", None)
        if plan is not None:
            old.set_fault_plan(None)
        if pressure is not None:
            old.pressure = None
        eng = self._engine_factory()
        # metric continuity: the service's stats (and the flight
        # recorder's forensic window) survive the engine
        eng.stats = old.stats
        eng.stats.record_restart()
        eng.flight = getattr(old, "flight", None)
        if plan is not None:
            eng.set_fault_plan(plan)
        eng.pressure = pressure
        self.engine = eng
        # replay admitted requests in submission order (dict order) as
        # continuations of their journals; failures fail only that handle
        for h, emitted in replay:
            h.rid = -1
            cap = int(h.params.get("max_new_tokens", 32))
            if len(emitted) >= cap:
                # the crash lost only the terminal event — the journal
                # already holds the whole budget
                from ..serving import RequestOutput
                self._finish_handle(h, RequestOutput(
                    rid=-1, prompt=list(h.params.get("prompt", [])),
                    generated=list(emitted), finish_reason="length"))
                continue
            self._admit_one(eng, h, newgen,
                            generated=emitted if emitted else None)
        with self._lock:
            for h in requeue:        # popped from the inbox mid-crash
                self._inbox.append(h)
        self._wake.set()
        if tr is not None:
            tr.complete("runner.restart", t_rec, track=self._trace_track,
                        args={"gen": newgen, "restarts": restarts,
                              "replayed": len(replay)})
        return newgen

    def _watch(self) -> None:
        """Watchdog thread: when the current step has run past
        step_deadline_s, recover and spawn a replacement stepping
        thread.  The wedged thread exits at its next generation check;
        its late callbacks are dropped by the generation guard."""
        poll = min(self.step_deadline_s / 4.0, 0.05)
        while True:
            with self._lock:
                if self._stopped:
                    return
                gen = self._gen
            ss = self._step_started
            if ss is not None and ss[0] == gen \
                    and time.monotonic() - ss[1] > self.step_deadline_s:
                tr = self._tracer()
                if tr is not None:
                    tr.instant("runner.watchdog_fired",
                               track=self._trace_track,
                               args={"gen": gen, "stuck_s": round(
                                   time.monotonic() - ss[1], 3)})
                newgen = self._recover(gen)
                if newgen is not None:
                    t = threading.Thread(target=self._loop, args=(newgen,),
                                         name=f"llm-engine-g{newgen}",
                                         daemon=True)
                    self._thread = t
                    t.start()
            time.sleep(poll)

    def _loop(self, gen: int) -> None:
        while True:
            with self._lock:
                if self._stopped or gen != self._gen:
                    return
            eng = self.engine
            try:
                self._apply_aborts(gen)
                self._sweep_deadlines(gen)
                self._admit_inbox(gen)
                if eng.has_unfinished():
                    self._step_started = (gen, time.monotonic())
                    try:
                        eng.step()
                    finally:
                        ss = self._step_started
                        if ss is not None and ss[0] == gen:
                            self._step_started = None
                    continue
            except Exception:
                newgen = self._recover(gen)
                if newgen is None:
                    return
                gen = newgen
                continue
            with self._lock:
                idle = not self._inbox and not self._aborts \
                    and not self._stopped
            if idle:
                self._wake.wait(self.idle_wait_s)
                self._wake.clear()
