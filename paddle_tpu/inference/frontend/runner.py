"""EngineRunner: the thread bridge between the async frontend and the
single-threaded LLMEngine.

The engine (inference/serving.py) is deliberately single-threaded — its
scheduler, page pool, and host-side batch buffers are mutated with no
locks.  The frontend, meanwhile, is an asyncio event loop serving many
sockets.  This module owns the seam: ONE dedicated thread steps the
engine forever, and every cross-thread interaction goes through queues
that the stepping thread drains at step boundaries (the only moments the
engine's state is consistent):

    HTTP thread                     engine thread
    -----------                     -------------
    submit()  ──▶ inbox deque  ──▶  engine.add_request(...)
    abort()   ──▶ abort deque  ──▶  engine.abort(rid, reason)
                                    engine.step()
    deliver(ev) ◀── on_token/on_finish callbacks (engine thread) ◀──┘

Tokens flow OUT through each request's ``deliver`` callable — invoked on
the engine thread with ("token", tok) / ("finish", RequestOutput)
events; the HTTP layer passes a closure that trampolines onto its event
loop (``loop.call_soon_threadsafe``), a sync caller can pass
``queue.Queue.put_nowait`` directly.  Backpressure is enforced HERE (not
in the engine): ``submit`` refuses work past ``max_pending``
(RunnerSaturated → the HTTP layer's 429) and while draining
(RunnerDraining → 503).

Deadlines are runner-owned: each handle carries an absolute monotonic
deadline covering queue wait AND generation; the stepping thread sweeps
expired handles every iteration and aborts them with reason
``"deadline"`` — so a deadline fires even for a request still sitting in
the admission queue.

``drain()`` is the graceful-shutdown half: stop admitting (submit
refuses), let the engine finish or deadline-out everything in flight,
then park the thread.  ``close(abort_inflight=True)`` is the impatient
variant that aborts the in-flight set instead of finishing it.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field

__all__ = ["EngineRunner", "RunnerSaturated", "RunnerDraining",
           "StreamHandle"]


class RunnerSaturated(RuntimeError):
    """Admission queue full — shed the request (HTTP 429)."""


class RunnerDraining(RuntimeError):
    """Server is draining — no new work (HTTP 503)."""


@dataclass
class StreamHandle:
    """One submitted request as the frontend sees it."""
    request_id: str                   # runner-scoped id (assigned here)
    deliver: object                   # callable(event) on the engine thread
    deadline: float | None            # absolute time.monotonic() deadline
    params: dict                      # add_request kwargs
    rid: int = -1                     # engine rid once admitted
    done: bool = False
    t_submit: float = field(default_factory=time.monotonic)


class EngineRunner:
    """Owns the engine's stepping thread and the cross-thread queues.

    Parameters
    ----------
    engine: an LLMEngine (ideally built with ``retain_outputs=False`` so
        a long-running server does not accumulate finished outputs).
    max_pending: admission bound — submitted-but-unfinished requests the
        runner will hold before shedding (queued + running).  Sized a
        few times ``engine.max_num_seqs`` so a burst queues instead of
        shedding, but an overload sheds instead of growing without
        bound.
    idle_wait_s: how long the stepping thread parks when there is no
        work (woken early by submit/abort/drain).
    """

    def __init__(self, engine, *, max_pending: int | None = None,
                 idle_wait_s: float = 0.05):
        self.engine = engine
        self.max_pending = int(max_pending
                               if max_pending is not None
                               else 4 * engine.max_num_seqs)
        self.idle_wait_s = float(idle_wait_s)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._inbox: deque = deque()          # StreamHandle, FIFO
        self._aborts: deque = deque()         # (request_id, reason)
        self._handles: dict = {}              # request_id -> StreamHandle
        self._by_rid: dict = {}               # engine rid -> StreamHandle
        self._inflight = 0                    # submitted, not yet finished
        self._draining = False
        self._stopped = False
        self._seq = itertools.count()
        self._thread = threading.Thread(target=self._loop,
                                        name="llm-engine", daemon=True)
        self._started = False

    # ------------------------------------------------------------------
    # any-thread API
    # ------------------------------------------------------------------

    def start(self) -> "EngineRunner":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def submit(self, prompt, *, deliver, deadline_s: float | None = None,
               **params) -> str:
        """Queue one generation request.  ``deliver`` receives
        ("token", int) events and exactly one terminal
        ("finish", RequestOutput) event, all on the engine thread.
        ``deadline_s`` is a relative budget from now (queue wait
        included).  Returns the runner request id (the abort() handle).
        Raises RunnerSaturated / RunnerDraining instead of queuing."""
        with self._lock:
            if self._draining or self._stopped:
                raise RunnerDraining("runner is draining")
            if self._inflight >= self.max_pending:
                raise RunnerSaturated(
                    f"{self._inflight} requests in flight >= max_pending "
                    f"{self.max_pending}")
            request_id = f"req-{next(self._seq)}"
            deadline = None if deadline_s is None \
                else time.monotonic() + float(deadline_s)
            h = StreamHandle(request_id=request_id, deliver=deliver,
                             deadline=deadline, params=dict(params))
            h.params["prompt"] = prompt
            self._handles[request_id] = h
            self._inbox.append(h)
            self._inflight += 1
        self._wake.set()
        return request_id

    def abort(self, request_id: str, reason: str = "aborted") -> None:
        """Request cancellation; applied at the next step boundary.  The
        stream still receives its terminal ("finish", output) event (with
        the abort reason) unless it already finished — aborting a
        finished/unknown id is a no-op."""
        with self._lock:
            self._aborts.append((request_id, reason))
        self._wake.set()

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, timeout_s: float | None = None) -> bool:
        """Graceful shutdown: stop admitting, finish (or deadline-out)
        everything in flight, park the thread.  True when the engine
        drained fully inside the timeout."""
        with self._lock:
            self._draining = True
        self._wake.set()
        t0 = time.monotonic()
        while True:
            with self._lock:
                if self._inflight == 0:
                    break
            if timeout_s is not None \
                    and time.monotonic() - t0 > float(timeout_s):
                break
            time.sleep(0.005)
        with self._lock:
            drained = self._inflight == 0
            self._stopped = True
        self._wake.set()
        if self._started:
            self._thread.join(timeout=5.0)
        return drained

    def close(self, *, abort_inflight: bool = True) -> None:
        """Impatient shutdown: abort whatever is still in flight (reason
        "shutdown"), then stop the thread."""
        if abort_inflight:
            with self._lock:
                ids = list(self._handles)
                self._draining = True
            for request_id in ids:
                self.abort(request_id, reason="shutdown")
        self.drain(timeout_s=30.0)

    # ------------------------------------------------------------------
    # engine thread
    # ------------------------------------------------------------------

    def _finish_handle(self, h, out) -> None:
        # engine thread only; lock held by caller where required
        if h.done:
            return
        h.done = True
        with self._lock:
            self._handles.pop(h.request_id, None)
            if h.rid >= 0:
                self._by_rid.pop(h.rid, None)
            self._inflight -= 1
        try:
            h.deliver(("finish", out))
        except Exception:
            pass                      # a dead consumer must not kill the loop

    def _admit_inbox(self) -> None:
        eng = self.engine
        while True:
            with self._lock:
                if not self._inbox:
                    return
                h = self._inbox.popleft()
            if h.done:                # aborted while still queued
                continue

            def _on_token(rid, tok, h=h):
                try:
                    h.deliver(("token", tok))
                except Exception:
                    pass

            def _on_finish(out, h=h):
                self._finish_handle(h, out)

            params = dict(h.params)
            prompt = params.pop("prompt")
            try:
                rid = eng.add_request(prompt, on_token=_on_token,
                                      on_finish=_on_finish, **params)
            except Exception as e:
                from ..serving import RequestOutput
                self._finish_handle(h, RequestOutput(
                    rid=-1, prompt=list(prompt), generated=[],
                    finish_reason=f"error: {type(e).__name__}: {e}"))
                continue
            h.rid = rid
            with self._lock:
                self._by_rid[rid] = h

    def _apply_aborts(self) -> None:
        while True:
            with self._lock:
                if not self._aborts:
                    return
                request_id, reason = self._aborts.popleft()
                h = self._handles.get(request_id)
            if h is None or h.done:
                continue
            if h.rid >= 0:
                # engine.abort fires on_finish -> _finish_handle
                self.engine.abort(h.rid, finish_reason=reason)
            else:
                # never reached the engine: synthesize the terminal event
                from ..serving import RequestOutput
                self._finish_handle(h, RequestOutput(
                    rid=-1, prompt=[], generated=[], finish_reason=reason))
                self.engine.stats.record_abort(reason)

    def _sweep_deadlines(self) -> None:
        now = time.monotonic()
        with self._lock:
            expired = [h.request_id for h in self._handles.values()
                       if h.deadline is not None and now > h.deadline
                       and not h.done]
        for request_id in expired:
            with self._lock:
                self._aborts.append((request_id, "deadline"))
        if expired:
            self._apply_aborts()

    def _loop(self) -> None:
        eng = self.engine
        while True:
            with self._lock:
                if self._stopped:
                    return
            self._apply_aborts()
            self._sweep_deadlines()
            self._admit_inbox()
            if eng.has_unfinished():
                eng.step()
                continue
            with self._lock:
                idle = not self._inbox and not self._aborts \
                    and not self._stopped
            if idle:
                self._wake.wait(self.idle_wait_s)
                self._wake.clear()
