"""The /v1/completions wire schema: OpenAI-completions-shaped, token-id
native.

This framework serves raw language models (no tokenizer ships with the
engine), so ``prompt`` is a list of token ids and completions come back
as token ids — the shape any OpenAI-style client library can drive once
pointed at ids instead of text.  ``parse_completion_request`` maps the
JSON body onto ``LLMEngine.add_request`` kwargs with hard validation (a
frontend must reject garbage before it costs engine work), and the
``completion_*`` helpers render the non-streaming response and the SSE
stream frames.

Request fields (POST /v1/completions, application/json):

    prompt              [int] token ids (required, non-empty)
    max_tokens          int, default 16
    temperature         float, default 0 (greedy)
    top_k / top_p       sampling knobs (engine semantics)
    repetition_penalty  float, default 1.0
    seed                int, default 0
    stop_token_id       int eos override (optional)
    spec_k              per-request speculative draft length (optional)
    stream              bool — SSE token stream vs one JSON body
    deadline_ms         per-request wall budget, queue wait included
                        (optional; server default applies otherwise)

Streaming frames mirror OpenAI's: ``data: {json}\\n\\n`` per token with
``choices[0].token`` the new token id, then a final frame carrying
``finish_reason``, then ``data: [DONE]``.
"""
from __future__ import annotations

import json

__all__ = ["ProtocolError", "parse_completion_request",
           "completion_response", "stream_token_frame",
           "stream_finish_frame", "error_body"]


class ProtocolError(ValueError):
    """Invalid request body → HTTP 400 with a JSON error."""


def _require(cond, msg):
    if not cond:
        raise ProtocolError(msg)


def parse_completion_request(body: bytes):
    """Parse + validate the JSON body.  Returns (engine_kwargs, stream,
    deadline_ms) where engine_kwargs feeds LLMEngine.add_request via
    EngineRunner.submit."""
    try:
        obj = json.loads(body.decode("utf-8") or "{}")
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"body is not valid JSON: {e}") from e
    _require(isinstance(obj, dict), "body must be a JSON object")

    prompt = obj.get("prompt")
    _require(isinstance(prompt, list) and prompt,
             "'prompt' must be a non-empty list of token ids")
    _require(all(isinstance(t, int) and not isinstance(t, bool)
                 for t in prompt),
             "'prompt' must contain integer token ids")

    def _num(name, default, kind, lo=None, hi=None):
        v = obj.get(name, default)
        _require(isinstance(v, (int, float)) and not isinstance(v, bool),
                 f"'{name}' must be a number")
        v = kind(v)
        _require(lo is None or v >= lo, f"'{name}' must be >= {lo}")
        _require(hi is None or v <= hi, f"'{name}' must be <= {hi}")
        return v

    kwargs = {
        "prompt": [int(t) for t in prompt],
        "max_new_tokens": _num("max_tokens", 16, int, lo=1),
        "temperature": _num("temperature", 0.0, float, lo=0.0),
        "top_k": _num("top_k", 0, int, lo=0),
        "top_p": _num("top_p", 1.0, float),
        "repetition_penalty": _num("repetition_penalty", 1.0, float),
        "seed": _num("seed", 0, int),
    }
    _require(0.0 < kwargs["top_p"] <= 1.0, "'top_p' must be in (0, 1]")
    _require(kwargs["repetition_penalty"] > 0.0,
             "'repetition_penalty' must be > 0")
    if obj.get("stop_token_id") is not None:
        kwargs["eos_token_id"] = _num("stop_token_id", None, int, lo=0)
    if obj.get("spec_k") is not None:
        kwargs["spec_k"] = _num("spec_k", None, int, lo=0)

    stream = obj.get("stream", False)
    _require(isinstance(stream, bool), "'stream' must be a boolean")
    deadline_ms = obj.get("deadline_ms")
    if deadline_ms is not None:
        deadline_ms = _num("deadline_ms", None, float, lo=1.0)
    return kwargs, stream, deadline_ms


def _finish_reason(out) -> str:
    # engine reasons "eos" -> OpenAI "stop"; "length" passes through;
    # abort reasons ("aborted"/"deadline"/"shutdown") pass through so
    # clients can tell WHY a stream ended early
    return "stop" if out.finish_reason == "eos" else out.finish_reason


def completion_response(request_id: str, model: str, out) -> bytes:
    """Non-streaming response body."""
    return json.dumps({
        "id": request_id,
        "object": "text_completion",
        "model": model,
        "choices": [{
            "index": 0,
            "token_ids": list(out.generated),
            "finish_reason": _finish_reason(out),
        }],
        "usage": {
            "prompt_tokens": len(out.prompt),
            "completion_tokens": len(out.generated),
            "total_tokens": len(out.prompt) + len(out.generated),
        },
    }).encode("utf-8")


def stream_token_frame(request_id: str, model: str, token: int) -> str:
    return json.dumps({
        "id": request_id,
        "object": "text_completion.chunk",
        "model": model,
        "choices": [{"index": 0, "token": int(token),
                     "finish_reason": None}],
    })


def stream_finish_frame(request_id: str, model: str, out) -> str:
    return json.dumps({
        "id": request_id,
        "object": "text_completion.chunk",
        "model": model,
        "choices": [{"index": 0, "token": None,
                     "finish_reason": _finish_reason(out)}],
        "usage": {
            "prompt_tokens": len(out.prompt),
            "completion_tokens": len(out.generated),
            "total_tokens": len(out.prompt) + len(out.generated),
        },
    })


def error_body(status: int, message: str, *, kind: str = "invalid_request",
               ) -> bytes:
    return json.dumps({"error": {"message": message, "type": kind,
                                 "code": int(status)}}).encode("utf-8")
