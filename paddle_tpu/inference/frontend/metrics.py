"""Prometheus text exposition for the serving frontend.

One function renders everything a scrape needs: the engine's
``ServingStats.snapshot()`` (latency quantiles, throughput, cache and
speculation counters — reservoir-backed, so snapshotting from the HTTP
thread is cheap and safe), the KV page pool gauges, and the frontend's
own request-lifecycle counters.  Format is the Prometheus text
exposition format v0.0.4: ``# HELP`` / ``# TYPE`` preambles, one sample
per line, labels in ``{}``; quantiles are exported as gauges under the
conventional ``{quantile="0.5"}`` labels (a true summary type needs
+Inf buckets we don't track).
"""
from __future__ import annotations

__all__ = ["render_metrics"]

_PREFIX = "paddle_tpu"


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Doc:
    def __init__(self):
        self.lines = []

    def metric(self, name, kind, help_text, samples):
        """samples: iterable of (labels-dict-or-None, value)."""
        full = f"{_PREFIX}_{name}"
        self.lines.append(f"# HELP {full} {help_text}")
        self.lines.append(f"# TYPE {full} {kind}")
        for labels, value in samples:
            if value is None:
                continue
            lbl = ""
            if labels:
                inner = ",".join(f'{k}="{_esc(v)}"'
                                 for k, v in sorted(labels.items()))
                lbl = "{" + inner + "}"
            v = float(value)
            sval = repr(int(v)) if v == int(v) else repr(v)
            self.lines.append(f"{full}{lbl} {sval}")

    def histogram(self, name, help_text, buckets, total, count):
        """One true Prometheus histogram: cumulative ``_bucket{le=}``
        samples (ascending, ending at +Inf) plus ``_sum``/``_count``.
        ``buckets`` is the ``_Hist.buckets()`` dict — already cumulative
        and insertion-ordered by upper bound."""
        full = f"{_PREFIX}_{name}"
        self.lines.append(f"# HELP {full} {help_text}")
        self.lines.append(f"# TYPE {full} histogram")
        for le, n in buckets.items():
            self.lines.append(f'{full}_bucket{{le="{_esc(le)}"}} {int(n)}')
        v = float(total)
        sval = repr(int(v)) if v == int(v) else repr(v)
        self.lines.append(f"{full}_sum {sval}")
        self.lines.append(f"{full}_count {int(count)}")

    def render(self) -> str:
        return "\n".join(self.lines) + "\n"


def render_metrics(snapshot: dict, *, engine=None,
                   frontend: dict | None = None,
                   router: dict | None = None) -> str:
    """Render one /metrics scrape.

    snapshot: ServingStats.snapshot() dict (or the fleet aggregate from
        ``ServingStats.aggregate`` when a router is attached).
    engine: the live LLMEngine for pool/queue gauges (optional so the
        renderer stays unit-testable with a bare snapshot).  Under a
        replica router this is replica 0 — the fleet-wide counters come
        from the aggregated snapshot, the pool gauges are one replica's.
    frontend: the frontend's own counters —
        {"requests_total": {(route, code): n}, "shed_total": n,
         "active_streams": n, "queue_depth": n, "draining": bool}.
    router: ReplicaRouter.router_counters() — per-replica routing gauges
        labeled {replica="i"}; None for a single-runner frontend.
    """
    d = _Doc()
    s = snapshot
    fe = frontend or {}

    # -- request lifecycle ------------------------------------------------
    d.metric("http_requests_total", "counter",
             "HTTP requests served, by route and status code.",
             [({"route": r, "code": str(c)}, n)
              for (r, c), n in sorted(fe.get("requests_total", {}).items())])
    d.metric("requests_admitted_total", "counter",
             "Generation requests admitted into the engine.",
             [(None, s.get("admitted"))])
    d.metric("requests_finished_total", "counter",
             "Generation requests retired by the engine.",
             [(None, s.get("retired"))])
    d.metric("aborts_total", "counter",
             "Generation requests aborted, by reason.",
             [({"reason": r}, n)
              for r, n in sorted((s.get("abort_reasons") or {}).items())]
             or [({"reason": "aborted"}, 0)])
    d.metric("shed_total", "counter",
             "Requests refused with 429 because the admission queue "
             "was full.", [(None, fe.get("shed_total", 0))])
    d.metric("active_streams", "gauge",
             "HTTP connections currently streaming tokens.",
             [(None, fe.get("active_streams", 0))])
    d.metric("queue_depth", "gauge",
             "Requests submitted to the runner and not yet finished.",
             [(None, fe.get("queue_depth", 0))])
    d.metric("draining", "gauge",
             "1 while the server is draining (rejecting new work).",
             [(None, 1 if fe.get("draining") else 0)])

    # -- latency ----------------------------------------------------------
    d.metric("ttft_seconds", "gauge",
             "Time to first token (queue wait included).",
             [({"quantile": "0.5"}, _ms(s.get("ttft_p50_ms"))),
              ({"quantile": "0.99"}, _ms(s.get("ttft_p99_ms")))])
    d.metric("itl_seconds", "gauge",
             "Inter-token latency (per-token decode interval).",
             [({"quantile": "0.5"}, _ms(s.get("itl_p50_ms"))),
              ({"quantile": "0.99"}, _ms(s.get("itl_p99_ms")))])
    d.metric("queue_wait_seconds", "gauge",
             "Admission queue wait (arrival to engine admission).",
             [({"quantile": "0.5"}, _ms(s.get("queue_wait_p50_ms"))),
              ({"quantile": "0.99"}, _ms(s.get("queue_wait_p99_ms")))])
    d.metric("throughput_tokens_per_second", "gauge",
             "Generated-token throughput over the stats window.",
             [(None, s.get("decode_tokens_per_s"))])
    d.metric("generated_tokens_total", "counter",
             "Tokens emitted by the engine.",
             [(None, s.get("decode_tokens"))])

    # -- latency histograms ----------------------------------------------
    # exact-count cumulative-bucket series next to the quantile gauges
    # above: buckets with identical bounds SUM across replicas/scrapes,
    # so these aggregate honestly where max-of-quantile gauges cannot
    for key, name, help_text in (
            ("ttft_hist", "ttft_hist_seconds",
             "Time to first token, as cumulative histogram buckets."),
            ("itl_hist", "itl_hist_seconds",
             "Inter-token latency, as cumulative histogram buckets."),
            ("step_hist", "step_duration_seconds",
             "Engine launch-cycle wall-clock duration, as cumulative "
             "histogram buckets.")):
        buckets = s.get(f"{key}_buckets")
        if buckets:
            d.histogram(name, help_text, buckets,
                        s.get(f"{key}_sum", 0.0), s.get(f"{key}_count", 0))

    # -- windowed telemetry + SLO -----------------------------------------
    # rolling-window quantiles labeled {window=,quantile=} — unlike the
    # lifetime gauges above these answer "how are we doing RIGHT NOW"
    w = s.get("windows")
    if w:
        lat_samples, rate_samples = [], []
        for wl in sorted((k for k in w if k != "bounds"),
                         key=lambda k: float(k[:-1])):
            for ch, st in sorted(w[wl].items()):
                if "p95_ms" in st:
                    for key, q in (("p50_ms", "0.5"), ("p95_ms", "0.95"),
                                   ("p99_ms", "0.99")):
                        lat_samples.append((
                            {"channel": ch, "window": wl, "quantile": q},
                            _ms(st.get(key))))
                elif "rate" in st:
                    rate_samples.append((
                        {"channel": ch, "window": wl}, st.get("rate")))
        d.metric("windowed_latency_seconds", "gauge",
                 "Rolling-window latency quantiles by channel (ttft, "
                 "itl, step, queue_wait, request).", lat_samples)
        d.metric("windowed_rate", "gauge",
                 "Rolling-window rates by channel (accept, deadline, "
                 "availability).", rate_samples)
        d.metric("slo_state", "gauge",
                 "SLO burn-rate state: 0 normal, 1 warn, 2 page.",
                 [(None, s.get("slo_state"))])
        burns = (s.get("slo") or {}).get("burn_rates") or {}
        d.metric("slo_burn_rate", "gauge",
                 "Error-budget burn rate per objective and window "
                 "(1.0 = consuming exactly the budget).",
                 [({"objective": obj, "window": wl}, v)
                  for wl, objs in sorted(burns.items())
                  for obj, v in sorted(objs.items()) if obj != "max"])
        d.metric("anomalies_detected_total", "counter",
                 "Slow-step/slow-request outliers flagged by the MAD "
                 "detector.", [(None, s.get("anomalies_detected"))])
        d.metric("anomalies_captured_total", "counter",
                 "Anomaly trace snapshots written to the spool.",
                 [(None, s.get("anomalies_captured"))])
        d.metric("anomaly_spool_dropped_total", "counter",
                 "Anomaly snapshots dropped by the spool bound.",
                 [(None, s.get("anomaly_spool_dropped"))])

    # -- async step pipeline ---------------------------------------------
    # each launch cycle split into the host dispatch section vs the
    # completion block on device results (overlap hides the latter)
    d.metric("step_dispatch_seconds_total", "counter",
             "Cumulative host dispatch time (pack/stage/launch enqueue).",
             [(None, s.get("dispatch_time_s"))])
    d.metric("step_block_seconds_total", "counter",
             "Cumulative completion-block time (waiting on device "
             "results).", [(None, s.get("block_time_s"))])
    d.metric("step_dispatch_seconds", "gauge",
             "Per-step host dispatch duration.",
             [({"quantile": "0.5"}, _ms(s.get("dispatch_ms_p50"))),
              ({"quantile": "0.99"}, _ms(s.get("dispatch_ms_p99")))])
    d.metric("step_block_seconds", "gauge",
             "Per-step completion-block duration.",
             [({"quantile": "0.5"}, _ms(s.get("block_ms_p50"))),
              ({"quantile": "0.99"}, _ms(s.get("block_ms_p99")))])

    # -- device-resident decode window ------------------------------------
    # how often the host blocked on the device, and how many emitted
    # tokens each block drained (1.0 per-step; -> K with the window on)
    d.metric("host_round_trips_total", "counter",
             "Host<->device completion blocks (one per launch drained).",
             [(None, s.get("host_round_trips"))])
    d.metric("tokens_per_launch", "gauge",
             "Emitted tokens (decode+verify) per host round-trip.",
             [(None, s.get("tokens_per_launch"))])
    d.metric("decode_window_k", "gauge",
             "Largest on-device decode window this engine ran (1 = "
             "per-step).", [(None, s.get("decode_window_k"))])
    d.metric("decode_window_fallbacks_total", "counter",
             "Eligible decode windows that ran per-step because the "
             "page pool could not pre-reserve K tokens of slack.",
             [(None, s.get("decode_window_fallbacks"))])
    d.metric("decode_window_shrinks_total", "counter",
             "Eligible decode windows that ran device-resident at a "
             "shrunk K' < K (largest slack the page pool covered).",
             [(None, s.get("decode_window_shrinks"))])

    # -- weight residency --------------------------------------------------
    # quantized weight pools shrink resident weight bytes 4x/8x vs f32;
    # the gauge sits next to kv_bytes_resident so HBM budgeting reads
    # both halves of the residency story from one scrape
    d.metric("weight_bytes_resident", "gauge",
             "Bytes of model weights resident on device (pools + "
             "scales), labeled by storage dtype.",
             [({"dtype": s.get("weight_dtype") or "float32"},
               s.get("weight_bytes_resident"))])
    d.metric("weight_bytes_resident_per_shard", "gauge",
             "Largest single shard's resident weight bytes (equals "
             "the total at tp=1).",
             [(None, s.get("weight_bytes_resident_per_shard"))])

    # -- fault tolerance --------------------------------------------------
    d.metric("engine_restarts_total", "counter",
             "Supervised engine rebuilds (crashed or hung steps).",
             [(None, s.get("engine_restarts"))])
    d.metric("uptime_seconds", "gauge",
             "Service uptime (survives engine rebuilds).",
             [(None, s.get("uptime_seconds"))])
    d.metric("quarantined_total", "counter",
             "Sequences retired with finish_reason=numerical_error.",
             [(None, s.get("quarantined"))])
    d.metric("faults_injected_total", "counter",
             "Injected faults fired, by kind (chaos testing).",
             [({"kind": k}, n)
              for k, n in sorted((s.get("fault_injections")
                                  or {}).items())]
             or [(None, 0)])
    d.metric("degradation_state", "gauge",
             "Pressure tier: 0 normal, 1 spec-shrink, 2 admit-pause, "
             "3 evict-parked.", [(None, s.get("degradation_state"))])
    d.metric("degradation_transitions_total", "counter",
             "Degradation tier changes.",
             [(None, s.get("degradation_transitions"))])
    d.metric("parked_evictions_total", "counter",
             "Parked pages proactively evicted under pressure.",
             [(None, s.get("parked_evictions"))])
    d.metric("abort_noops_total", "counter",
             "Aborts of already-finished/unknown request ids (benign).",
             [(None, s.get("abort_noops"))])

    # -- prefix cache and speculation ------------------------------------
    d.metric("prefix_cache_hit_rate", "gauge",
             "Fraction of prompt tokens served from cached KV pages.",
             [(None, s.get("prefix_hit_rate"))])
    d.metric("spec_accept_rate", "gauge",
             "Fraction of speculated draft tokens accepted by verify.",
             [(None, s.get("accept_rate"))])

    # -- hierarchical KV (host spill tier) ---------------------------------
    d.metric("kv_pages_spilled_total", "counter",
             "Pressure-evicted KV pages spilled to the host tier "
             "instead of destroyed.",
             [(None, s.get("kv_pages_spilled"))])
    d.metric("kv_pages_restored_total", "counter",
             "Spilled pages restored HBM-side for returning prefixes.",
             [(None, s.get("kv_pages_restored"))])
    d.metric("kv_spill_dropped_total", "counter",
             "Spill candidates the host tier refused (tier disabled, "
             "page oversized, or unregistered).",
             [(None, s.get("kv_spill_dropped"))])
    d.metric("kv_prefetch_hit_pages_total", "counter",
             "Restored pages that went on to serve a prefix-cache hit.",
             [(None, s.get("kv_prefetch_hit_pages"))])
    d.metric("spill_tier_hit_rate", "gauge",
             "Fraction of spill-tier consults that found the requested "
             "chain hash resident.",
             [(None, s.get("spill_tier_hit_rate"))])
    d.metric("host_kv_bytes", "gauge",
             "Host spill-tier bytes, by kind (resident vs capacity).",
             [({"kind": "resident"}, s.get("host_kv_bytes_resident")),
              ({"kind": "capacity"}, s.get("host_kv_bytes_capacity"))])

    # -- replica routing --------------------------------------------------
    if router is not None:
        d.metric("replicas", "gauge",
                 "Data-parallel engine replicas behind the router.",
                 [(None, router.get("replicas"))])
        d.metric("replica_outstanding_tokens", "gauge",
                 "Routing load estimate per replica: prompt + budget "
                 "tokens submitted and not yet finished.",
                 [({"replica": str(i)}, v) for i, v in
                  enumerate(router.get("outstanding_tokens", []))])
        d.metric("replica_routed_requests_total", "counter",
                 "Requests landed on each replica.",
                 [({"replica": str(i)}, v) for i, v in
                  enumerate(router.get("routed_requests", []))])
        d.metric("replica_affinity_hits_total", "counter",
                 "Requests routed by a prefix-affinity match, per "
                 "replica.",
                 [({"replica": str(i)}, v) for i, v in
                  enumerate(router.get("affinity_hits", []))])

    # -- engine gauges ----------------------------------------------------
    if engine is not None:
        pool = engine.blocks
        d.metric("kv_pages", "gauge",
                 "KV page pool occupancy, by state.",
                 [({"state": "used"}, pool.num_used),
                  ({"state": "free"}, pool.num_free),
                  ({"state": "cached"}, pool.num_cached),
                  ({"state": "spill_pending"},
                   getattr(pool, "num_spill_pending", 0))])
        d.metric("engine_running_seqs", "gauge",
                 "Sequences in the decode batch.",
                 [(None, len(engine._running))])
        d.metric("engine_waiting_seqs", "gauge",
                 "Sequences queued inside the engine for admission.",
                 [(None, len(engine._waiting))])
        d.metric("engine_compiles_total", "counter",
                 "XLA compiles triggered, by program kind.",
                 [({"kind": k}, n)
                  for k, n in sorted(engine.compile_counts.items())])
    return d.render()


def _ms(v):
    return None if v is None else float(v) / 1000.0
