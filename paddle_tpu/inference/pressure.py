"""Graceful degradation under KV-page pressure.

The engine's only built-in answer to pool exhaustion is
preempt-and-recompute: evict a whole running sequence and replay its
prefill later.  That is correct but expensive — and it punishes a
sequence that was making progress.  The ``DegradationController``
interposes cheaper levers *before* preemption becomes necessary, in
escalating tiers keyed on the live free-page fraction:

    NORMAL       full service
    SPEC_SHRINK  halve speculative draft length (verify rows are the
                 biggest transient page consumers)
    ADMIT_PAUSE  stop admitting new sequences; the frontend sheds with
                 429 + a Retry-After derived from the free-page trend
    EVICT_PARKED proactively evict LRU parked (refcount-0 cached)
                 pages a few per step, trading future prefix-cache
                 hits for headroom now.  With a host spill tier
                 attached (inference/kv_tier.py) this lever is
                 SPILL-FIRST: registered pages quarantine for the
                 engine's step-boundary drain and live on host-side
                 instead of dying, so the trade becomes
                 hit-latency-for-headroom rather than hits-for-headroom

Escalation is immediate — a pressure spike engages the right tier the
same step.  De-escalation is hysteretic: the controller steps *one*
tier back toward NORMAL only after ``cooldown_steps`` consecutive
steps above the current tier's exit threshold, and the exit thresholds
sit strictly above the entry thresholds, so the engine cannot flap
between tiers on a noisy free-page signal.
"""
from __future__ import annotations

import time
from collections import deque

__all__ = ["DegradationController", "NORMAL", "SPEC_SHRINK",
           "ADMIT_PAUSE", "EVICT_PARKED", "STATE_NAMES"]

NORMAL = 0
SPEC_SHRINK = 1
ADMIT_PAUSE = 2
EVICT_PARKED = 3

STATE_NAMES = {NORMAL: "normal", SPEC_SHRINK: "spec_shrink",
               ADMIT_PAUSE: "admit_pause", EVICT_PARKED: "evict_parked"}


class DegradationController:
    """Tiered load-shedding state machine over the free-page fraction.

    ``enter[i]`` is the free fraction at or below which tier ``i+1``
    engages; ``exit[i]`` (strictly greater) is the fraction the pool
    must sustain for ``cooldown_steps`` consecutive steps before the
    controller steps back down from tier ``i+1``.
    """

    def __init__(self, *, enter=(0.30, 0.18, 0.10),
                 exit=(0.40, 0.28, 0.20), cooldown_steps: int = 8,
                 evict_batch: int = 4, history: int = 64):
        if len(enter) != 3 or len(exit) != 3:
            raise ValueError("enter/exit must each name 3 tier thresholds")
        for i, (lo, hi) in enumerate(zip(enter, exit)):
            if not hi > lo:
                raise ValueError(
                    f"exit[{i}]={hi} must exceed enter[{i}]={lo} "
                    "(hysteresis gap)")
        self.enter = tuple(float(x) for x in enter)
        self.exit = tuple(float(x) for x in exit)
        self.cooldown_steps = int(cooldown_steps)
        self.evict_batch = int(evict_batch)
        self.state = NORMAL
        self.transitions: list[tuple[int, int, int]] = []  # (step, frm, to)
        self._step = 0
        self._calm = 0
        self._total = 0
        self._history: deque[tuple[float, int]] = deque(maxlen=int(history))

    # -- per-step update ---------------------------------------------------

    def update(self, blocks, spec_reserved: int = 0) -> int:
        """Observe the pool and move the state machine.  Returns the
        (possibly new) state.  Called once per engine step.

        ``spec_reserved`` credits back pages the async engine's
        prestage took SPECULATIVELY for the next launch: at this point
        of a synchronous step they would still be free, so counting
        them as used would skew the free-page fraction (and the
        retry-after trend) against the overlap engine for pages that
        are not real demand yet.

        Parked (refcount-0 cached) pages count as headroom too,
        mirroring ``BlockManager.can_allocate``: the allocator evicts
        them on demand, so they are reclaimable supply, not demand.
        Counting them as used deadlocks a long prefix-caching run —
        retirement parks pages instead of freeing them, the strict
        free fraction ratchets below the ADMIT_PAUSE exit threshold,
        and admission never resumes even though nearly the whole pool
        is evictable on demand.  (Found by replaying sustained traffic
        through the fleet simulator, which shares this controller.)"""
        self._step += 1
        total = blocks.num_blocks - 1  # slot 0 is the null block
        self._total = total
        reclaimable = int(getattr(blocks, "num_cached", 0))
        # spill-quarantined pages are headroom too: they free
        # unconditionally at the next step-boundary drain, so counting
        # them as used would double-escalate the very lever (spill-first
        # EVICT_PARKED) that created them
        reclaimable += int(getattr(blocks, "num_spill_pending", 0))
        free = min(blocks.num_free + reclaimable + int(spec_reserved), total)
        f = free / total if total > 0 else 1.0
        self._history.append((time.monotonic(), free))

        # deepest tier whose entry threshold the pool has breached
        target = NORMAL
        for tier in (EVICT_PARKED, ADMIT_PAUSE, SPEC_SHRINK):
            if f <= self.enter[tier - 1]:
                target = tier
                break

        if target > self.state:
            self._move(target)
            self._calm = 0
        elif self.state > NORMAL:
            # one tier back only after a full calm cooldown above the
            # CURRENT tier's exit threshold
            if f > self.exit[self.state - 1]:
                self._calm += 1
                if self._calm >= self.cooldown_steps:
                    self._move(self.state - 1)
                    self._calm = 0
            else:
                self._calm = 0
        return self.state

    def _move(self, to: int) -> None:
        self.transitions.append((self._step, self.state, to))
        self.state = to

    # -- levers the engine/frontend consult --------------------------------

    @property
    def tier_entries(self) -> int:
        """Escalating transitions so far (NORMAL->worse or worse->worse):
        how many times pressure forced the controller UP a tier.  The
        serve_bench memory-pressure A/B reports this next to preemptions
        — quantized pages must show strictly fewer of both at matched
        traffic."""
        return sum(1 for _, frm, to in self.transitions if to > frm)

    @property
    def admission_paused(self) -> bool:
        return self.state >= ADMIT_PAUSE

    @property
    def evict_now(self) -> bool:
        return self.state >= EVICT_PARKED

    def spec_k_cap(self, max_spec_k: int) -> int:
        """Cap on per-request draft length under the current tier."""
        if self.state == NORMAL:
            return max_spec_k
        if self.state == SPEC_SHRINK:
            return max(1, max_spec_k // 2)
        return 0

    def retry_after_s(self, *, floor: float = 1.0,
                      ceil: float = 30.0) -> float:
        """Estimate seconds until admission resumes, from the live
        free-page trend.  Non-recovering trend → the ceiling."""
        if len(self._history) < 2:
            return ceil
        (t0, p0), (t1, p1) = self._history[0], self._history[-1]
        dt = t1 - t0
        if dt <= 0.0:
            return ceil
        slope = (p1 - p0) / dt  # pages freed per second
        if slope <= 0.0:
            return ceil
        # pages still needed to clear the admission-pause exit threshold
        need = self.exit[ADMIT_PAUSE - 1] * self._total - p1
        if need <= 0.0:
            return floor
        return max(floor, min(ceil, need / slope))
