"""Hierarchical KV: a host-DRAM spill tier below the HBM page pool.

Today KV pressure ends in death: when the ``DegradationController``
escalates to EVICT_PARKED, refcount-0 cached pages are destroyed and a
returning user re-prefills from scratch even though their prefix was
resident seconds ago.  The ``HostSpillPool`` is the tier below HBM
that the ROADMAP names as the path to millions-of-users KV residency
per chip: evicted parked pages spill here instead of dying, keyed by
the same rolling chain hashes the prefix cache and the affinity router
already speak, and admission restores them HBM-side so only the
residual prefill suffix is ever recomputed.

The pool is deliberately dumb about dtypes and layouts: a spilled page
is a named dict of host ``numpy`` arrays (``k``/``v`` for f32 pages;
``kc``/``vc`` plus their f32 ``ks``/``vs`` scale rows for int8 pages)
and the pool only sums ``nbytes``.  That keeps the tier correct by
construction for every KV dtype the engine grows — restored bytes are
the exact bytes that were spilled, which is what pins the serve_bench
A/B byte-identical.

Concurrency: one lock guards the whole pool.  ``insert`` / ``take`` /
``lookup`` run on the engine thread at step boundaries, so their
acquire is uncontended in the common case; ``hint`` is called by the
frontend router at pick time and ``stats`` by whichever thread renders
``/metrics`` — those are the crossings the lock is actually for.
"""
from __future__ import annotations

import threading
from collections import OrderedDict, deque

__all__ = ["HostSpillPool"]


class HostSpillPool:
    """Bounded-byte, LRU, chain-hash-keyed host store of evicted KV pages.

    One entry per spilled HBM block.  A block can be registered under
    several chain hashes (``BlockManager._block_hashes`` is a set), so
    entries index every hash to one shared payload — the bytes are
    stored once.  ``capacity_bytes <= 0`` disables the tier (inserts
    become counted drops); that is also how a tier-off A/B arm is
    expressed without ripping out the plumbing.
    """

    def __init__(self, capacity_bytes: int, *, max_hints: int = 1024):
        self.capacity_bytes = int(capacity_bytes)
        self._lock = threading.Lock()
        self._entries: OrderedDict[int, dict] = OrderedDict()  # eid -> entry
        self._by_hash: dict[int, int] = {}                     # hash -> eid
        self._next_eid = 0
        self._bytes = 0
        # bumped on every successful insert: consumers that cache a
        # "nothing here for me" verdict (the engine's per-waiting-request
        # consult) re-check only when content actually arrived
        self._gen = 0
        # counters (read via stats())
        self.spilled_pages = 0        # successful inserts
        self.restored_pages = 0       # successful takes
        self.dropped_oversized = 0    # page bigger than the whole tier
        self.dropped_evicted = 0      # LRU-evicted to make room
        self.hits = 0                 # lookup/take found the hash
        self.misses = 0               # lookup/take missed
        # cross-thread prefetch hints (router -> engine)
        self._hints: deque[tuple[int, ...]] = deque(maxlen=int(max_hints))
        self.hints_received = 0
        self.hints_dropped = 0        # deque overflow (oldest displaced)

    # -- capacity ----------------------------------------------------------

    @property
    def bytes_resident(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def gen(self) -> int:
        """Content generation: bumps on every successful insert."""
        with self._lock:
            return self._gen

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, h: int) -> bool:
        """Uncounted membership probe (for tests / prefetch planning)."""
        with self._lock:
            return h in self._by_hash

    # -- spill / restore (engine thread) ------------------------------------

    def insert(self, hashes, arrays: dict) -> bool:
        """Store one page under every hash in ``hashes``.

        Returns False (a counted drop) when the page alone exceeds the
        tier capacity; otherwise LRU-evicts resident entries until it
        fits.  A hash that is already resident is re-pointed at the new
        payload — the engine's copy is fresher by construction (it was
        live after the old spill).
        """
        hashes = tuple(int(h) for h in hashes)
        if not hashes:
            return False
        nbytes = int(sum(a.nbytes for a in arrays.values()))
        with self._lock:
            if self.capacity_bytes <= 0 or nbytes > self.capacity_bytes:
                self.dropped_oversized += 1
                return False
            for h in hashes:        # displace any stale entry for these keys
                eid = self._by_hash.get(h)
                if eid is not None:
                    self._drop_entry(eid, counted=False)
            while self._bytes + nbytes > self.capacity_bytes:
                old_eid, _ = next(iter(self._entries.items()))
                self._drop_entry(old_eid, counted=True)
            eid = self._next_eid
            self._next_eid += 1
            self._entries[eid] = {"hashes": hashes, "arrays": dict(arrays),
                                  "nbytes": nbytes}
            for h in hashes:
                self._by_hash[h] = eid
            self._bytes += nbytes
            self.spilled_pages += 1
            self._gen += 1
            return True

    def lookup(self, h: int) -> bool:
        """Counted residency probe — admission's tier consult on a
        prefix-cache miss.  Refreshes LRU recency on hit."""
        with self._lock:
            eid = self._by_hash.get(int(h))
            if eid is None:
                self.misses += 1
                return False
            self._entries.move_to_end(eid)
            self.hits += 1
            return True

    def take(self, h: int) -> dict | None:
        """Pop the page stored under ``h`` for an HBM restore.  Returns
        the entry (``hashes`` tuple + ``arrays`` dict) or None.  Not a
        counted consult — ``lookup`` is the hit/miss surface; ``take``
        only moves bytes.

        Take-not-copy: once restored the page is registered back in the
        HBM prefix cache, so a host copy would be a second, staler
        replica that could shadow future spills of the same chain.
        """
        with self._lock:
            eid = self._by_hash.get(int(h))
            if eid is None:
                return None
            entry = self._entries.pop(eid)
            for hh in entry["hashes"]:
                self._by_hash.pop(hh, None)
            self._bytes -= entry["nbytes"]
            self.restored_pages += 1
            return {"hashes": entry["hashes"], "arrays": entry["arrays"]}

    def _drop_entry(self, eid: int, *, counted: bool) -> None:  # guarded-by: _lock
        entry = self._entries.pop(eid)
        for h in entry["hashes"]:
            self._by_hash.pop(h, None)
        self._bytes -= entry["nbytes"]
        if counted:
            self.dropped_evicted += 1

    # -- prefetch hints (router thread -> engine thread) ---------------------

    def hint(self, hashes) -> None:
        """Queue a returning request's chain hashes for pre-staging.
        Thread-safe; called by the frontend router at pick time."""
        hashes = tuple(int(h) for h in hashes)
        if not hashes:
            return
        with self._lock:
            if len(self._hints) == self._hints.maxlen:
                self.hints_dropped += 1
            self._hints.append(hashes)
            self.hints_received += 1

    def drain_hints(self) -> list:
        """Engine thread: pop every queued hint (oldest first)."""
        with self._lock:
            if not self._hints:
                return []
            out = list(self._hints)
            self._hints.clear()
        return out

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            lookups = self.hits + self.misses
            return {
                "capacity_bytes": self.capacity_bytes,
                "bytes_resident": self._bytes,
                "entries": len(self._entries),
                "spilled_pages": self.spilled_pages,
                "restored_pages": self.restored_pages,
                "dropped_oversized": self.dropped_oversized,
                "dropped_evicted": self.dropped_evicted,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / lookups, 4) if lookups else 0.0,
                "hints_received": self.hints_received,
                "hints_dropped": self.hints_dropped,
            }
