"""Speculative decoding over the paged KV cache: propose -> verify ->
accept/rollback.

Plain continuous-batching decode (inference/serving.py) pays one full
forward pass per emitted token.  Speculative decoding (Leviathan et al.,
"Fast Inference from Transformers via Speculative Decoding") breaks that
coupling: a cheap DRAFTER proposes K tokens, the target model scores all
K+1 positions in ONE pass (a [last_token, drafts...] row of the
engine's single ragged step program, whose raw logits at every packed
position ride along with the sampled tokens), and rejection sampling
accepts a prefix of the drafts.  Acceptance is provably exact:

- temperature 0: a draft is accepted iff it equals the target argmax at
  its position, and the first rejection emits that argmax — so the
  output stream is byte-identical to plain decode, by induction.
- sampled: accept draft d with probability min(1, p(d)/q(d)) where p is
  the target distribution (the FULL LogitProcessor chain — penalty,
  temperature, top-k, top-p — via sampling.target_dist) and q the draft
  distribution; on rejection, resample from max(p - q, 0) renormalized.
  The emitted token is distributed exactly as p, so the sampled stream
  follows the target distribution — the drafter only changes HOW FAST
  tokens arrive, never WHICH distribution they come from.

Both shipped drafters propose deterministically, making q one-hot: the
accept probability collapses to p(draft) and the rejection residual to p
with the draft zeroed out, which keeps the host-side math cheap and the
exactness argument one line.

Rejected drafts leave garbage K/V in the pages the verify step wrote;
``BlockManager.truncate`` rolls the table back (releasing empty tail
pages and scrubbing content hashes so the prefix cache never serves
rolled-back K/V).

Drafters
--------
``NGramDrafter``: prompt-lookup decoding — find the longest recent
n-gram suffix that occurred earlier in the context and propose the
tokens that followed it.  Zero extra model FLOPs, pure host work; wins
on repetitive text (code, structured output, self-repeating loops).

``DraftModelDrafter``: a small draft model with its OWN paged cache,
embedded as a private single-slot LLMEngine used purely as a
program/pool container.  Catch-up tokens and subsequent drafts
each ride a single-row launch of the engine's ragged step program, and
the engine's post-verify ``commit`` truncates the draft cache back
to the accepted prefix so both caches stay in lock-step.
"""
from __future__ import annotations

import numpy as np

from .kv_cache import NULL_BLOCK
from .sampling import make_samp, target_dist

__all__ = ["Drafter", "NGramDrafter", "DraftModelDrafter",
           "verify_and_accept"]


class Drafter:
    """Proposes draft tokens for a running sequence.

    ``propose(rid, context, k)`` returns ``(drafts, q_dists)`` — up to k
    proposed token ids and, for stochastic drafters, the [len(drafts), V]
    proposal distributions q (None means deterministic proposals, i.e.
    one-hot q).  Returning ``([], None)`` opts the sequence out of
    speculation for this step (it plain-decodes).

    ``commit(rid, n_valid)`` is called after each verify round with the
    sequence's accepted length (prompt + emitted tokens whose identity
    the drafter may rely on); stateful drafters roll their own caches
    back here.  ``release(rid)`` drops all per-sequence state (retire or
    preemption).
    """

    def propose(self, rid, context, k):  # pragma: no cover - interface
        raise NotImplementedError

    def commit(self, rid, n_valid):
        pass

    def release(self, rid):
        pass


class NGramDrafter(Drafter):
    """Prompt-lookup decoding: match the context's trailing n-gram
    against earlier context and propose the continuation of its most
    recent prior occurrence.  Longest n wins; stateless and free."""

    def __init__(self, *, max_ngram: int = 3, min_ngram: int = 1,
                 max_context: int = 2048):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self.max_context = int(max_context)

    def propose(self, rid, context, k):
        ctx = list(context[-self.max_context:])
        L = len(ctx)
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            pat = ctx[L - n:]
            for i in range(L - n - 1, -1, -1):
                if ctx[i:i + n] == pat:
                    cont = ctx[i + n:i + n + k]
                    if cont:
                        return cont, None
                    break
        return [], None


class DraftModelDrafter(Drafter):
    """Small-draft-model proposals with their own paged KV cache.

    The inner LLMEngine is a CONTAINER, not a scheduler: this class
    drives its ragged step program by hand, one sequence per call, so
    the draft cache lives in the same kind of paged pool (and rolls
    back through the same ``truncate``) as the target's.
    ``capacity`` bounds how many sequences can hold draft state at once
    — a pool-exhausted proposal returns ``([], None)`` and the engine
    falls back to plain decode for that sequence.
    """

    def __init__(self, model, *, block_size: int = 16,
                 max_model_len: int | None = None, capacity: int = 8,
                 catchup_bucket: int = 64, kv_dtype: str = "float32"):
        from .serving import LLMEngine   # deferred: serving imports us

        nblk = -(-int(max_model_len or model.config.max_position_embeddings)
                 // int(block_size))
        # kv_dtype rides through so a quantized target engine can keep
        # its draft cache quantized too (half the reason to quantize is
        # freeing HBM for MORE resident state, drafts included)
        self._eng = LLMEngine(
            model, max_num_seqs=1, block_size=block_size,
            num_blocks=1 + int(capacity) * nblk,
            max_model_len=max_model_len,
            max_prefill_tokens=int(catchup_bucket),
            prefill_token_bucket=int(catchup_bucket),
            enable_prefix_caching=False, kv_dtype=kv_dtype)
        self._valid: dict = {}            # rid -> tokens with draft K/V

    @property
    def engine(self):
        return self._eng

    def propose(self, rid, context, k):
        eng = self._eng
        bm = eng.blocks
        n = len(context)
        k = min(int(k), eng.max_model_len - n)
        if k <= 0 or n == 0:
            return [], None
        if rid not in self._valid or not bm.has(rid):
            if not bm.allocate(rid, n):
                return [], None
            self._valid[rid] = 0
        if not bm.ensure(rid, n + k):
            self.release(rid)
            return [], None
        # catch up: feed every context token not yet in the draft cache
        # (at least the newest one) through one ragged chunk row, then
        # greedy-decode the remaining drafts one token at a time
        st = min(self._valid.get(rid, 0), n - 1)
        tok = self._chunk(rid, context[st:], st)
        drafts = [tok]
        pos = n
        while len(drafts) < k:
            tok = self._decode(rid, tok, pos)
            drafts.append(tok)
            pos += 1
        self._valid[rid] = n + len(drafts) - 1
        return drafts, None

    def commit(self, rid, n_valid):
        eng = self._eng
        if rid in self._valid and eng.blocks.has(rid):
            eng.blocks.truncate(rid, int(n_valid))
            self._valid[rid] = min(self._valid[rid], int(n_valid))

    def release(self, rid):
        if self._eng.blocks.has(rid):
            self._eng.blocks.free(rid)
        self._valid.pop(rid, None)

    def _chunk(self, rid, gap, start):
        # one single-row ragged launch: the gap enters at absolute
        # positions start..start+g-1, greedy-sampling the last position
        eng = self._eng
        g = len(gap)
        Tq = eng._ragged_bucket(g)
        toks = np.zeros((Tq,), np.int32)
        toks[:g] = gap
        cu = np.asarray([0, g], np.int32)
        kvl = np.asarray([start + g], np.int32)
        bt = np.full((2, eng.nblk), NULL_BLOCK, np.int32)
        bt[0] = eng.blocks.padded_table(rid, eng.nblk)
        lidx = np.asarray([g - 1], np.int32)
        samp = make_samp(1, eng.config.vocab_size)    # greedy defaults
        sampled, _, _ = eng._launch_ragged(Tq, toks, cu, kvl, bt, lidx,
                                           samp, g)
        return int(np.asarray(sampled)[0])

    def _decode(self, rid, tok, pos):
        # a decode token is just a one-token ragged row (same program)
        eng = self._eng
        toks = np.asarray([tok], np.int32)
        cu = np.asarray([0, 1], np.int32)
        kvl = np.asarray([pos + 1], np.int32)
        bt = np.full((2, eng.nblk), NULL_BLOCK, np.int32)
        bt[0] = eng.blocks.padded_table(rid, eng.nblk)
        lidx = np.zeros((1,), np.int32)
        samp = make_samp(1, eng.config.vocab_size)    # greedy defaults
        sampled, _, _ = eng._launch_ragged(eng._ragged_bucket(1), toks,
                                           cu, kvl, bt, lidx, samp, 1)
        return int(np.asarray(sampled)[0])


def verify_and_accept(logits, drafts, *, q_dists=None, temperature=0.0,
                      top_k=0, top_p=1.0, penalty=1.0, seen=None,
                      rng=None):
    """Rejection-sampling acceptance for ONE sequence's verify logits.

    logits: [k+1, V] target logits — row i is the position that feeds
    draft i (row k is the bonus position after the last draft).
    drafts: the k proposed tokens.  q_dists: [k, V] proposal
    distributions, or None for deterministic (one-hot) drafters.
    seen: the request's repetition-penalty mask (mutated in place as
    tokens are accepted, exactly as sequential decode would grow it).
    rng: numpy Generator for the sampled path (None is fine for greedy).

    Returns ``(n_accepted, emitted)`` — emitted is the accepted draft
    prefix plus exactly one more token: the rejection resample, or the
    bonus token when every draft survived.  Each emitted token is
    distributed exactly as plain decode at its position.
    """
    lg = np.asarray(logits, np.float32)
    k = len(drafts)
    greedy = temperature <= 0.0
    emitted = []

    def dist(i):
        return target_dist(lg[i], temperature=temperature, top_k=top_k,
                           top_p=top_p, penalty=penalty, seen=seen)

    def note(tok):
        if seen is not None:
            seen[tok] = True

    for i, d in enumerate(drafts):
        d = int(d)
        p = dist(i)
        if greedy:
            if p[d] > 0.0:                       # d IS the argmax
                emitted.append(d)
                note(d)
                continue
            g = int(np.argmax(p))
            emitted.append(g)
            note(g)
            return i, emitted
        q = None if q_dists is None else np.asarray(q_dists[i], np.float32)
        qd = 1.0 if q is None else float(q[d])
        ratio = p[d] / qd if qd > 0.0 else 0.0
        if float(rng.uniform()) < min(1.0, ratio):
            emitted.append(d)
            note(d)
            continue
        # rejected: resample from the residual max(p - q, 0); one-hot q
        # zeroes only the draft itself
        if q is None:
            res = p.copy()
            res[d] = 0.0
        else:
            res = np.maximum(p - q, 0.0)
        s = float(res.sum())
        res = res / s if s > 0.0 else p
        t = int(np.searchsorted(np.cumsum(res), rng.uniform(), side="right"))
        t = min(t, len(res) - 1)
        emitted.append(t)
        note(t)
        return i, emitted

    # every draft accepted: the bonus position emits one more token
    p = dist(k)
    if greedy:
        t = int(np.argmax(p))
    else:
        t = int(np.searchsorted(np.cumsum(p), rng.uniform(), side="right"))
        t = min(t, len(p) - 1)
    emitted.append(t)
    note(t)
    return k, emitted
