"""Deterministic fault injection for the serving tier.

A ``FaultPlan`` is a seeded, step-indexed schedule of failures the chaos
harness drives through the engine: raised step exceptions, artificially
slow steps, NaN-corrupted logit rows, a simulated pool-exhaustion
window, and injected client disconnects at the frontend.  The plan owns
a single global step counter that the engine advances exactly once per
``LLMEngine.step`` call; because the plan object is carried across an
engine rebuild (the runner re-installs it on the replacement engine)
and consumed faults never re-fire, a schedule like "crash at step 5,
NaN at step 12" means what it says even when steps 6-8 were lost to the
restart that crash 5 triggered.

Fault firing is "current step >= scheduled step and not yet consumed"
rather than strict equality — a fault scheduled inside a window the
engine never reaches exactly (because a restart skipped it, or because
no launch happened that step) stays armed until the next opportunity.

Every engine seam guards on ``self.fault_plan is None`` first, so an
engine without a plan pays a single attribute check per step and
nothing else.
"""
from __future__ import annotations

import random

__all__ = ["FaultPlan", "InjectedFault"]


class InjectedFault(RuntimeError):
    """Raised by a FaultPlan crash fault inside LLMEngine.step."""


class FaultPlan:
    """A deterministic schedule of injected serving faults.

    Parameters
    ----------
    seed:
        Seeds the internal RNG used to pick NaN row indices.
    crash_steps:
        Plan steps at which ``take_crash`` fires (raise inside step).
    slow_steps:
        ``{step: seconds}`` — ``take_slow`` returns the sleep duration
        once per scheduled entry.
    nan_steps:
        Plan steps at which one live logit row is corrupted.  The fault
        stays armed across steps with no launch (a step may admit work
        without launching the program) and fires at the next launch.
    pool_window:
        ``(start, end)`` inclusive plan-step window during which the
        BlockManager reports the pool exhausted (allocation pressure
        without actually shrinking the pool).
    conn_drop_requests:
        Ordinals (0-based) of *streaming* frontend requests whose
        connection is dropped server-side after the first token frame.
    inflight_crash_steps / inflight_slow_steps:
        Like ``crash_steps``/``slow_steps`` but fired from the engine's
        COMPLETION seam, while the scheduled step's launch is genuinely
        in flight on-device (overlap mode only — a synchronous engine
        never leaves a launch in flight, so these seams never fire
        there).  Step indices are keyed on completion order, which the
        depth-1 pipeline keeps equal to dispatch order: "in-flight
        crash at step 5" dies between step 5's launch and its
        materialization, after step 4's outputs were delivered.
    """

    def __init__(self, *, seed: int = 0, crash_steps=(), slow_steps=None,
                 nan_steps=(), pool_window=None, conn_drop_requests=(),
                 inflight_crash_steps=(), inflight_slow_steps=None):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self.step = 0
        self._crash = sorted(int(s) for s in crash_steps)
        self._slow = sorted((int(s), float(d))
                            for s, d in (slow_steps or {}).items())
        self._inflight_crash = sorted(int(s) for s in inflight_crash_steps)
        self._inflight_slow = sorted(
            (int(s), float(d))
            for s, d in (inflight_slow_steps or {}).items())
        self._nan = sorted(int(s) for s in nan_steps)
        self.pool_window = (None if pool_window is None
                            else (int(pool_window[0]), int(pool_window[1])))
        self._pool_entered = False
        self._conn_drop = frozenset(int(i) for i in conn_drop_requests)
        self._stream_ordinal = 0
        # step-timeline hook: the owning engine's set_tracer/set_fault_plan
        # install these so every fired fault lands in the trace as an
        # instant; None keeps each take_* at one extra attribute check
        self.tracer = None
        self.trace_track = "engine"

    def _trace(self, kind: str, **args) -> None:
        tr = self.tracer
        if tr is not None:
            args["step"] = self.step
            tr.instant("fault." + kind, track=self.trace_track, args=args)

    @classmethod
    def seeded(cls, seed: int, *, n_crash: int = 1, n_nan: int = 1,
               n_slow: int = 1, slow_s: float = 1.0,
               pool_window_len: int = 4, horizon: int = 40,
               n_conn_drop: int = 0, n_requests: int = 0) -> "FaultPlan":
        """Derive a full chaos schedule from one seed.

        Faults are spread over ``[2, horizon)`` so step 0/1 (first
        compiles) stay clean and the schedule is reproducible for a
        given (seed, horizon).
        """
        rng = random.Random(seed)
        steps = list(range(2, max(horizon, 10)))
        rng.shuffle(steps)
        it = iter(steps)
        crash = sorted(next(it) for _ in range(n_crash))
        nan = sorted(next(it) for _ in range(n_nan))
        slow = {next(it): slow_s for _ in range(n_slow)}
        pool = None
        if pool_window_len > 0:
            start = next(it)
            pool = (start, start + pool_window_len - 1)
        drops = ()
        if n_conn_drop and n_requests:
            drops = rng.sample(range(n_requests),
                               min(n_conn_drop, n_requests))
        return cls(seed=seed, crash_steps=crash, slow_steps=slow,
                   nan_steps=nan, pool_window=pool,
                   conn_drop_requests=drops)

    # -- engine-step seams -------------------------------------------------

    def advance(self) -> None:
        """Advance the global plan step.  Called once per engine step,
        by whichever engine currently holds the plan."""
        self.step += 1

    def take_crash(self) -> bool:
        """True once per scheduled crash whose step has been reached."""
        if self._crash and self.step >= self._crash[0]:
            self._crash.pop(0)
            self._trace("crash")
            return True
        return False

    def take_slow(self) -> float:
        """Sleep seconds for a due slow-step fault, else 0.0."""
        if self._slow and self.step >= self._slow[0][0]:
            dur = self._slow.pop(0)[1]
            self._trace("slow", seconds=dur)
            return dur
        return 0.0

    def take_inflight_crash(self) -> bool:
        """True once per scheduled in-flight crash whose step has been
        reached.  The engine consults this at the top of its completion
        seam, only when the ticket it is about to block on genuinely
        crossed a step boundary in flight."""
        if self._inflight_crash and self.step >= self._inflight_crash[0]:
            self._inflight_crash.pop(0)
            self._trace("inflight_crash")
            return True
        return False

    def take_inflight_slow(self) -> float:
        """Sleep seconds for a due in-flight hang fault, else 0.0.
        Fired from the completion seam like ``take_inflight_crash`` —
        the hang sits between a launch and its materialization, where
        the runner's step-deadline watchdog must still catch it."""
        if self._inflight_slow and self.step >= self._inflight_slow[0][0]:
            dur = self._inflight_slow.pop(0)[1]
            self._trace("inflight_slow", seconds=dur)
            return dur
        return 0.0

    def take_nan_row(self, n_rows: int) -> int | None:
        """Row index to corrupt in the current launch, or None.

        Armed once the plan step reaches the next scheduled NaN step;
        fires at the first launch with at least one live row after
        that, so a no-launch step cannot silently swallow the fault.
        """
        if n_rows > 0 and self._nan and self.step >= self._nan[0]:
            self._nan.pop(0)
            row = self._rng.randrange(n_rows)
            self._trace("nan", row=row)
            return row
        return None

    # -- pool seam ---------------------------------------------------------

    def pool_exhausted(self) -> bool:
        """True while the plan step is inside the exhaustion window.
        Installed as ``BlockManager._fault_hook``."""
        if self.pool_window is None:
            return False
        lo, hi = self.pool_window
        return lo <= self.step <= hi

    def take_pool_entry(self) -> bool:
        """True exactly once, the first step the pool window is active
        (for fault-injection accounting)."""
        if not self._pool_entered and self.pool_exhausted():
            self._pool_entered = True
            self._trace("pool", window=list(self.pool_window))
            return True
        return False

    # -- frontend seam -----------------------------------------------------

    def take_conn_drop(self) -> bool:
        """True when the current streaming request's ordinal is in the
        drop set.  Called once per streaming request, in arrival
        order."""
        i = self._stream_ordinal
        self._stream_ordinal += 1
        if i in self._conn_drop:
            self._trace("conn", ordinal=i)
            return True
        return False

    # -- introspection -----------------------------------------------------

    def exhausted(self) -> bool:
        """True once every scheduled engine-side fault has fired."""
        return not (self._crash or self._slow or self._nan
                    or self._inflight_crash or self._inflight_slow)

    def __repr__(self):
        return (f"FaultPlan(step={self.step}, crash={self._crash}, "
                f"slow={self._slow}, nan={self._nan}, "
                f"pool={self.pool_window}, "
                f"inflight_crash={self._inflight_crash}, "
                f"inflight_slow={self._inflight_slow})")
