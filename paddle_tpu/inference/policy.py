"""Pure host-side serving policies, shared by the engine and the fleet
simulator.

The fleet simulator (``paddle_tpu/sim``) models the serving tiers as a
discrete-event system, but the DECISIONS those tiers make — which
prefill chunks a step packs, which replica a request lands on, how many
host round trips a decode window costs — are plain host Python with no
device state.  Duplicating them in the simulator would let the model
drift from the engine; instead the decision cores live here, stdlib
only, and BOTH sides call them:

    pack_prefill_chunks     the FCFS token-budget chunking rule
                            ``LLMEngine._schedule_prefill_chunks`` packs
                            a step with (serving.py calls it with the
                            CoW-resolution hook; the simulator calls it
                            with a pool-capacity hook)
    pick_replica            the routing decision inside
                            ``ReplicaRouter._pick`` (affinity / least /
                            random), lifted out so the simulator routes
                            synthetic fleets with the SAME tie-breaks
    window_chunks           the decode-window launch plan: how a K-step
                            window slices a row's remaining budget into
                            launches, i.e. the host-round-trip
                            accounting ``serve_bench --decode-window``
                            measures

Everything here is deterministic given its inputs; any randomness comes
in through a caller-owned ``random.Random`` (the random routing policy),
never from module state.
"""
from __future__ import annotations

__all__ = ["pack_prefill_chunks", "pick_replica", "window_chunks"]


def pack_prefill_chunks(candidates, budget: int, admit=None, out=None):
    """FCFS prefill-chunk packing under a per-step token budget.

    ``candidates``: (key, remaining_tokens) pairs already in FCFS
    (arrival) order.  ``admit``: optional predicate called just before a
    candidate takes budget; returning False skips it WITHOUT consuming
    budget (the engine hangs copy-on-write resolution here — a CoW
    preemption may also retroactively remove an earlier chunk from
    ``out``, which is why the accumulator is caller-visible).  ``out``:
    the list chunks are appended to (default: a fresh list).

    Returns ``out`` holding (key, chunk_len) pairs with
    ``sum(chunk_len) <= budget``: each candidate takes
    ``min(remaining, budget_left)`` — a long prompt takes whatever
    budget is left and resumes next step, so one 4096-token prompt
    never stalls running decodes.
    """
    chunks = out if out is not None else []
    budget = int(budget)
    for key, rem in candidates:
        if budget <= 0:
            break
        if rem <= 0:
            continue
        if admit is not None and not admit(key):
            continue
        take = min(int(rem), budget)
        chunks.append((key, take))
        budget -= take
    return chunks


def pick_replica(policy: str, hashes, registries, outstanding, rng=None):
    """One routing decision: ``(replica_index, was_affinity_hit)``.

    ``hashes``: the prompt's leading page chain hashes (empty disables
    affinity matching).  ``registries``: per-replica containers
    supporting ``in`` over those hashes.  ``outstanding``: per-replica
    outstanding-token loads.  ``rng``: a caller-seeded random.Random,
    consulted only by the "random" policy.

    Policy semantics (the ``ReplicaRouter`` contract, bit for bit):

    * random — uniform choice from ``rng``.
    * affinity — the replica matching the LONGEST leading run of page
      hashes wins; equal runs > 0 break to the lower outstanding load;
      no match anywhere falls through to least.
    * least — lowest outstanding-token load, ties to the LOWEST index
      (``min`` is stable), so a drained fleet fills deterministically.
    """
    n = len(outstanding)
    if policy == "random":
        return rng.randrange(n), False
    if policy == "affinity" and hashes:
        best, best_run = None, 0
        for i in range(n):
            reg = registries[i]
            run = 0
            for h in hashes:              # leading run: prefix pages chain
                if h not in reg:
                    break
                run += 1
            if run > best_run or (run == best_run and run > 0
                                  and outstanding[i] < outstanding[best]):
                best, best_run = i, run
        if best_run > 0:
            return best, True
    # least-outstanding-tokens; ties -> lowest index (min is stable)
    return min(range(n), key=lambda i: outstanding[i]), False


def window_chunks(remaining: int, k: int):
    """Decode-window launch plan for one row with ``remaining`` budget
    tokens left: the sequence of per-launch window lengths the engine's
    ``min(K, budget_left)`` reservation rule produces.  ``len(result)``
    is the row's host-round-trip count — the accounting behind
    ``decode_window_host_round_trips_per_token`` falling from ~1.0
    toward ~1/K when the window engages."""
    remaining = int(remaining)
    k = max(1, int(k))
    out = []
    while remaining > 0:
        take = min(k, remaining)
        out.append(take)
        remaining -= take
    return out
