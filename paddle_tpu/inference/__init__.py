"""Inference API (reference python/paddle/inference/): Config /
create_predictor / Predictor over the serving artifact.

The reference's engine is a C++ runtime executing a translated program
with TensorRT/oneDNN backends; this framework's serving artifact is the
compiled StableHLO program saved by ``jit.save`` — already ahead-of-time
traced, fused and portable — so the Predictor is a thin, zero-copy
executor over ``jit.load`` with the familiar handle-based API
(get_input_names / get_input_handle / run / get_output_handle).
TensorRT/XPU/oneDNN knobs are accepted and recorded but are no-ops: XLA
owns codegen on TPU.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Config", "Predictor", "PredictorPool", "Tensor",
           "create_predictor", "get_version", "DataType", "PlaceType",
           "PrecisionType", "get_num_bytes_of_data_type",
           "convert_to_mixed_precision",
           "BlockManager", "BlockPoolExhausted", "LLMEngine", "Request",
           "RequestOutput", "Drafter", "NGramDrafter", "DraftModelDrafter",
           "FaultPlan", "InjectedFault", "DegradationController",
           "HostSpillPool"]


def __getattr__(name):
    # serving engine loads lazily: importing paddle_tpu.inference must not
    # pull jax/model code for Predictor-only users
    if name in ("LLMEngine", "Request", "RequestOutput"):
        from .serving import LLMEngine, Request, RequestOutput
        return {"LLMEngine": LLMEngine, "Request": Request,
                "RequestOutput": RequestOutput}[name]
    if name in ("BlockManager", "BlockPoolExhausted"):
        from .kv_cache import BlockManager, BlockPoolExhausted
        return {"BlockManager": BlockManager,
                "BlockPoolExhausted": BlockPoolExhausted}[name]
    if name in ("Drafter", "NGramDrafter", "DraftModelDrafter"):
        from . import spec_decode
        return getattr(spec_decode, name)
    if name in ("FaultPlan", "InjectedFault"):
        from . import faults
        return getattr(faults, name)
    if name == "DegradationController":
        from .pressure import DegradationController
        return DegradationController
    if name == "HostSpillPool":
        from .kv_tier import HostSpillPool
        return HostSpillPool
    raise AttributeError(name)


class DataType:
    FLOAT32 = "float32"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    INT8 = "int8"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    BOOL = "bool"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    XPU = "xpu"
    CUSTOM = "custom"


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


def get_num_bytes_of_data_type(dtype) -> int:
    return int(np.dtype(str(dtype)).itemsize)


def get_version() -> str:
    from .. import __version__
    return f"paddle_tpu {__version__} (StableHLO serving)"


class Config:
    """Predictor configuration (reference inference Config).  Model path
    is the ``jit.save`` prefix; accelerator-specific switches are
    recorded for API parity but XLA owns compilation."""

    def __init__(self, prog_file=None, params_file=None):
        self._prefix = None
        self._device = "tpu"
        self._device_id = 0
        self._flags = {}
        if prog_file is not None:
            self._set_prefix(prog_file)

    # -- model location --
    def _set_prefix(self, path):
        # jit.save artifacts share one prefix; accept any artifact name
        p = str(path)
        for suffix in (".pdmodel", ".pdiparams.npz", ".pdiparams"):
            if p.endswith(suffix):
                p = p[: -len(suffix)]
                break
        self._prefix = p

    def set_prog_file(self, path):
        self._set_prefix(path)

    def prog_file(self):
        return None if self._prefix is None else self._prefix + ".pdmodel"

    def params_file(self):
        return None if self._prefix is None \
            else self._prefix + ".pdiparams.npz"

    def set_model(self, prog_file, params_file=None):
        # params live beside the program under the shared prefix; an
        # explicit params_file must agree with it
        self._set_prefix(prog_file)
        if params_file is not None:
            want = self.params_file()
            got = str(params_file)
            if got not in (want, want[: -len(".npz")], self._prefix):
                raise ValueError(
                    f"params_file {got!r} does not match the prefix "
                    f"({want}); jit.save artifacts share one prefix")

    # -- device selection --
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=PrecisionType.Float32):
        self._device, self._device_id = "tpu", device_id   # TPU build

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device != "cpu"

    def gpu_device_id(self):
        return self._device_id

    # -- parity no-ops (recorded) --
    def _noop(self, name):
        def f(*a, **k):
            self._flags[name] = (a, k)
        return f

    def __getattr__(self, name):
        if name.startswith(("enable_", "disable_", "switch_", "set_")):
            return self._noop(name)
        raise AttributeError(name)

    def summary(self):
        return (f"Config(prefix={self._prefix!r}, device={self._device}, "
                f"recorded_flags={sorted(self._flags)})")


class Tensor:
    """Handle over one predictor input/output slot (reference
    inference Tensor): copy_from_cpu / copy_to_cpu / shape."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(np.asarray(self._value).shape)

    def reshape(self, shape):
        self._value = np.asarray(self._value).reshape(shape)


class Predictor:
    """Executes the saved StableHLO program (reference Predictor over the
    C++ engine).  Input arity/order come from the exported signature."""

    def __init__(self, config: Config):
        from ..jit import load as jit_load

        if config._prefix is None:
            raise ValueError("Config has no model path (set_model)")
        self._layer = jit_load(config._prefix)
        n_in = len(self._layer._exported.in_avals) \
            - len(self._layer._loaded_params) \
            - len(self._layer._loaded_buffers)
        self._inputs = [Tensor(f"x{i}") for i in range(max(n_in, 0))]
        self._outputs = []
        self.config = config

    def get_input_names(self):
        return [t.name for t in self._inputs]

    def get_input_handle(self, name):
        for t in self._inputs:
            if t.name == name:
                return t
        raise KeyError(name)

    def run(self, inputs=None):
        """Handle-based (no args, copy_from_cpu beforehand) or direct
        (list of arrays -> list of arrays) execution."""
        direct = inputs is not None
        feed = inputs if direct else [t._value for t in self._inputs]
        if any(v is None for v in feed):
            missing = [t.name for t in self._inputs if t._value is None]
            raise ValueError(f"inputs not set: {missing}")
        out = self._layer.forward(*feed)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        arrays = [np.asarray(o.numpy()) for o in outs]
        self._outputs = []
        for i, a in enumerate(arrays):
            t = Tensor(f"out{i}")
            t._value = a
            self._outputs.append(t)
        return arrays if direct else True

    def get_output_names(self):
        return [t.name for t in self._outputs]

    def get_output_handle(self, name):
        for t in self._outputs:
            if t.name == name:
                return t
        raise KeyError(name)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class PredictorPool:
    """A pool of predictors over one model (reference PredictorPool);
    under XLA the compiled program is shared, so pool members are cheap."""

    def __init__(self, config: Config, size: int = 1):
        first = Predictor(config)
        self._preds = [first]
        for _ in range(size - 1):
            p = Predictor.__new__(Predictor)
            p._layer = first._layer
            p._inputs = [Tensor(t.name) for t in first._inputs]
            p._outputs = []
            p.config = config
            self._preds.append(p)

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]


def convert_to_mixed_precision(src_model_file, src_params_file,
                               dst_model_file, dst_params_file,
                               mixed_precision=PrecisionType.Bfloat16,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """Rewrite a saved fp32 program to mixed precision (reference
    convert_to_mixed_precision, analysis_predictor.h:101 /
    convert_to_mixed_model tooling).

    The serialized artifact is re-exported with every floating-point
    parameter stored in the reduced dtype and up-cast at program entry
    (a cast XLA fuses into the first consumer) — halving parameter
    memory and HBM traffic.  On TPU this is the whole story for compute
    too: XLA's default matmul precision already runs fp32 contractions
    as bf16 MXU passes, so op-level compute matches the reference's
    mixed program without rewriting op dtypes.  ``keep_io_types=True``
    (default, reference semantics) keeps the program's input/output
    dtypes as exported; ``False`` converts floating io to the reduced
    dtype.  ``black_list`` is accepted for parity (per-op precision is
    governed by XLA on TPU, not by the serialized program).
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export

    from ..jit import load as jit_load

    low = jnp.dtype(str(mixed_precision))
    if not jnp.issubdtype(low, jnp.floating):
        raise ValueError(f"mixed_precision must be a float dtype, got "
                         f"{mixed_precision}")

    # src/dst params files must share their model file's prefix (the
    # jit.save artifact contract, same validation as Config.set_model)
    src_cfg = Config(str(src_model_file))
    if src_params_file is not None:
        src_cfg.set_model(str(src_model_file), str(src_params_file))
    dst_cfg = Config(str(dst_model_file))
    if dst_params_file is not None:
        dst_cfg.set_model(str(dst_model_file), str(dst_params_file))
    dst_prefix = dst_cfg._prefix

    layer = jit_load(src_cfg._prefix)
    exported = layer._exported

    params = {k: p._data for k, p in layer._loaded_params.items()}
    buffers = dict(layer._loaded_buffers)
    n_state = len(params) + len(buffers)
    input_avals = list(exported.in_avals)[n_state:]

    def _is_f(d):
        return jnp.issubdtype(d, jnp.floating)

    # dst-side stored dtypes: floats drop to `low`, everything else kept
    low_params = {k: (v.astype(low) if _is_f(v.dtype) else v)
                  for k, v in params.items()}

    def pure(low_p, bufs, *in_arrays):
        full_p = {k: (v.astype(params[k].dtype)
                      if _is_f(v.dtype) else v) for k, v in low_p.items()}
        cast_in = [x.astype(a.dtype)
                   if _is_f(a.dtype) and x.dtype != a.dtype else x
                   for x, a in zip(in_arrays, input_avals)]
        out = exported.call(full_p, bufs, *cast_in)
        if keep_io_types:
            return out
        return jax.tree.map(
            lambda o: o.astype(low) if _is_f(o.dtype) else o, out)

    p_structs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                 for k, v in low_params.items()}
    b_structs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                 for k, v in buffers.items()}
    in_structs = [
        jax.ShapeDtypeStruct(
            a.shape, low if (not keep_io_types and _is_f(a.dtype))
            else a.dtype)
        for a in input_avals]
    new_exported = jax_export.export(jax.jit(pure))(p_structs, b_structs,
                                                    *in_structs)
    with open(dst_prefix + ".pdmodel", "wb") as f:
        f.write(new_exported.serialize())
    from ..jit import save_params_npz
    save_params_npz(dst_prefix, low_params, buffers)
