"""Continuous-batching decode engine over the paged-KV Pallas kernel.

The r5 kernel work (ops/pallas/paged_attention.py) gave single-token
decode over paged KV; what was missing is the ENGINE that serves a stream
of requests through it (the reference's serving stack around
block_multi_head_attention; vLLM's engine shape).  Three pieces:

- ``BlockManager`` (inference/kv_cache.py): a fixed page pool with
  per-sequence block tables — admission claims pages, decode grows them
  one page at a time, retirement/preemption returns them.  With prefix
  caching on (the default) the pool is content-addressed: admission
  matches each prompt's token chain against pages other sequences
  already computed, takes refcounted references on the hits, and only
  the MISS SUFFIX is allocated and prefilled.  Writes into a shared
  page copy it first (copy-on-write), and freed pages park in an LRU so
  a hot system prompt stays resident until the pool truly needs the
  space.

- A continuous-batching scheduler: every ``step()`` admits waiting
  requests into the running batch (no waiting for the batch to drain),
  retires sequences on eos/max-tokens, and — when the page pool is
  exhausted mid-decode — preempts the youngest sequence, returning its
  pages and requeuing it for recomputation (which now hits the prefix
  cache its own freed pages just populated).  Prefill is CHUNKED: each
  step packs at most ``max_prefill_tokens`` pending prompt tokens —
  partially-prefilled requests resume across steps at their absolute
  positions — so a long prompt never stalls running decodes; every
  step still runs one decode for the whole running set.

- Bucketed compiled programs instead of per-request recompiles:
    * a varlen PREFILL step for whole-prompt-from-zero batches (the
      flash_attention_varlen segment idiom, padded to a token bucket);
    * a CHUNKED PREFILL step for resumed/cache-hit chunks — the chunk's
      K/V land in the paged cache first, then attention gathers each
      sequence's pages back densely, so chunk tokens attend to the
      cached prefix they never computed;
    * a single-token batched DECODE step driving the paged-attention
      kernel, padded to the max-batch bucket.
  All thread the KV caches through with buffer donation, so the
  [L, num_blocks, H_kv, bs, D] pool is updated in place on TPU instead
  of copied per step.

The decode math is term-for-term the math of ``_make_decode_fwd``
(models/llama.py), so greedy engine output is token-identical to
``LlamaForCausalLM.generate`` — with the prefix cache ON or OFF — and
tests/test_llm_engine.py + tests/test_prefix_cache.py hold the paths
together.

Speculative decoding (inference/spec_decode.py) rides the same cache: a
host-side ``Drafter`` proposes K tokens per running sequence, a fourth
bucketed program — VERIFY, the chunked-prefill gather math returning
logits at EVERY position — scores all drafts in one pass, and host-side
rejection sampling accepts a prefix (greedy output stays byte-identical
to plain decode; sampled output follows the target distribution
exactly).  Rejected tokens roll back via ``BlockManager.truncate``.
Verify and plain-decode sequences share each step: per-request
``spec_k`` opts in, and a low acceptance rate auto-disables speculation
for that request.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.llama import _rms_weight, _rope_positions
from ..ops.pallas import paged_attention as _pa
from ..ops.pallas import flash_attention_varlen as _fav
from ..profiler import RecordEvent, ServingStats
from .kv_cache import NULL_BLOCK, BlockManager, BlockPoolExhausted
from .sampling import make_samp, samp_structs, sample_tokens

__all__ = ["LLMEngine", "Request", "RequestOutput"]


@dataclass
class Request:
    """One generation request in the engine's queues."""
    rid: int
    prompt: list                      # original prompt token ids
    max_new_tokens: int
    temperature: float
    eos_token_id: object              # int | None
    seed: int
    top_k: int = 0                    # 0 -> off
    top_p: float = 1.0                # 1.0 -> off
    repetition_penalty: float = 1.0   # 1.0 -> off
    spec_k: int = 0                   # max draft tokens per verify round
    # scheduler state
    tokens: list = field(default_factory=list)   # tokens to (re)prefill
    generated: list = field(default_factory=list)
    cached: int = 0                   # positions whose KV is in the pool
    arrival: int = 0                  # admission priority (FCFS)
    slot: int = -1                    # stable decode-batch slot
    t_arrival: float = 0.0            # wall clock at add_request (TTFT)
    bt_version: int = -1              # last block-table version packed
    seen: object = None               # [V] bool penalty mask (lazy)
    spec_proposed: int = 0            # drafts sent to verify (lifetime)
    spec_accepted: int = 0            # drafts accepted (lifetime)
    spec_disabled: bool = False       # acceptance fell below the floor
    # streaming hooks (both called from the engine's stepping thread)
    on_token: object = None           # callable(rid, token) per emission
    on_finish: object = None          # callable(RequestOutput) at the end


@dataclass
class RequestOutput:
    rid: int
    prompt: list
    generated: list                   # includes the eos token when hit
    finish_reason: str                # "eos" | "length" | abort reason
                                      # ("aborted", "deadline", ...)

    @property
    def token_ids(self):
        return list(self.prompt) + list(self.generated)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class LLMEngine:
    """Continuous-batching serving loop over one LlamaForCausalLM.

    Parameters
    ----------
    model: LlamaForCausalLM (weights are snapshot via decode_params()).
    max_num_seqs: decode-batch capacity (the padded decode batch size).
    block_size: KV page size in tokens (must satisfy the paged kernel's
        bs % 8 == 0 to be kernel-eligible on TPU).
    num_blocks: page-pool size.  Default sizes the pool so every batch
        slot can reach max_model_len (no preemption under the default).
    max_model_len: longest prompt+generation the engine accepts; fixes
        the static block-table width of the decode program.
    max_prefill_tokens: per-STEP prompt-token budget.  Prompts longer
        than this are prefilled in chunks across steps (decode of the
        running set proceeds every step regardless).
    prefill_token_bucket: flat prefill buffers are padded up to a
        multiple of this, bounding the number of prefill programs by
        max_prefill_tokens / bucket (x the few batch buckets).
    enable_prefix_caching: content-hash full KV pages and reuse them
        across requests sharing a token prefix (BlockManager docstring
        has the page lifecycle).  Greedy output is byte-identical on
        or off.
    drafter: a spec_decode.Drafter (or the string "ngram" for the
        prompt-lookup drafter) proposing draft tokens; None disables
        speculative decoding engine-wide.
    spec_k: default per-request draft length (requests may override via
        add_request(spec_k=); 0 means plain decode).
    max_spec_k: hard per-round draft ceiling; fixes the verify program's
        static token width max_num_seqs * (max_spec_k + 1).
    spec_accept_floor / spec_window: once a request has sent spec_window
        drafts to verify, speculation auto-disables for it if its
        lifetime acceptance rate sits below the floor (the drafter is
        not helping; stop paying the verify overhead).
    retain_outputs: keep every finished RequestOutput in the dict that
        ``run()`` returns.  A long-running server (the HTTP frontend)
        passes False — outputs are delivered through each request's
        ``on_finish`` callback instead, so finished requests cost no
        memory once their stream closes.

    The engine is SINGLE-THREADED by design: add_request/step/abort must
    all be called from one thread (the frontend's EngineRunner owns that
    thread and bridges other threads in via queues drained at step
    boundaries).  abort() in particular relies on being between steps,
    when pool state is consistent.
    """

    def __init__(self, model, *, max_num_seqs: int = 8, block_size: int = 16,
                 num_blocks: int | None = None, max_model_len: int | None = None,
                 max_prefill_tokens: int = 512,
                 prefill_token_bucket: int = 64,
                 enable_prefix_caching: bool = True,
                 drafter=None, spec_k: int = 0, max_spec_k: int = 8,
                 spec_accept_floor: float = 0.35, spec_window: int = 32,
                 retain_outputs: bool = True):
        cfg = model.config
        self.config = cfg
        self.params = model.decode_params()
        self.max_num_seqs = int(max_num_seqs)
        self.block_size = int(block_size)
        self.max_model_len = int(max_model_len or cfg.max_position_embeddings)
        self.max_prefill_tokens = int(max_prefill_tokens)
        self.prefill_token_bucket = int(prefill_token_bucket)
        self.enable_prefix_caching = bool(enable_prefix_caching)

        # static block-table width: pages needed by a max-length sequence
        self.nblk = -(-self.max_model_len // self.block_size)
        if num_blocks is None:
            num_blocks = 1 + self.max_num_seqs * self.nblk
        self.blocks = BlockManager(
            num_blocks, self.block_size,
            enable_prefix_caching=self.enable_prefix_caching)
        if self.blocks.num_free < self.nblk:
            raise ValueError(
                f"num_blocks={num_blocks} cannot hold even one "
                f"max_model_len={self.max_model_len} sequence "
                f"({self.nblk} pages needed)")

        self._nh = cfg.num_attention_heads
        self._kvh = cfg.num_key_value_heads
        self._hd = cfg.hidden_size // self._nh
        L = cfg.num_hidden_layers
        dt = self.params["embed"].dtype
        self._kc = jnp.zeros((L, num_blocks, self._kvh, self.block_size,
                              self._hd), dt)
        self._vc = jnp.zeros_like(self._kc)

        self._waiting: deque = deque()
        self._running: list = []
        self._finished: dict = {}
        self._next_rid = 0
        self._arrival = 0
        self.retain_outputs = bool(retain_outputs)

        # stable decode slots + persistent host-side decode buffers: rows
        # are updated incrementally (grow/retire/CoW bump the table
        # version) instead of rebuilt from scratch every token
        B = self.max_num_seqs
        self._slot_used = [False] * B
        self._d_toks = np.zeros((B,), np.int32)
        self._d_pos = np.zeros((B,), np.int32)
        self._d_bt = np.full((B, self.nblk), NULL_BLOCK, np.int32)
        self._d_samp = make_samp(B, cfg.vocab_size)
        self._d_owner = [None] * B        # rid currently packed in each row

        # speculative decoding: a host-side drafter proposes up to
        # max_spec_k tokens per decode-ready sequence; one fixed-shape
        # verify program scores every (sequence, draft) pair per step
        if drafter == "ngram":
            from .spec_decode import NGramDrafter
            drafter = NGramDrafter()
        self.drafter = drafter
        self.spec_k = int(spec_k)
        self.max_spec_k = int(max_spec_k)
        self.spec_accept_floor = float(spec_accept_floor)
        self.spec_window = int(spec_window)
        self._verify_Tq = B * (self.max_spec_k + 1)

        # program caches: compile counts == len() of these.  The counter
        # dict is the test-visible compile-count regression guard: every
        # program BUILD (not call) bumps its kind, so a mixed stream can
        # assert "exactly N programs" without reaching into the caches.
        self._decode_progs: dict = {}
        self._prefill_progs: dict = {}
        self._chunked_progs: dict = {}
        self._verify_prog = None
        self._cow_prog = None
        self.compile_counts = {"decode": 0, "prefill": 0, "chunked": 0,
                               "verify": 0, "cow": 0}
        self._evictions_seen = 0
        self.stats = ServingStats()

    # ------------------------------------------------------------------
    # request API
    # ------------------------------------------------------------------

    def add_request(self, prompt, max_new_tokens: int = 32,
                    temperature: float = 0.0, eos_token_id=None,
                    seed: int = 0, top_k: int = 0, top_p: float = 1.0,
                    repetition_penalty: float = 1.0,
                    spec_k: int | None = None,
                    on_token=None, on_finish=None) -> int:
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) + int(max_new_tokens) > self.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_model_len "
                f"({self.max_model_len})")
        if not 0.0 < float(top_p) <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if int(top_k) < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if float(repetition_penalty) <= 0.0:
            raise ValueError(
                f"repetition_penalty must be > 0, got {repetition_penalty}")
        if spec_k is None:
            spec_k = self.spec_k
        spec_k = min(int(spec_k), self.max_spec_k) \
            if self.drafter is not None else 0
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid=rid, prompt=prompt, tokens=list(prompt),
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature),
                      eos_token_id=eos_token_id, seed=int(seed),
                      top_k=int(top_k), top_p=float(top_p),
                      repetition_penalty=float(repetition_penalty),
                      spec_k=spec_k, t_arrival=time.perf_counter(),
                      on_token=on_token, on_finish=on_finish)
        if req.repetition_penalty != 1.0:
            req.seen = np.zeros((self.config.vocab_size,), bool)
            req.seen[prompt] = True
        self._waiting.append(req)
        return rid

    def has_unfinished(self) -> bool:
        return bool(self._waiting or self._running)

    def abort(self, request_id: int, finish_reason: str = "aborted"):
        """Retire a request before it finishes — the client disconnected,
        its deadline passed, or the server is shedding it.

        Works at ANY point of the request's lifetime as observed between
        steps: still queued (nothing allocated), mid-chunked-prefill
        (pages for the already-prefilled prefix are live, resume state in
        ``req.cached``), mid-decode, or mid-speculation (the post-verify
        ``truncate`` already rolled back rejected drafts, so pool state
        is consistent at every step boundary).  Pages return through
        ``BlockManager.release`` — the abort-hardened path that only
        DECREFS pages shared with live neighbours (their chain hashes
        survive, so aborting one reader of a hot system prompt never
        evicts it) and never registers the aborted tail.

        Returns the partial RequestOutput, or None when request_id is
        unknown or already finished (an abort racing a natural finish is
        a benign no-op).  Must be called from the engine's stepping
        thread, between steps — the frontend's EngineRunner queues
        cross-thread aborts and applies them at the next step boundary.
        """
        req = None
        for r in self._running:
            if r.rid == request_id:
                req = r
                self._running.remove(r)
                self._release_slot(r)
                break
        else:
            for r in self._waiting:
                if r.rid == request_id:
                    req = r
                    self._waiting.remove(r)
                    break
        if req is None:
            return None
        # a waiting request normally holds no pages — unless it was
        # preempted after generating (pages freed then) or never admitted
        # (never allocated); release() covers the running/mid-prefill case
        if self.blocks.has(req.rid):
            self.blocks.release(req.rid)
        if self.drafter is not None:
            self.drafter.release(req.rid)
        out = RequestOutput(rid=req.rid, prompt=list(req.prompt),
                            generated=list(req.generated),
                            finish_reason=finish_reason)
        if self.retain_outputs:
            self._finished[req.rid] = out
        self.stats.record_abort(finish_reason)
        if req.on_finish is not None:
            req.on_finish(out)
        return out

    def _notify_tokens(self, req, toks) -> None:
        if req.on_token is not None:
            for t in toks:
                req.on_token(req.rid, int(t))

    @property
    def num_decode_programs(self) -> int:
        return len(self._decode_progs)

    @property
    def num_prefill_programs(self) -> int:
        return len(self._prefill_progs) + len(self._chunked_progs)

    def run(self) -> dict:
        """Drive step() until every queued request finishes.  Outputs by
        rid; the run's metrics (incl. cache hits/misses, CoW copies,
        evictions, chunked-prefill queue depth) are in ``summary()``."""
        while self.has_unfinished():
            self.step()
        return dict(self._finished)

    def summary(self) -> dict:
        """One dict of serving metrics + block-pool state for this run."""
        out = self.stats.summary()
        out["block_pool"] = self.blocks.stats()
        return out

    def program_specs(self, *, large_bytes: int = 1 << 20) -> list:
        """Every program this engine compiles, as analysis ProgramSpecs.

        Arguments are ShapeDtypeStructs (nothing allocates or runs) and
        donate_argnums is the INTENDED device donation — the analyzer
        audits the TPU contract even when the process runs on CPU, where
        the builders drop donation.  ``graftlint --audit-serving`` and
        tests/test_serving_audit.py consume this.
        """
        from ..analysis import ProgramSpec

        sds = jax.ShapeDtypeStruct
        i32 = jnp.int32
        params = jax.tree_util.tree_map(
            lambda x: sds(np.shape(x), x.dtype), self.params)
        kc = sds(self._kc.shape, self._kc.dtype)
        vc = sds(self._vc.shape, self._vc.dtype)
        dt = self.params["embed"].dtype
        declared = dt if np.dtype(dt).name in ("bfloat16", "float16") \
            else None
        V = self.config.vocab_size
        Bb = self.max_num_seqs
        Tp, Bp = self.prefill_token_bucket, 1
        Tq, Bv = self._verify_Tq, self.max_num_seqs

        dec_fn, dec_donate = self._make_decode_fn(Bb)
        pre_fn, pre_donate = self._make_prefill_fn(Tp, Bp)
        chk_fn, chk_donate = self._make_chunked_fn(Tp, Bp)
        ver_fn, ver_donate = self._make_verify_fn(Tq, Bv)
        cow_fn, cow_donate = self._make_cow_fn()

        def seqs(n):      # [n] i32 token/pos/index vectors
            return sds((n,), i32)

        bt = sds((Bp + 1, self.nblk), i32)
        return [
            ProgramSpec(
                "serving.decode", dec_fn,
                (params, kc, vc, seqs(Bb), seqs(Bb),
                 sds((Bb, self.nblk), i32), samp_structs(Bb, V)),
                donate_argnums=dec_donate, declared_dtype=declared,
                large_bytes=large_bytes),
            ProgramSpec(
                "serving.prefill", pre_fn,
                (params, kc, vc, seqs(Tp), seqs(Tp), seqs(Tp), bt,
                 seqs(Bp + 1), seqs(Bp), samp_structs(Bp, V)),
                donate_argnums=pre_donate, declared_dtype=declared,
                large_bytes=large_bytes),
            ProgramSpec(
                "serving.chunked_prefill", chk_fn,
                (params, kc, vc, seqs(Tp), seqs(Tp), seqs(Tp), bt,
                 seqs(Bp), samp_structs(Bp, V)),
                donate_argnums=chk_donate, declared_dtype=declared,
                large_bytes=large_bytes),
            ProgramSpec(
                "serving.verify", ver_fn,
                (params, kc, vc, seqs(Tq), seqs(Tq), seqs(Tq),
                 sds((Bv + 1, self.nblk), i32)),
                donate_argnums=ver_donate, declared_dtype=declared,
                large_bytes=large_bytes),
            ProgramSpec(
                "serving.cow_copy", cow_fn,
                (kc, vc, sds((), i32), sds((), i32)),
                donate_argnums=cow_donate, declared_dtype=declared,
                large_bytes=large_bytes),
        ]

    # ------------------------------------------------------------------
    # scheduler
    # ------------------------------------------------------------------

    def _decode_ready(self, req) -> bool:
        """Prefill complete and exactly the last generated token's KV is
        still unwritten (the decode step writes it and samples the next)."""
        return (req.cached >= len(req.tokens)
                and req.cached == len(req.prompt) + len(req.generated) - 1)

    def step(self) -> list:
        """One engine iteration: admit -> chunked prefill -> decode ->
        retire.  Returns the requests that finished during this step."""
        finished = []

        admitted = self._admit()
        if admitted:
            self.stats.record_admission(len(admitted))
        self.stats.record_prefill_queue(
            sum(1 for r in self._running if r.cached < len(r.tokens))
            + len(self._waiting))

        chunks = self._schedule_prefill_chunks()
        emitted_now = set()
        if chunks:
            t0 = time.perf_counter()
            with RecordEvent("llm_engine.prefill"):
                first = self._run_prefill(chunks)
            dur = time.perf_counter() - t0
            done = [(req, tok) for (req, n), tok in zip(chunks, first)
                    if req.cached + n == len(req.tokens)]
            self.stats.record_prefill(
                dur, sum(n for _, n in chunks), len(done))
            for req, n in chunks:
                req.cached += n
                if self.enable_prefix_caching:
                    self.blocks.commit_prefill(req.rid, n)
            for req, tok in done:
                req.generated.append(int(tok))
                if req.seen is not None:
                    req.seen[int(tok)] = True
                emitted_now.add(id(req))
                if len(req.generated) == 1:
                    self.stats.record_ttft(
                        time.perf_counter() - req.t_arrival)
                self._notify_tokens(req, (tok,))
                self._maybe_retire(req, finished)

        # decode everyone already in the batch (sequences that finished
        # prefill THIS step already produced their token above; sequences
        # still mid-prefill are not decode-ready yet)
        batch = [r for r in self._running
                 if id(r) not in emitted_now and self._decode_ready(r)]

        # speculative sequences verify first (the drafter proposed for
        # them); everything else plain-decodes in the same step
        spec, batch = self._split_spec(batch)
        spec, demoted = self._reserve_verify_pages(spec)
        batch.extend(demoted)
        if spec:
            # fold the non-speculating decode-ready sequences into the
            # SAME verify launch as zero-draft rows (one packed token ->
            # one emitted token): the step issues one program instead of
            # a verify plus a decode, which is where speculation's
            # launch-count savings actually land
            batch = [r for r in batch
                     if r in self._running and self._decode_ready(r)]
            folded = self._reserve_decode_pages(batch)
            # reserving the folded rows can preempt a verify member —
            # drop any such casualty before packing the launch
            spec = [(r, d, q) for (r, d, q) in spec if r in self._running]
            spec.extend((r, [], None) for r in folded)
            batch = []
        if spec:
            t0 = time.perf_counter()
            with RecordEvent("llm_engine.verify"):
                per_seq_logits = self._run_verify(spec)
            dur = time.perf_counter() - t0
            n_emitted = 0
            for (req, drafts, qd), lg in zip(spec, per_seq_logits):
                n_emitted += self._apply_spec_result(req, drafts, qd, lg,
                                                     finished)
            self.stats.record_verify(
                dur, n_emitted, len(self._running) / self.max_num_seqs)

        # verify reservation/CoW may have preempted plain-decode members
        batch = [r for r in batch
                 if r in self._running and self._decode_ready(r)]
        batch = self._reserve_decode_pages(batch)
        if batch:
            t0 = time.perf_counter()
            with RecordEvent("llm_engine.decode"):
                toks = self._run_decode(batch)
            dur = time.perf_counter() - t0
            self.stats.record_decode(
                dur, len(batch), len(self._running) / self.max_num_seqs)
            for req, tok in zip(batch, toks):
                if self.enable_prefix_caching:
                    self.blocks.commit_decode_token(req.rid,
                                                    req.generated[-1])
                req.cached += 1
                req.generated.append(int(tok))
                if req.seen is not None:
                    req.seen[int(tok)] = True
                self._notify_tokens(req, (tok,))
                self._maybe_retire(req, finished)

        ev = self.blocks.eviction_count
        if ev != self._evictions_seen:
            self.stats.record_evictions(ev - self._evictions_seen)
            self._evictions_seen = ev
        return finished

    def _claim_slot(self, req) -> None:
        req.slot = self._slot_used.index(False)
        self._slot_used[req.slot] = True

    def _release_slot(self, req) -> None:
        if req.slot >= 0:
            self._slot_used[req.slot] = False
            req.slot = -1

    def _admit(self) -> list:
        """Pull waiting requests into the running set while batch slots
        and pool pages allow.  With prefix caching, admission matches the
        prompt's token chain against the cache and allocates only the
        miss suffix; chunked prefill means admission is no longer gated
        on the per-step token budget."""
        admitted = []
        while self._waiting and len(self._running) < self.max_num_seqs:
            req = self._waiting[0]
            if self.enable_prefix_caching:
                hit = self.blocks.acquire(req.rid, req.tokens)
                if hit is None:
                    break
                req.cached = hit
                self.stats.record_cache_lookup(hit, len(req.tokens) - hit)
            else:
                if not self.blocks.allocate(req.rid, len(req.tokens)):
                    break
                req.cached = 0
            self._waiting.popleft()
            req.arrival = self._arrival
            self._arrival += 1
            req.bt_version = -1
            self._claim_slot(req)
            self._running.append(req)
            admitted.append(req)
        return admitted

    def _schedule_prefill_chunks(self) -> list:
        """Pack at most max_prefill_tokens pending prompt tokens into this
        step, FCFS, resuming partially-prefilled requests first.  Resolves
        copy-on-write for each chunk's first write position (the only spot
        a chunk can touch a shared page) before the program runs."""
        budget = self.max_prefill_tokens
        chunks = []
        for req in sorted(list(self._running), key=lambda r: r.arrival):
            if budget <= 0:
                break
            rem = len(req.tokens) - req.cached
            if rem <= 0 or req not in self._running:
                continue
            if self.enable_prefix_caching:
                if not self._resolve_cow(req, req.cached,
                                         drop_from=chunks):
                    continue                     # req itself was preempted
            chunks.append((req, min(rem, budget)))
            budget -= min(rem, budget)
        return chunks

    def _resolve_cow(self, req, pos: int, drop_from: list | None = None) \
            -> bool:
        """Privatize the page holding ``pos`` if it is shared, preempting
        victims while the pool has no page for the copy.  False when req
        itself had to be preempted."""
        while True:
            try:
                cw = self.blocks.cow_if_shared(req.rid, pos)
            except BlockPoolExhausted:
                victim = self._pick_victim(exclude=req)
                if victim is None:
                    self._preempt(req)
                    return False
                self._preempt(victim)
                if drop_from is not None:
                    drop_from[:] = [c for c in drop_from
                                    if c[0] is not victim]
                continue
            if cw is not None:
                self._apply_cow(*cw)
                self.stats.record_cow()
            return True

    def _reserve_decode_pages(self, batch: list) -> list:
        """Grow each sequence's table for the token this step will write
        (plus a private copy of a still-shared tail page); preempt the
        youngest runner whenever the pool comes up short."""
        ok = []
        for req in sorted(batch, key=lambda r: r.arrival):
            if req not in self._running:   # evicted as a victim earlier
                continue
            while req is not None:
                if not self.blocks.ensure(req.rid, req.cached + 1):
                    victim = self._pick_victim(exclude=req)
                    if victim is None:
                        self._preempt(req)
                        req = None
                        break
                    self._preempt(victim)
                    ok = [r for r in ok if r is not victim]
                    continue
                if self.enable_prefix_caching:
                    if not self._resolve_cow(req, req.cached):
                        req = None
                        break
                    ok = [r for r in ok if r in self._running]
                break
            if req is not None:
                ok.append(req)
        return ok

    def _pick_victim(self, exclude):
        """Youngest-arrival running sequence other than ``exclude``."""
        cands = [r for r in self._running if r is not exclude]
        if not cands:
            return None
        return max(cands, key=lambda r: r.arrival)

    def _preempt(self, req) -> None:
        """Return req's pages and requeue it (front of the line) for
        recomputation: its next prefill covers prompt + tokens generated
        so far, which rebuilds the exact KV state — greedy decoding
        resumes token-identically.  With prefix caching the freed full
        pages park in the cache, so the recompute's admission hits the
        very pages this preemption returned and re-prefills only the
        tail."""
        self.blocks.free(req.rid)
        self._running.remove(req)
        self._release_slot(req)
        req.tokens = list(req.prompt) + list(req.generated)
        req.cached = 0
        req.bt_version = -1
        self._waiting.appendleft(req)
        if self.drafter is not None:
            self.drafter.release(req.rid)
        self.stats.record_preemption()

    def _maybe_retire(self, req, finished: list) -> None:
        eos = req.eos_token_id
        if eos is not None and req.generated[-1] == int(eos):
            reason = "eos"
        elif len(req.generated) >= req.max_new_tokens:
            reason = "length"
        else:
            return
        self.blocks.free(req.rid)
        self._running.remove(req)
        self._release_slot(req)
        out = RequestOutput(rid=req.rid, prompt=list(req.prompt),
                            generated=list(req.generated),
                            finish_reason=reason)
        if self.retain_outputs:
            self._finished[req.rid] = out
        finished.append(out)
        if self.drafter is not None:
            self.drafter.release(req.rid)
        self.stats.record_retirement()
        if req.on_finish is not None:
            req.on_finish(out)

    # ------------------------------------------------------------------
    # speculative decoding: propose -> verify -> accept/rollback
    # ------------------------------------------------------------------

    def _split_spec(self, batch: list):
        """Ask the drafter for up to spec_k tokens per eligible sequence.
        Sequences with no proposal (or speculation off/disabled/cut to
        zero by length limits) fall through to plain decode."""
        if self.drafter is None:
            return [], batch
        spec, plain = [], []
        for req in batch:
            k = 0 if req.spec_disabled else req.spec_k
            # the verify step writes K/V at cached..cached+k, so the
            # sequence may hold at most max_model_len tokens afterwards;
            # drafting past max_new_tokens (plus the bonus token) is waste
            k = min(k,
                    self.max_model_len - len(req.prompt) - len(req.generated),
                    req.max_new_tokens - len(req.generated) - 1)
            if k <= 0:
                plain.append(req)
                continue
            context = list(req.prompt) + list(req.generated)
            drafts, qd = self.drafter.propose(req.rid, context, k)
            if not drafts:
                plain.append(req)
                continue
            spec.append((req, [int(t) for t in drafts[:k]], qd))
        return spec, plain

    def _page_starts(self, a: int, b: int) -> list:
        """First written position in each page the write window [a, b]
        (inclusive) touches — the positions _resolve_cow must privatize."""
        bs = self.block_size
        out = [a]
        p = (a // bs + 1) * bs
        while p <= b:
            out.append(p)
            p += bs
        return out

    def _reserve_verify_pages(self, spec: list):
        """Grow each speculative sequence's table for its K+1 writes and
        privatize every shared page in the window.  The pool is never
        preempted FOR speculation: when ensure() comes up short the draft
        shrinks (k -> k-1 -> ... -> plain decode) instead.  CoW of the
        first write position is required for plain decode too, so that
        path keeps the usual victim-preemption behaviour."""
        ok, demoted = [], []
        for req, drafts, qd in spec:
            if req not in self._running:
                continue
            k = len(drafts)
            while k > 0 and not self.blocks.ensure(req.rid,
                                                   req.cached + k + 1):
                k -= 1
            if k == 0:
                demoted.append(req)
                continue
            drafts = drafts[:k]
            if self.enable_prefix_caching:
                alive = True
                for pos in self._page_starts(req.cached, req.cached + k):
                    if not self._resolve_cow(req, pos):
                        alive = False           # req itself was preempted
                        break
                ok = [it for it in ok if it[0] in self._running]
                if not alive:
                    continue
            ok.append((req, drafts, qd))
        return ok, demoted

    def _get_verify_prog(self):
        if self._verify_prog is None:
            run, donate = self._make_verify_fn(self._verify_Tq,
                                               self.max_num_seqs)
            if jax.default_backend() == "cpu":
                donate = ()
            self._verify_prog = jax.jit(run, donate_argnums=donate)
            self.compile_counts["verify"] += 1
        return self._verify_prog

    def _make_verify_fn(self, Tq: int, Bv: int):
        """The chunked-prefill gather math, returning raw f32 logits at
        EVERY packed position instead of sampling the last token of each
        sequence: row i scores the token AFTER packed token i, which is
        exactly the target distribution the i-th draft must survive.
        Sampling happens on host (spec_decode.verify_and_accept) because
        acceptance is sequential in i — draft i conditions on drafts
        < i being accepted.  One fixed (Tq, Bv) bucket keeps the compile
        count at 1."""
        nh, kvh, d = self._nh, self._kvh, self._hd
        bs = self.block_size
        nblk = self.nblk
        S = nblk * bs
        eps = self.config.rms_norm_eps
        theta = self.config.rope_theta
        sm_scale = 1.0 / (d ** 0.5)

        def run(params, kc, vc, toks, seg, rel, bt):
            # toks/seg/rel [Tq] int32 (pads: seg == Bv -> the null row of
            # bt); rel is each token's absolute position; bt [Bv+1, nblk].
            x = jnp.take(params["embed"], toks, axis=0)       # [Tq, H]
            keypos = jnp.arange(S, dtype=jnp.int32)

            def body(x, inp):
                p, kcl, vcl = inp
                h = _rms_weight(x, p["ln1"], eps)
                q = (h @ p["wq"]).reshape(Tq, nh, d)
                k = (h @ p["wk"]).reshape(Tq, kvh, d)
                v = (h @ p["wv"]).reshape(Tq, kvh, d)
                q = _rope_positions(q, rel, theta)
                k = _rope_positions(k, rel, theta)
                blk = bt[seg, rel // bs]                      # [Tq]
                slot = rel % bs
                kcl = kcl.at[blk, :, slot, :].set(k.astype(kcl.dtype))
                vcl = vcl.at[blk, :, slot, :].set(v.astype(vcl.dtype))
                kg = kcl[bt].transpose(0, 1, 3, 2, 4) \
                    .reshape(Bv + 1, S, kvh, d)
                vg = vcl[bt].transpose(0, 1, 3, 2, 4) \
                    .reshape(Bv + 1, S, kvh, d)
                kq = kg[seg]                                  # [Tq, S, kvh, d]
                vq = vg[seg]
                if kvh != nh:
                    kq = jnp.repeat(kq, nh // kvh, axis=2)
                    vq = jnp.repeat(vq, nh // kvh, axis=2)
                sc = jnp.einsum("qhd,qshd->qhs", q.astype(jnp.float32),
                                kq.astype(jnp.float32)) * sm_scale
                mask = keypos[None, None, :] <= rel[:, None, None]
                sc = jnp.where(mask, sc, -jnp.inf)
                pr = jax.nn.softmax(sc, axis=-1)
                att = jnp.einsum("qhs,qshd->qhd", pr,
                                 vq.astype(jnp.float32)).astype(x.dtype)
                x = x + att.reshape(Tq, nh * d) @ p["wo"]
                h2 = _rms_weight(x, p["ln2"], eps)
                a = jax.nn.silu((h2 @ p["gate"]).astype(jnp.float32)
                                ).astype(h2.dtype) * (h2 @ p["up"])
                return x + a @ p["down"], (kcl, vcl)

            x, (kc, vc) = lax.scan(body, x, (params["layers"], kc, vc))
            h = _rms_weight(x, params["norm_f"], eps)
            logits = (h.astype(jnp.float32)
                      @ params["head"].astype(jnp.float32))   # [Tq, V]
            return logits, kc, vc

        return run, (1, 2)

    def _run_verify(self, spec: list):
        """Pack every speculative sequence's [last_generated, d_1..d_k]
        window into one verify call; returns each sequence's [k+1, V]
        logits slice (position cached+i scores the token after draft i)."""
        Tq, Bv = self._verify_Tq, self.max_num_seqs
        toks = np.zeros((Tq,), np.int32)
        seg = np.full((Tq,), Bv, np.int32)            # pads -> sentinel
        rel = np.zeros((Tq,), np.int32)
        bt = np.full((Bv + 1, self.nblk), NULL_BLOCK, np.int32)
        slices = []
        off = 0
        for i, (req, drafts, _) in enumerate(spec):
            w = [req.generated[-1]] + drafts
            n = len(w)
            toks[off:off + n] = w
            seg[off:off + n] = i
            rel[off:off + n] = np.arange(req.cached, req.cached + n)
            bt[i] = self.blocks.padded_table(req.rid, self.nblk)
            slices.append((off, n))
            off += n
        prog = self._get_verify_prog()
        logits, self._kc, self._vc = prog(self.params, self._kc, self._vc,
                                          toks, seg, rel, bt)
        logits = np.asarray(logits)
        # every sequence's table was (re)packed fresh above, and the
        # post-verify truncate changes it again — force decode repacks
        for req, _, _ in spec:
            req.bt_version = -1
        return [logits[o:o + n] for o, n in slices]

    def _apply_spec_result(self, req, drafts, qd, lg, finished) -> int:
        """Turn one sequence's verify logits into emitted tokens: run
        rejection-sampling acceptance, commit the accepted prefix's K/V,
        truncate the rejected tail out of the page table (scrubbing its
        content hashes), and advance the request exactly as that many
        plain decode steps would have.  Returns tokens emitted."""
        from .spec_decode import verify_and_accept

        k = len(drafts)
        rng = None
        if req.temperature > 0.0:
            # keyed by (seed, position): reproducible across scheduling
            # orders and preemptions, like _req_key on the device path
            rng = np.random.Generator(np.random.Philox(
                key=[req.seed & 0xFFFFFFFF, len(req.generated)]))
        n_acc, emitted = verify_and_accept(
            lg, drafts, q_dists=qd, temperature=req.temperature,
            top_k=req.top_k, top_p=req.top_p,
            penalty=req.repetition_penalty, seen=req.seen, rng=rng)
        # cut to the generation budget, and at the first eos token
        room = req.max_new_tokens - len(req.generated)
        emitted = emitted[:room]
        if req.eos_token_id is not None:
            eos = int(req.eos_token_id)
            if eos in emitted:
                emitted = emitted[:emitted.index(eos) + 1]
        m = len(emitted)                              # >= 1: room >= 1
        # K/V validity: positions cached..cached+n_acc hold
        # [generated[-1], accepted drafts]; m <= n_acc + 1 tokens advance
        # the clock, and when the m-th is the bonus/resample its K/V is
        # written by the NEXT step (decode invariant), not this one.
        if self.enable_prefix_caching:
            for tok in [req.generated[-1]] + emitted[:m - 1]:
                self.blocks.commit_decode_token(req.rid, tok)
        req.cached += m
        # roll the speculative tail (rejected drafts + over-reserved
        # pages) back out of the table; prefix-cache hashes covering
        # rolled-back K/V are scrubbed inside truncate
        rolled = self.blocks.truncate(req.rid, req.cached)
        req.generated.extend(emitted)
        if req.seen is not None:
            req.seen[emitted] = True
        self._notify_tokens(req, emitted)
        j = m - 1 if m == n_acc + 1 else m            # emitted draft count
        if k:                                         # zero-draft rows are
            req.spec_proposed += k                    # plain decode riding
            req.spec_accepted += min(j, n_acc)        # the verify launch
            self.stats.record_spec(proposed=k, accepted=min(j, n_acc),
                                   emitted=m, rollback=k - j,
                                   pages_rolled=rolled)
            if (not req.spec_disabled
                    and req.spec_proposed >= self.spec_window
                    and req.spec_accepted
                    < self.spec_accept_floor * req.spec_proposed):
                req.spec_disabled = True
                self.stats.record_spec_disable()
            self.drafter.commit(
                req.rid, len(req.prompt) + len(req.generated) - (m - j))
        self._maybe_retire(req, finished)
        return m

    # ------------------------------------------------------------------
    # copy-on-write page copy (device side)
    # ------------------------------------------------------------------

    def _make_cow_fn(self):
        """(unjitted page-copy fn, intended donate_argnums) — the spec the
        analyzer sees; _apply_cow jits it (CPU drops donation: the CPU
        runtime cannot alias and would warn every call)."""
        def run(kc, vc, s, d):
            kc = kc.at[:, d].set(kc[:, s])
            vc = vc.at[:, d].set(vc[:, s])
            return kc, vc

        return run, (0, 1)

    def _apply_cow(self, src: int, dst: int) -> None:
        """Copy page src -> dst across every layer's K and V cache.  The
        copy is dispatched immediately so device program order keeps it
        ahead of any later prefill/decode write into dst."""
        if self._cow_prog is None:
            run, donate = self._make_cow_fn()
            if jax.default_backend() == "cpu":
                donate = ()
            self._cow_prog = jax.jit(run, donate_argnums=donate)
            self.compile_counts["cow"] += 1
        self._kc, self._vc = self._cow_prog(
            self._kc, self._vc, np.int32(src), np.int32(dst))

    # ------------------------------------------------------------------
    # compiled decode step
    # ------------------------------------------------------------------

    def _decode_bucket(self, n: int) -> int:
        # one bucket: the full batch width.  Padding decode to max_num_seqs
        # costs little (one token per slot) and pins the compile count at 1.
        return self.max_num_seqs

    def _get_decode_prog(self, Bb: int):
        key = (Bb, self.nblk)
        prog = self._decode_progs.get(key)
        if prog is None:
            prog = self._build_decode(Bb)
            self._decode_progs[key] = prog
            self.compile_counts["decode"] += 1
        return prog

    def _build_decode(self, Bb: int):
        run, donate = self._make_decode_fn(Bb)
        if jax.default_backend() == "cpu":
            donate = ()
        return jax.jit(run, donate_argnums=donate)

    def _make_decode_fn(self, Bb: int):
        nh, kvh, d = self._nh, self._kvh, self._hd
        bs = self.block_size
        eps = self.config.rms_norm_eps
        theta = self.config.rope_theta
        dt = self.params["embed"].dtype
        # the interpreted kernel costs a Python step per (B, H_kv, nblk)
        # grid cell EVERY decode — serving on CPU uses the XLA reference
        # path (term-identical math) unless a test forces the interpreter
        use_pallas = _pa.INTERPRET is True or (
            jax.default_backend() == "tpu"
            and _pa.supports(Bb, nh, kvh, d, bs, self.nblk, dt))

        def run(params, kc, vc, toks, pos, bt, samp):
            # toks/pos [Bb] int32; bt [Bb, nblk] int32; samp is the
            # sampling.make_samp pytree of per-row parameters.  pos is the
            # cache position the fresh token's K/V lands in; attention
            # covers pos+1 entries.
            x = jnp.take(params["embed"], toks, axis=0)       # [Bb, H]

            def body(x, inp):
                p, kcl, vcl = inp
                h = _rms_weight(x, p["ln1"], eps)
                q = (h @ p["wq"]).reshape(Bb, nh, d)
                k = (h @ p["wk"]).reshape(Bb, kvh, d)
                v = (h @ p["wv"]).reshape(Bb, kvh, d)
                q = _rope_positions(q, pos, theta)
                k = _rope_positions(k, pos, theta)
                blk = jnp.take_along_axis(bt, (pos // bs)[:, None],
                                          axis=1)[:, 0]
                slot = pos % bs
                kcl = kcl.at[blk, :, slot, :].set(k.astype(kcl.dtype))
                vcl = vcl.at[blk, :, slot, :].set(v.astype(vcl.dtype))
                if use_pallas:
                    att = _pa.paged_decode_attention(q, kcl, vcl, bt,
                                                     pos + 1)
                else:
                    att = _pa.paged_decode_reference(q, kcl, vcl, bt,
                                                     pos + 1)
                x = x + att.reshape(Bb, nh * d) @ p["wo"]
                h2 = _rms_weight(x, p["ln2"], eps)
                a = jax.nn.silu((h2 @ p["gate"]).astype(jnp.float32)
                                ).astype(h2.dtype) * (h2 @ p["up"])
                return x + a @ p["down"], (kcl, vcl)

            x, (kc, vc) = lax.scan(body, x, (params["layers"], kc, vc))
            h = _rms_weight(x, params["norm_f"], eps)
            logits = (h.astype(jnp.float32)
                      @ params["head"].astype(jnp.float32))
            return sample_tokens(logits, samp), kc, vc

        # donation reuses the pool buffers in place; _build_decode drops
        # it on CPU (that runtime cannot alias and would warn every call)
        return run, (1, 2)

    def _run_decode(self, batch: list):
        Bb = self._decode_bucket(len(batch))
        prog = self._get_decode_prog(Bb)
        # incremental host-side batch assembly over stable slots: only
        # rows whose sequence grew/CoW'd (table version bump) repack the
        # [nblk] block table; empty slots are nulled once on transition
        cur = {req.slot: req for req in batch}
        samp = self._d_samp
        for s in range(Bb):
            if self._d_owner[s] is not None and s not in cur:
                self._d_bt[s].fill(NULL_BLOCK)
                self._d_toks[s] = 0
                self._d_pos[s] = 0
                samp["temps"][s] = 0.0
                samp["top_k"][s] = 0
                samp["top_p"][s] = 1.0
                samp["penalty"][s] = 1.0
                samp["seen"][s] = False
                self._d_owner[s] = None
        for s, req in cur.items():
            if self._d_owner[s] != req.rid:
                self._d_owner[s] = req.rid
                samp["temps"][s] = req.temperature
                samp["top_k"][s] = req.top_k
                samp["top_p"][s] = req.top_p
                samp["penalty"][s] = req.repetition_penalty
                req.bt_version = -1          # force a row repack
            self._d_toks[s] = req.generated[-1]
            self._d_pos[s] = req.cached
            ver = self.blocks.table_version(req.rid)
            if req.bt_version != ver:
                self._d_bt[s] = self.blocks.padded_table(req.rid, self.nblk)
                req.bt_version = ver
            if req.seen is not None:
                np.copyto(samp["seen"][s], req.seen)
            if req.temperature > 0.0:
                # greedy rows never touch their key: an all-greedy batch
                # skips per-step key derivation entirely
                samp["keys"][s] = self._req_key(req)
        out, self._kc, self._vc = prog(self.params, self._kc, self._vc,
                                       self._d_toks, self._d_pos,
                                       self._d_bt, samp)
        out = np.asarray(out)
        return [out[req.slot] for req in batch]

    def _req_key(self, req):
        # key for token i of request r depends only on (seed, i): sampling
        # is reproducible across scheduling orders and preemptions
        key = jax.random.fold_in(jax.random.PRNGKey(req.seed),
                                 len(req.generated))
        return np.asarray(key, np.uint32)

    # ------------------------------------------------------------------
    # compiled prefill steps
    # ------------------------------------------------------------------

    def _prefill_buckets(self, n_tokens: int, n_seqs: int):
        tb = self.prefill_token_bucket
        Tp = max(tb, -(-n_tokens // tb) * tb)
        Bp = min(_next_pow2(max(n_seqs, 1)), self.max_num_seqs)
        Bp = max(Bp, 1)
        return Tp, Bp

    def _get_prefill_prog(self, Tp: int, Bp: int):
        key = (Tp, Bp)
        prog = self._prefill_progs.get(key)
        if prog is None:
            prog = self._build_prefill(Tp, Bp)
            self._prefill_progs[key] = prog
            self.compile_counts["prefill"] += 1
        return prog

    def _get_chunked_prog(self, Tp: int, Bp: int):
        key = (Tp, Bp)
        prog = self._chunked_progs.get(key)
        if prog is None:
            prog = self._build_prefill_chunked(Tp, Bp)
            self._chunked_progs[key] = prog
            self.compile_counts["chunked"] += 1
        return prog

    def _build_prefill(self, Tp: int, Bp: int):
        run, donate = self._make_prefill_fn(Tp, Bp)
        if jax.default_backend() == "cpu":
            donate = ()
        return jax.jit(run, donate_argnums=donate)

    def _make_prefill_fn(self, Tp: int, Bp: int):
        nh, kvh, d = self._nh, self._kvh, self._hd
        bs = self.block_size
        eps = self.config.rms_norm_eps
        theta = self.config.rope_theta
        sm_scale = 1.0 / (d ** 0.5)
        # the varlen flash kernel wants TPU (or its own interpret flag),
        # packed MHA [T, H, D]; otherwise a dense segment-masked f32
        # composition computes the same masked softmax
        probe = jnp.zeros((Tp, nh, d), self.params["embed"].dtype)
        probe_k = jnp.zeros((Tp, kvh, d), self.params["embed"].dtype)
        use_varlen = bool(_fav.use_varlen_flash(probe, probe_k, True))

        def attend(q, k, v, seg, rel, cu):
            if use_varlen:
                return _fav._varlen_attention(True, sm_scale, q, k, v,
                                              cu, cu)
            if kvh != nh:
                k = jnp.repeat(k, nh // kvh, axis=1)
                v = jnp.repeat(v, nh // kvh, axis=1)
            sc = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * sm_scale
            mask = (seg[None, :, None] == seg[None, None, :]) \
                & (rel[None, None, :] <= rel[None, :, None])
            sc = jnp.where(mask, sc, -jnp.inf)
            pr = jax.nn.softmax(sc, axis=-1)
            out = jnp.einsum("hqk,khd->qhd", pr, v.astype(jnp.float32))
            return out.astype(q.dtype)

        def run(params, kc, vc, toks, seg, rel, bt, cu, last_idx, samp):
            # toks/seg/rel [Tp] int32 (pads carry seg == Bp, a row of the
            # null page in bt); bt [Bp+1, nblk]; cu [Bp+1] varlen offsets;
            # last_idx [Bp] flat index of each sequence's final token;
            # samp is the make_samp pytree, one row per sequence.
            x = jnp.take(params["embed"], toks, axis=0)       # [Tp, H]

            def body(x, inp):
                p, kcl, vcl = inp
                h = _rms_weight(x, p["ln1"], eps)
                q = (h @ p["wq"]).reshape(Tp, nh, d)
                k = (h @ p["wk"]).reshape(Tp, kvh, d)
                v = (h @ p["wv"]).reshape(Tp, kvh, d)
                q = _rope_positions(q, rel, theta)
                k = _rope_positions(k, rel, theta)
                blk = bt[seg, rel // bs]                      # [Tp]
                slot = rel % bs
                kcl = kcl.at[blk, :, slot, :].set(k.astype(kcl.dtype))
                vcl = vcl.at[blk, :, slot, :].set(v.astype(vcl.dtype))
                att = attend(q, k, v, seg, rel, cu)
                x = x + att.reshape(Tp, nh * d) @ p["wo"]
                h2 = _rms_weight(x, p["ln2"], eps)
                a = jax.nn.silu((h2 @ p["gate"]).astype(jnp.float32)
                                ).astype(h2.dtype) * (h2 @ p["up"])
                return x + a @ p["down"], (kcl, vcl)

            x, (kc, vc) = lax.scan(body, x, (params["layers"], kc, vc))
            h = _rms_weight(x, params["norm_f"], eps)
            hsel = h[last_idx]                                # [Bp, H]
            logits = (hsel.astype(jnp.float32)
                      @ params["head"].astype(jnp.float32))
            return sample_tokens(logits, samp), kc, vc

        return run, (1, 2)

    def _build_prefill_chunked(self, Tp: int, Bp: int):
        run, donate = self._make_chunked_fn(Tp, Bp)
        if jax.default_backend() == "cpu":
            donate = ()
        return jax.jit(run, donate_argnums=donate)

    def _make_chunked_fn(self, Tp: int, Bp: int):
        """Chunk prefill: tokens enter at ABSOLUTE positions (a resumed
        chunk or a cache-hit suffix starts mid-sequence).  Each layer
        writes the chunk's K/V into the paged cache first, then gathers
        every sequence's pages back densely — so chunk tokens attend to
        cached-prefix positions this program never computed (the prefix
        pages carry KV written by an earlier chunk/request)."""
        nh, kvh, d = self._nh, self._kvh, self._hd
        bs = self.block_size
        nblk = self.nblk
        S = nblk * bs
        eps = self.config.rms_norm_eps
        theta = self.config.rope_theta
        sm_scale = 1.0 / (d ** 0.5)

        def run(params, kc, vc, toks, seg, rel, bt, last_idx, samp):
            # toks/seg/rel [Tp] int32 (pads: seg == Bp -> the null row of
            # bt); rel is each token's absolute position; bt [Bp+1, nblk];
            # last_idx [Bp] flat index of each chunk's final token.
            x = jnp.take(params["embed"], toks, axis=0)       # [Tp, H]
            keypos = jnp.arange(S, dtype=jnp.int32)

            def body(x, inp):
                p, kcl, vcl = inp
                h = _rms_weight(x, p["ln1"], eps)
                q = (h @ p["wq"]).reshape(Tp, nh, d)
                k = (h @ p["wk"]).reshape(Tp, kvh, d)
                v = (h @ p["wv"]).reshape(Tp, kvh, d)
                q = _rope_positions(q, rel, theta)
                k = _rope_positions(k, rel, theta)
                blk = bt[seg, rel // bs]                      # [Tp]
                slot = rel % bs
                kcl = kcl.at[blk, :, slot, :].set(k.astype(kcl.dtype))
                vcl = vcl.at[blk, :, slot, :].set(v.astype(vcl.dtype))
                # gather each sequence's pages to [Bp+1, S, kvh, d]
                kg = kcl[bt].transpose(0, 1, 3, 2, 4) \
                    .reshape(Bp + 1, S, kvh, d)
                vg = vcl[bt].transpose(0, 1, 3, 2, 4) \
                    .reshape(Bp + 1, S, kvh, d)
                kq = kg[seg]                                  # [Tp, S, kvh, d]
                vq = vg[seg]
                if kvh != nh:
                    kq = jnp.repeat(kq, nh // kvh, axis=2)
                    vq = jnp.repeat(vq, nh // kvh, axis=2)
                sc = jnp.einsum("qhd,qshd->qhs", q.astype(jnp.float32),
                                kq.astype(jnp.float32)) * sm_scale
                mask = keypos[None, None, :] <= rel[:, None, None]
                sc = jnp.where(mask, sc, -jnp.inf)
                pr = jax.nn.softmax(sc, axis=-1)
                att = jnp.einsum("qhs,qshd->qhd", pr,
                                 vq.astype(jnp.float32)).astype(x.dtype)
                x = x + att.reshape(Tp, nh * d) @ p["wo"]
                h2 = _rms_weight(x, p["ln2"], eps)
                a = jax.nn.silu((h2 @ p["gate"]).astype(jnp.float32)
                                ).astype(h2.dtype) * (h2 @ p["up"])
                return x + a @ p["down"], (kcl, vcl)

            x, (kc, vc) = lax.scan(body, x, (params["layers"], kc, vc))
            h = _rms_weight(x, params["norm_f"], eps)
            hsel = h[last_idx]                                # [Bp, H]
            logits = (hsel.astype(jnp.float32)
                      @ params["head"].astype(jnp.float32))
            return sample_tokens(logits, samp), kc, vc

        return run, (1, 2)

    def _run_prefill(self, chunks: list):
        """chunks: [(req, n_chunk)].  Whole-prompt-from-zero batches ride
        the varlen program (PR-1 fast path, kernel-eligible on TPU);
        resumed chunks / cache-hit suffixes ride the chunked program."""
        classic = all(req.cached == 0 and n == len(req.tokens)
                      for req, n in chunks)
        total = sum(n for _, n in chunks)
        Tp, Bp = self._prefill_buckets(total, len(chunks))

        toks = np.zeros((Tp,), np.int32)
        seg = np.full((Tp,), Bp, np.int32)            # pads -> sentinel
        rel = np.zeros((Tp,), np.int32)
        bt = np.full((Bp + 1, self.nblk), NULL_BLOCK,
                     np.int32)                        # sentinel row: null
        last_idx = np.zeros((Bp,), np.int32)
        samp = make_samp(Bp, self.config.vocab_size)
        cu = np.zeros((Bp + 1,), np.int32)

        off = 0
        for i, (req, n) in enumerate(chunks):
            toks[off:off + n] = req.tokens[req.cached:req.cached + n]
            seg[off:off + n] = i
            rel[off:off + n] = np.arange(req.cached, req.cached + n)
            bt[i] = self.blocks.padded_table(req.rid, self.nblk)
            last_idx[i] = off + n - 1
            samp["temps"][i] = req.temperature
            samp["top_k"][i] = req.top_k
            samp["top_p"][i] = req.top_p
            samp["penalty"][i] = req.repetition_penalty
            if req.seen is not None:
                np.copyto(samp["seen"][i], req.seen)
            if req.temperature > 0.0:
                # only sampled rows need a key: all-greedy prefill steps
                # skip the per-request PRNG fold-in altogether
                samp["keys"][i] = self._req_key(req)
            off += n
            cu[i + 1] = off
        # empty trailing batch slots: zero-length sequences whose
        # last_idx points at token 0; their sampled token is discarded
        cu[len(chunks) + 1:] = off

        if classic:
            prog = self._get_prefill_prog(Tp, Bp)
            out, self._kc, self._vc = prog(self.params, self._kc, self._vc,
                                           toks, seg, rel, bt, cu,
                                           last_idx, samp)
        else:
            prog = self._get_chunked_prog(Tp, Bp)
            out, self._kc, self._vc = prog(self.params, self._kc, self._vc,
                                           toks, seg, rel, bt,
                                           last_idx, samp)
        out = np.asarray(out)
        return [out[i] for i in range(len(chunks))]


# graft-lint import-of-engine hook: PT_ANALYSIS=strict refuses to import a
# serving module whose source carries ERROR-severity tracer hazards (the
# default 'off' mode is a single flag read).
from ..analysis import enforce_import as _enforce_import  # noqa: E402

_enforce_import(__name__, __file__)
