"""Continuous-batching decode engine over the paged-KV Pallas kernel.

The r5 kernel work (ops/pallas/paged_attention.py) gave single-token
decode over paged KV; what was missing is the ENGINE that serves a stream
of requests through it (the reference's serving stack around
block_multi_head_attention; vLLM's engine shape).  Three pieces:

- ``BlockManager`` (inference/kv_cache.py): a fixed page pool with
  per-sequence block tables — admission claims pages, decode grows them
  one page at a time, retirement/preemption returns them.  With prefix
  caching on (the default) the pool is content-addressed: admission
  matches each prompt's token chain against pages other sequences
  already computed, takes refcounted references on the hits, and only
  the MISS SUFFIX is allocated and prefilled.  Writes into a shared
  page copy it first (copy-on-write), and freed pages park in an LRU so
  a hot system prompt stays resident until the pool truly needs the
  space.

- A continuous-batching scheduler: every ``step()`` admits waiting
  requests into the running batch (no waiting for the batch to drain),
  retires sequences on eos/max-tokens, and — when the page pool is
  exhausted mid-decode — preempts the youngest sequence, returning its
  pages and requeuing it for recomputation (which now hits the prefix
  cache its own freed pages just populated).  Prefill is CHUNKED: each
  step packs at most ``max_prefill_tokens`` pending prompt tokens —
  partially-prefilled requests resume across steps at their absolute
  positions — so a long prompt never stalls running decodes; every
  step still runs one decode for the whole running set.

- ONE ragged compiled step program instead of per-phase programs
  (arxiv 2604.15464's serving shape): every step packs its whole mix —
  prefill chunks entering at absolute positions, resumed chunks,
  cache-hit suffixes, single decode tokens, and k-draft verify windows
  — as rows of flat query tokens described by ``(cu_seqlens, kv_lens,
  block_tables)``, padded to one token bucket.  Each layer writes the
  packed tokens' K/V into the paged cache, then one ragged
  paged-attention launch (ops/pallas/paged_attention.py) lets every
  token attend to its row's pages at its absolute position; a prefill
  chunk, a decode token, and a verify window differ only in their
  ``query_lens``.  On CPU the XLA dense-gather reference computes the
  same masked softmax (the oracle the byte-identity tests pin).  The
  caches thread through with buffer donation, so the
  [L, num_blocks, H_kv, bs, D] pool is updated in place on TPU instead
  of copied per step.

The decode math is term-for-term the math of ``_make_decode_fwd``
(models/llama.py), so greedy engine output is token-identical to
``LlamaForCausalLM.generate`` — with the prefix cache ON or OFF — and
tests/test_llm_engine.py + tests/test_prefix_cache.py hold the paths
together.

Speculative decoding (inference/spec_decode.py) rides the same cache
and the same program: a host-side ``Drafter`` proposes K tokens per
running sequence, the step packs each speculative sequence's
[last_token, d_1..d_k] window as one ragged row (the program returns
raw logits at every packed position alongside the sampled tokens), and
host-side rejection sampling accepts a prefix (greedy output stays
byte-identical to plain decode; sampled output follows the target
distribution exactly).  Rejected tokens roll back via
``BlockManager.truncate``.  Verify rows, prefill chunks, and
plain-decode rows share each step's single launch: per-request
``spec_k`` opts in, and a low acceptance rate auto-disables speculation
for that request.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.jaxcompat import shard_map
from ..models.llama import _rms_weight, _rope_positions
from ..ops.pallas import paged_attention as _pa
from ..ops.pallas import quant_matmul as _qm
from ..profiler import RecordEvent, ServingStats
from .faults import InjectedFault
from .kv_cache import (NULL_BLOCK, BlockManager, BlockPoolExhausted,
                       prefix_chain_hashes)
from .policy import pack_prefill_chunks
from .pressure import STATE_NAMES as _TIER_NAMES
from .sampling import (advance_keys, make_samp, samp_structs,
                       sample_tokens)

__all__ = ["LLMEngine", "Request", "RequestOutput"]


@dataclass
class Request:
    """One generation request in the engine's queues."""
    rid: int
    prompt: list                      # original prompt token ids
    max_new_tokens: int
    temperature: float
    eos_token_id: object              # int | None
    seed: int
    top_k: int = 0                    # 0 -> off
    top_p: float = 1.0                # 1.0 -> off
    repetition_penalty: float = 1.0   # 1.0 -> off
    spec_k: int = 0                   # max draft tokens per verify round
    # scheduler state
    tokens: list = field(default_factory=list)   # tokens to (re)prefill
    generated: list = field(default_factory=list)
    cached: int = 0                   # positions whose KV is in the pool
    arrival: int = 0                  # admission priority (FCFS)
    slot: int = -1                    # stable decode-batch slot
    t_arrival: float = 0.0            # wall clock at add_request (TTFT)
    seen: object = None               # [V] bool penalty mask (lazy)
    spec_proposed: int = 0            # drafts sent to verify (lifetime)
    spec_accepted: int = 0            # drafts accepted (lifetime)
    spec_disabled: bool = False       # acceptance fell below the floor
    tier_checked: int = -1            # spill-tier generation last consulted
    # streaming hooks (both called from the engine's stepping thread)
    on_token: object = None           # callable(rid, token) per emission
    on_finish: object = None          # callable(RequestOutput) at the end


@dataclass
class RequestOutput:
    rid: int
    prompt: list
    generated: list                   # includes the eos token when hit
    finish_reason: str                # "eos" | "length" | abort reason
                                      # ("aborted", "deadline", ...)

    @property
    def token_ids(self):
        return list(self.prompt) + list(self.generated)


@dataclass
class _StepTicket:
    """One dispatched-but-not-completed ragged launch.

    ``dispatch()`` fills it with the launch's UNMATERIALIZED device
    arrays plus the packed-row layout needed to apply them; ``complete()``
    pops it, blocks on the arrays, and commits.  The pipeline is depth-1
    by design: the next dispatch needs the sampled tokens this ticket
    carries (a decode row's input IS the previous step's output), so at
    most one launch is ever in flight."""
    chunks: list
    spec: list
    batch: list
    sampled: object                   # device array (async, not blocked)
    logits: object                    # device array | None
    fin: object                       # device array
    spec_slices: list
    chunk_slots: list
    batch_slots: list
    dispatch_s: float                 # host seconds packing + launching
    t_launch: float                   # perf_counter at launch return
    launch_ns: int                    # tracer clock at launch (0 untraced)
    inflight: bool = False            # crossed a step() boundary in flight
    window: int = 0                   # K of a decode-window launch (0 =
                                      # per-step; sampled/fin are [K, B])


class _DecodeBufs:
    """One set of persistent host-side pack buffers for the pure-decode
    fast path.  With overlap on the engine holds TWO and alternates
    launches between them: CPU PJRT may zero-copy alias an aligned host
    array into the program's input, so the buffers of an in-flight
    launch must not be rewritten until its results materialize.

    ``bt_ver`` maps rid -> the block-table version staged into THIS
    buffer's ``bt`` row (the per-buffer replacement for the old
    per-request ``bt_version`` field: each buffer tracks its own
    staleness).  ``layout`` is the rid order last packed."""

    __slots__ = ("toks", "cu", "kvl", "bt", "samp", "layout", "bt_ver")

    def __init__(self, B, nblk, Lq, vocab_size):
        self.toks = np.zeros((B,), np.int32)
        self.cu = np.zeros((B + 1,), np.int32)
        self.kvl = np.zeros((B,), np.int32)
        self.bt = np.full((B + 1, nblk), NULL_BLOCK, np.int32)
        self.samp = make_samp(Lq, vocab_size)
        self.layout: tuple = ()
        self.bt_ver: dict = {}


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class LLMEngine:
    """Continuous-batching serving loop over one LlamaForCausalLM.

    Parameters
    ----------
    model: LlamaForCausalLM (weights are snapshot via decode_params()).
    max_num_seqs: decode-batch capacity (the padded decode batch size).
    block_size: KV page size in tokens (must satisfy the paged kernel's
        bs % 8 == 0 to be kernel-eligible on TPU).
    num_blocks: page-pool size.  Default sizes the pool so every batch
        slot can reach max_model_len (no preemption under the default).
    max_model_len: longest prompt+generation the engine accepts; fixes
        the static block-table width of the decode program.
    max_prefill_tokens: per-STEP prompt-token budget.  Prompts longer
        than this are prefilled in chunks across steps (decode of the
        running set proceeds every step regardless).
    prefill_token_bucket: the ragged step's flat token buffer is padded
        to max_num_seqs for decode-sized launches and to a multiple of
        this above it, bounding the number of compiled step programs by
        max_prefill_tokens / bucket + 1.
    enable_prefix_caching: content-hash full KV pages and reuse them
        across requests sharing a token prefix (BlockManager docstring
        has the page lifecycle).  Greedy output is byte-identical on
        or off.
    drafter: a spec_decode.Drafter (or the string "ngram" for the
        prompt-lookup drafter) proposing draft tokens; None disables
        speculative decoding engine-wide.
    spec_k: default per-request draft length (requests may override via
        add_request(spec_k=); 0 means plain decode).
    max_spec_k: hard per-round draft ceiling; fixes the ragged program's
        static logit-row width max_num_seqs * (max_spec_k + 1).
    spec_accept_floor / spec_window: once a request has sent spec_window
        drafts to verify, speculation auto-disables for it if its
        lifetime acceptance rate sits below the floor (the drafter is
        not helping; stop paying the verify overhead).
    kv_dtype: "float32" (full-width pages in the model dtype) or "int8"
        (pages quantize symmetrically at commit time with per-page-per-
        head f32 scales in a parallel pool; attention dequantizes inline
        at read time).  Int8 pages cost ~4x less HBM per resident
        sequence; greedy outputs are near-identical, gated by the
        tolerance oracle in tests rather than byte-equality.
    retain_outputs: keep every finished RequestOutput in the dict that
        ``run()`` returns.  A long-running server (the HTTP frontend)
        passes False — outputs are delivered through each request's
        ``on_finish`` callback instead, so finished requests cost no
        memory once their stream closes.
    tp: tensor-parallel degree.  tp > 1 lays the SAME ragged step over a
        1-D device mesh via shard_map: attention heads (Hq and Hkv) and
        the KV/scale page pools shard per chip along the head axis,
        block tables and (cu_seqlens, kv_lens) replicate, and one
        all-gather of per-shard attention heads (plus logit slices when
        vocab_size % tp == 0) runs INSIDE the compiled step — the host
        still sees one launch per step and ``compile_counts`` still
        counts one attention program kind.  Requires num_attention_heads
        % tp == 0 and num_key_value_heads % tp == 0.  Head partitioning
        is by contiguous blocks, so GQA group structure is preserved and
        greedy outputs stay byte-identical to tp=1.  Host bookkeeping
        (BlockManager, scheduler, sampling params) is untouched — it is
        mesh-blind.  Testable on CPU via
        ``XLA_FLAGS=--xla_force_host_platform_device_count=N``.
    overlap: run the step loop as a dispatch/completion PIPELINE (the
        default).  ``step()`` first pre-stages and completes the launch
        the previous call left in flight, then dispatches this call's
        launch WITHOUT materializing its results — JAX async dispatch
        keeps the device busy across the step boundary while the host
        does the next call's admission/scheduling/packing.  Greedy
        output is byte-identical on or off and ``compile_counts`` is
        unchanged (the pipeline adds zero programs); the visible
        difference is that a request's outputs surface one ``step()``
        call later and ``run()`` takes one extra draining call.  False
        restores the fully synchronous launch-then-block step.
    decode_window: K > 1 runs STEADY pure-decode packs as one
        device-resident K-step window: a single compiled program loops
        attention -> logit-processor chain -> sampling -> paged K/V
        append K times on device (sampled tokens, per-row PRNG keys,
        ``seen`` masks, and kv_lens carried as loop state), and the host
        drains up to K committed tokens per launch instead of paying a
        round-trip per token.  Rows hitting eos/length freeze under an
        active-mask (the loop exits early when every row is done); block
        tables refresh only at window boundaries, with K tokens of page
        slack pre-reserved per row before launch — when the pool cannot
        cover the window the step falls back to the per-step path (never
        preempting for a window).  Mixed packs (prefill chunks, verify
        rows) and waiting-queue pressure always take the per-step path,
        so admission latency is unchanged.  Greedy output is
        byte-identical to decode_window=1; ``compile_counts`` gains at
        most one "scan" program kind, only when a window launches.

    The engine is SINGLE-THREADED by design: add_request/step/abort must
    all be called from one thread (the frontend's EngineRunner owns that
    thread and bridges other threads in via queues drained at step
    boundaries).  abort() in particular relies on being between steps,
    when pool state is consistent.
    """

    def __init__(self, model, *, max_num_seqs: int = 8, block_size: int = 16,
                 num_blocks: int | None = None, max_model_len: int | None = None,
                 max_prefill_tokens: int = 512,
                 prefill_token_bucket: int = 64,
                 enable_prefix_caching: bool = True,
                 drafter=None, spec_k: int = 0, max_spec_k: int = 8,
                 spec_accept_floor: float = 0.35, spec_window: int = 32,
                 retain_outputs: bool = True,
                 fault_plan=None, pressure=None,
                 kv_dtype: str = "float32", tp: int = 1,
                 tracer=None, overlap: bool = True,
                 decode_window: int = 1,
                 weight_dtype: str = "float32",
                 kv_tier=None):
        cfg = model.config
        self.config = cfg
        self.params = model.decode_params()
        if kv_dtype not in ("float32", "int8"):
            raise ValueError(
                f"kv_dtype must be 'float32' or 'int8', got {kv_dtype!r}")
        self.kv_dtype = kv_dtype
        if weight_dtype not in ("float32", "int8", "int4"):
            raise ValueError(
                "weight_dtype must be 'float32', 'int8' or 'int4', "
                f"got {weight_dtype!r}")
        self.weight_dtype = weight_dtype
        # the step's activations keep the model's float dtype even when
        # the embed table is about to become a quantized pool + scales
        self._act_dtype = self.params["embed"].dtype
        if self.weight_dtype != "float32":
            self.params = self._quantize_params(self.params)
        self.tp = int(tp)
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        if self.tp > 1:
            if (cfg.num_attention_heads % self.tp
                    or cfg.num_key_value_heads % self.tp):
                raise ValueError(
                    f"tp={self.tp} must divide num_attention_heads="
                    f"{cfg.num_attention_heads} and num_key_value_heads="
                    f"{cfg.num_key_value_heads} (contiguous head "
                    "partition keeps GQA groups on one shard)")
            from ..distributed.auto_parallel.process_mesh import ProcessMesh
            self._mesh = ProcessMesh(list(range(self.tp)),
                                     dim_names=["tp"]).jax_mesh()
        else:
            self._mesh = None
        # the unembedding shards over vocab only when it divides evenly
        # (padding the vocab axis would poison the per-row finiteness
        # flag); otherwise the head matmul replicates and the per-layer
        # attention-head all-gather is the step's collective
        self._shard_head = self.tp > 1 and cfg.vocab_size % self.tp == 0
        self.max_num_seqs = int(max_num_seqs)
        self.block_size = int(block_size)
        self.max_model_len = int(max_model_len or cfg.max_position_embeddings)
        self.max_prefill_tokens = int(max_prefill_tokens)
        self.prefill_token_bucket = int(prefill_token_bucket)
        self.enable_prefix_caching = bool(enable_prefix_caching)

        # static block-table width: pages needed by a max-length sequence
        self.nblk = -(-self.max_model_len // self.block_size)
        if num_blocks is None:
            num_blocks = 1 + self.max_num_seqs * self.nblk
        self.blocks = BlockManager(
            num_blocks, self.block_size,
            enable_prefix_caching=self.enable_prefix_caching)
        if self.blocks.num_free < self.nblk:
            raise ValueError(
                f"num_blocks={num_blocks} cannot hold even one "
                f"max_model_len={self.max_model_len} sequence "
                f"({self.nblk} pages needed)")
        # hierarchical KV: a HostSpillPool (inference/kv_tier.py) turns
        # EVICT_PARKED from kill into spill — pages quarantine in the
        # pool and move host-side at the step-boundary drain, and
        # admission gets them back as ordinary prefix-cache content
        self.kv_tier = kv_tier
        if kv_tier is not None:
            self.blocks.spill_on_evict = True
        # chain hashes restored from the tier and not yet claimed by an
        # admission hit (prefetch-hit attribution is by hash, so block
        # reuse can never misattribute)
        self._staged_hashes: set = set()

        self._nh = cfg.num_attention_heads
        self._kvh = cfg.num_key_value_heads
        self._hd = cfg.hidden_size // self._nh
        L = cfg.num_hidden_layers
        dt = self._act_dtype
        if self.kv_dtype == "int8":
            # int8 pages + a parallel per-page-per-head f32 scale pool
            # (symmetric: float = int8 * scale).  Scales are written at
            # commit time inside the step program; the kernel/reference
            # dequantizes inline at read time, so every host-side page
            # structure (hashing, CoW, sharing, parking) is unchanged.
            self._kc = jnp.zeros((L, num_blocks, self._kvh,
                                  self.block_size, self._hd), jnp.int8)
            self._vc = jnp.zeros_like(self._kc)
            self._ks = jnp.zeros((L, num_blocks, self._kvh), jnp.float32)
            self._vs = jnp.zeros_like(self._ks)
        else:
            # "float32" means full-width model dtype (f32/bf16) pages
            self._kc = jnp.zeros((L, num_blocks, self._kvh,
                                  self.block_size, self._hd), dt)
            self._vc = jnp.zeros_like(self._kc)
            self._ks = self._vs = None
        if self.tp > 1:
            # lay the pools and the head-partitioned weights out on the
            # mesh ONCE at construction; every step launch then runs
            # without resharding transfers
            self.params = self._shard_params(self.params)
            kv_sh = NamedSharding(self._mesh, P(None, None, "tp"))
            self._kv_sharding = kv_sh
            self._kc = jax.device_put(self._kc, kv_sh)
            self._vc = jax.device_put(self._vc, kv_sh)
            if self._ks is not None:
                self._ks = jax.device_put(self._ks, kv_sh)
                self._vs = jax.device_put(self._vs, kv_sh)
        # scale-reset feed: pages BlockManager handed out since the last
        # launch (their old scales are dead); consumed by _launch_ragged
        self._fresh_np = np.zeros((num_blocks,), bool)

        self._waiting: deque = deque()
        self._running: list = []
        self._finished: dict = {}
        self._next_rid = 0
        self._arrival = 0
        self.retain_outputs = bool(retain_outputs)

        # stable batch slots (pure-decode steps pack rows in slot order,
        # so a steady batch keeps a stable layout) + persistent host-side
        # buffers for the decode fast path: rows are updated
        # incrementally (grow/retire/CoW bump the table version, any
        # membership/order change breaks the layout signature) instead of
        # rebuilt from scratch every token
        B = self.max_num_seqs
        self._slot_used = [False] * B

        # speculative decoding: a host-side drafter proposes up to
        # max_spec_k tokens per decode-ready sequence; each speculative
        # sequence rides the step's single ragged launch as one
        # [last_token, drafts...] row
        if drafter == "ngram":
            from .spec_decode import NGramDrafter
            drafter = NGramDrafter()
        self.drafter = drafter
        self.spec_k = int(spec_k)
        self.max_spec_k = int(max_spec_k)
        self.spec_accept_floor = float(spec_accept_floor)
        self.spec_window = int(spec_window)
        # logit-row width of the ragged program: spec rows need k+1
        # scored positions each; without a drafter one row == one logit.
        # The program returns raw per-position logits (for host-side
        # draft acceptance) only when a drafter exists.
        self._with_logits = drafter is not None
        self._Lq = B * (self.max_spec_k + 1) if self._with_logits else B

        # decode fast-path buffers (general mixed launches repack from
        # scratch; steady pure-decode steps reuse these).  Two sets:
        # with overlap on, launches alternate buffers so the host never
        # rewrites arrays a still-in-flight launch may be aliasing
        # (overlap off only ever touches buffer 0).  lidx is read-only
        # to the program and safely shared between them.
        self.overlap = bool(overlap)
        self._dbufs = (_DecodeBufs(B, self.nblk, self._Lq, cfg.vocab_size),
                       _DecodeBufs(B, self.nblk, self._Lq, cfg.vocab_size))
        self._d_cur = 0                   # buffer of the latest launch
        self._d_lidx = np.minimum(np.arange(self._Lq), B - 1) \
            .astype(np.int32)
        # dispatch/completion pipeline state (depth-1 queue)
        self._inflight: _StepTicket | None = None
        self._prestaged = None            # (buf index, layout) when valid
        self._pending_finished: list = [] # finishes from an abort() flush
        self._spec_pages: dict = {}       # rid -> pages prestage reserved

        # program cache: ONE attention program kind, keyed only by the
        # flat-token bucket Tq.  The counter dict is the test-visible
        # compile-count regression guard: every program BUILD (not call)
        # bumps its kind, so a mixed stream can assert "exactly N
        # programs" without reaching into the caches.
        self._ragged_progs: dict = {}
        self._cow_prog = None
        self.compile_counts = {"ragged": 0, "cow": 0}
        # device-resident decode window (K > 1): one extra program kind
        # ("scan") cached here, NOT in _ragged_progs — the decode/prefill
        # program-count properties stay exact.  The "scan" key joins
        # compile_counts only when a window actually compiles, so
        # decode_window=1 engines keep the historical exact-dict budgets.
        self.decode_window = int(decode_window)
        if self.decode_window < 1:
            raise ValueError(
                f"decode_window must be >= 1, got {decode_window}")
        self._window_prog = None
        # padding accounting: real packed tokens vs bucket width, plus
        # what the pre-ragged four-program engine would have padded to
        # (serve_bench --mixed reports the two ratios side by side)
        self.pad_stats = {"real": 0, "padded": 0, "legacy_padded": 0}
        self._evictions_seen = 0
        self.peak_resident_seqs = 0
        self.stats = ServingStats()
        self.stats.set_decode_window(self.decode_window)
        self.stats.set_weight_residency(
            self.weight_dtype, self.weight_bytes_resident(),
            self.weight_bytes_resident_per_shard())
        # per-request flight recorder (inference/flight.py): None means
        # every request-lifecycle seam is one attribute check and
        # nothing else — the tracer's zero-cost contract
        self.flight = None
        # step-timeline tracer (profiler/trace.py): None means every
        # instrumentation seam is one attribute check and nothing else —
        # the same zero-cost contract the fault plan keeps
        self.tracer = None
        self._trace_track = "engine"
        self._trace_steps = 0
        # resolve this engine's launch geometry from the tuning cache
        # once at build — pure host-side dict reads (no compile) whose
        # provenance summary() and serve_bench records surface
        self._tuning_report = self._resolve_tuning()

        # fault-tolerance surfaces: a FaultPlan drives deterministic
        # chaos through the step/pool seams (None -> one attribute check
        # per step); a DegradationController (inference/pressure.py)
        # sheds load in tiers before preemption becomes necessary
        self.fault_plan = None
        self.set_fault_plan(fault_plan)
        self.pressure = pressure
        self.set_tracer(tracer)

    def set_fault_plan(self, plan) -> None:
        """Install (or clear) a FaultPlan on this engine and its pool.
        The runner re-installs the same plan on a rebuilt engine, so a
        schedule survives recovery with its consumed faults consumed."""
        self.fault_plan = plan
        self.blocks._fault_hook = plan.pool_exhausted \
            if plan is not None else None
        if plan is not None:
            plan.tracer = self.tracer
            plan.trace_track = self._trace_track

    def set_tracer(self, tracer) -> None:
        """Install (or clear) a step-timeline Tracer on this engine (and
        on its fault plan, so injected faults land in the trace).  With
        None installed the step loop performs no trace work at all."""
        self.tracer = tracer
        if tracer is not None:
            self._trace_track = tracer.register("engine")
        if self.fault_plan is not None:
            self.fault_plan.tracer = tracer
            self.fault_plan.trace_track = self._trace_track

    def set_flight(self, recorder) -> None:
        """Install (or clear) a per-request FlightRecorder
        (inference/flight.py).  With None installed the request
        lifecycle seams perform no forensic work at all."""
        self.flight = recorder

    def _tier(self) -> int:
        """Current degradation tier (0 when no pressure controller)."""
        return 0 if self.pressure is None else self.pressure.state

    def dump_trace(self, path) -> int:
        """Write this engine's step timeline as Chrome trace-event JSON
        (Perfetto-loadable); returns the number of events written.
        Raises when tracing was never enabled."""
        if self.tracer is None:
            raise RuntimeError(
                "tracing is not enabled: build the engine with tracer= "
                "or call set_tracer() first")
        return self.tracer.dump(path)

    # ------------------------------------------------------------------
    # quantized weight pools (weight_dtype != "float32")
    # ------------------------------------------------------------------

    def _quantize_params(self, params) -> dict:
        """Quantize decode_params ONCE at engine build into the pool
        layout the fused dequant-matmul kernel streams.

        Every projection/MLP weight ``name`` becomes a ``name_q``
        quantized pool + ``name_s`` f32 scale tensor (int8:
        per-output-channel; int4: nibble-packed with per-128-row-group
        scales — see ops/pallas/quant_matmul.py); the embedding becomes
        a per-vocab-row pool dequantized inline at gather.  Norms stay
        f32 — they are O(H) gauge vectors, not bandwidth.  Runs BEFORE
        ``_shard_params``: column-slicing commutes with quantization,
        so tp=N shards the pools and scales by the same head/column
        blocks with no resharding."""
        wdt = self.weight_dtype
        layers = params["layers"]
        out_layers = {"ln1": layers["ln1"], "ln2": layers["ln2"]}
        quant = jax.vmap(lambda w: _qm.quantize_weight(w, wdt))
        for name in ("wq", "wk", "wv", "wo", "gate", "up", "down"):
            q, s = quant(layers[name])
            out_layers[name + "_q"] = q
            out_layers[name + "_s"] = s
        eq, es = _qm.quantize_embedding(params["embed"], wdt)
        hq, hs = _qm.quantize_weight(params["head"], wdt)
        return {"layers": out_layers, "embed_q": eq, "embed_s": es,
                "norm_f": params["norm_f"], "head_q": hq, "head_s": hs}

    def _weight_ops(self):
        """(mm, embed, head_logits) for the step bodies, resolved once
        per program build.

        f32 engines get the literal dense expressions (byte-identity
        with every pre-quantization program); quantized engines route
        every projection/MLP/head matmul through the fused
        dequant-matmul kernel on TPU (or under a forced interpreter)
        and through its term-identical XLA fake-quant reference
        everywhere else — the same split-contract the paged attention
        kernel keeps."""
        dt = self._act_dtype
        wdt = self.weight_dtype
        if wdt != "float32":
            use_qmm = _qm.INTERPRET is True or \
                jax.default_backend() == "tpu"

            def mm(h, p, name):
                q, s = p[name + "_q"], p[name + "_s"]
                if use_qmm and _qm.supports(h.shape[0], h.shape[1],
                                            q.shape[-1], wdt):
                    out = _qm.matmul(h, q, s, weight_dtype=wdt)
                else:
                    out = _qm.reference_matmul(h, q, s, wdt)
                return out.astype(h.dtype)

            def embed(params, toks):
                return _qm.dequantize_rows(
                    jnp.take(params["embed_q"], toks, axis=0),
                    jnp.take(params["embed_s"], toks), wdt).astype(dt)

            def head_logits(params, hsel):
                q, s = params["head_q"], params["head_s"]
                if use_qmm and _qm.supports(hsel.shape[0], hsel.shape[1],
                                            q.shape[-1], wdt):
                    return _qm.matmul(hsel.astype(jnp.float32), q, s,
                                      weight_dtype=wdt)
                return _qm.reference_matmul(hsel, q, s, wdt)
        else:
            def mm(h, p, name):
                return h @ p[name]

            def embed(params, toks):
                return jnp.take(params["embed"], toks, axis=0)

            def head_logits(params, hsel):
                return (hsel.astype(jnp.float32)
                        @ params["head"].astype(jnp.float32))
        return mm, embed, head_logits

    # ------------------------------------------------------------------
    # tensor-parallel layout (tp > 1)
    # ------------------------------------------------------------------

    def _param_specs(self) -> dict:
        """PartitionSpec pytree for decode_params under the 1-D tp mesh.

        q/k/v projections column-shard along their HEAD output axis
        (leading L axis from the per-layer stack, then hidden, then
        heads*head_dim) — each shard computes its contiguous head block
        with the full replicated activation, so no contraction is ever
        split and greedy outputs stay byte-identical to tp=1.  wo, the
        MLP, and the norms replicate; the unembedding column-shards over
        vocab only when it divides evenly.

        Quantized engines shard the SAME axes: a quantized pool slices
        along its output-column axis exactly like the f32 weight it
        replaced, and its scales slice with it (int8 scales are
        per-output-column; int4 scales keep a leading row-group axis),
        so tp=N never reshards or requantizes.
        """
        layers = {k: P() for k in self.params["layers"]}
        if self.weight_dtype == "float32":
            for k in ("wq", "wk", "wv"):
                layers[k] = P(None, None, "tp")
            return {"layers": layers, "embed": P(), "norm_f": P(),
                    "head": P(None, "tp") if self._shard_head else P()}
        for k in ("wq_q", "wk_q", "wv_q"):
            layers[k] = P(None, None, "tp")
        scale_cols = P(None, "tp") if self.weight_dtype == "int8" \
            else P(None, None, "tp")
        for k in ("wq_s", "wk_s", "wv_s"):
            layers[k] = scale_cols
        out = {"layers": layers, "embed_q": P(), "embed_s": P(),
               "norm_f": P()}
        if self._shard_head:
            out["head_q"] = P(None, "tp")
            out["head_s"] = P("tp") if self.weight_dtype == "int8" \
                else P(None, "tp")
        else:
            out["head_q"] = out["head_s"] = P()
        return out

    def _shard_params(self, params) -> dict:
        # specs lead the map (a PartitionSpec is itself a tuple pytree,
        # so it must be the is_leaf-guarded side)
        return jax.tree_util.tree_map(
            lambda s, x: jax.device_put(x, NamedSharding(self._mesh, s)),
            self._param_specs(), params,
            is_leaf=lambda x: isinstance(x, P))

    def _step_specs(self, n_host_args: int):
        """(in_specs, out_specs) for the shard_map-wrapped ragged step.

        KV/scale pools shard along their H_kv axis; params follow
        ``_param_specs``; the ``n_host_args`` trailing host-packed
        operands (tokens, cu_seqlens, kv_lens, block tables, logit
        index, sampling pytree — plus the fresh-page mask in int8 mode)
        replicate, a single P() covering each pytree by prefix.  Every
        non-pool output (sampled tokens, finiteness flags, logits) is
        genuinely replicated after the in-step all-gathers, so its
        out_spec is P().
        """
        kv = P(None, None, "tp")
        pools = (kv, kv) if self.kv_dtype == "float32" else (kv,) * 4
        in_specs = (self._param_specs(), *pools) + (P(),) * n_host_args
        out_front = (P(), P(), P()) if self._with_logits else (P(), P())
        return in_specs, out_front + pools

    def _wrap_tp(self, run, n_host_args: int):
        """shard_map the step body over the tp mesh (identity at tp=1).

        check_vma=False: the body mixes replicated and sharded operands
        and resolves them with explicit all-gathers, the same contract
        as the auto-parallel tier's cached psum programs.
        """
        if self.tp == 1:
            return run
        in_specs, out_specs = self._step_specs(n_host_args)
        return shard_map(run, mesh=self._mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)

    # ------------------------------------------------------------------
    # request API
    # ------------------------------------------------------------------

    def add_request(self, prompt, max_new_tokens: int = 32,
                    temperature: float = 0.0, eos_token_id=None,
                    seed: int = 0, top_k: int = 0, top_p: float = 1.0,
                    repetition_penalty: float = 1.0,
                    spec_k: int | None = None, generated=None,
                    on_token=None, on_finish=None) -> int:
        """Queue one generation request; returns its rid.

        ``generated`` re-admits a request that already emitted tokens
        (the runner's crash-recovery replay): the request enters exactly
        as a preempted sequence would — prefill covers prompt+generated,
        ``max_new_tokens`` still counts from the ORIGINAL prompt — so
        with the same seed the continuation is byte-identical to the
        uninterrupted run (sampling keys derive from (seed,
        len(generated)), and the prefix cache makes the re-prefill
        cheap when the old engine's pages survived).
        """
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        generated = [int(t) for t in (generated or [])]
        if len(generated) >= int(max_new_tokens):
            raise ValueError(
                f"continuation already holds {len(generated)} of "
                f"max_new_tokens={max_new_tokens} tokens")
        if len(prompt) + int(max_new_tokens) > self.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_model_len "
                f"({self.max_model_len})")
        if not 0.0 < float(top_p) <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if int(top_k) < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if float(repetition_penalty) <= 0.0:
            raise ValueError(
                f"repetition_penalty must be > 0, got {repetition_penalty}")
        if spec_k is None:
            spec_k = self.spec_k
        spec_k = min(int(spec_k), self.max_spec_k) \
            if self.drafter is not None else 0
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid=rid, prompt=prompt,
                      tokens=list(prompt) + generated,
                      generated=list(generated),
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature),
                      eos_token_id=eos_token_id, seed=int(seed),
                      top_k=int(top_k), top_p=float(top_p),
                      repetition_penalty=float(repetition_penalty),
                      spec_k=spec_k, t_arrival=time.perf_counter(),
                      on_token=on_token, on_finish=on_finish)
        if req.repetition_penalty != 1.0:
            req.seen = np.zeros((self.config.vocab_size,), bool)
            req.seen[prompt] = True
            req.seen[generated] = True
        self._waiting.append(req)
        fl = self.flight
        if fl is not None:
            fl.open(rid, prompt_tokens=len(prompt),
                    t_submit=req.t_arrival)
        tr = self.tracer
        if tr is not None:
            tr.async_begin("req", f"{self._trace_track}:{rid}",
                           args={"rid": rid,
                                 "prompt_tokens": len(prompt),
                                 "replayed": len(generated),
                                 "max_new_tokens": int(max_new_tokens)})
            tr.instant("request.queued", track=self._trace_track,
                       args={"rid": rid})
        return rid

    def has_unfinished(self) -> bool:
        # an in-flight launch still owes its completion even when every
        # queue is empty — run() drains the pipeline through it; same
        # for outputs an abort() flush buffered for the next step()
        return bool(self._waiting or self._running
                    or self._inflight is not None
                    or self._pending_finished)

    def abort(self, request_id: int, finish_reason: str = "aborted"):
        """Retire a request before it finishes — the client disconnected,
        its deadline passed, or the server is shedding it.

        Works at ANY point of the request's lifetime as observed between
        steps: still queued (nothing allocated), mid-chunked-prefill
        (pages for the already-prefilled prefix are live, resume state in
        ``req.cached``), mid-decode, or mid-speculation (the post-verify
        ``truncate`` already rolled back rejected drafts, so pool state
        is consistent at every step boundary).  Pages return through
        ``BlockManager.release`` — the abort-hardened path that only
        DECREFS pages shared with live neighbours (their chain hashes
        survive, so aborting one reader of a hot system prompt never
        evicts it) and never registers the aborted tail.

        Returns the partial RequestOutput, or None when request_id is
        unknown or already finished (an abort racing a natural finish is
        a benign, COUNTED no-op — ``stats.abort_noops`` — never an
        error).  Must be called from the engine's stepping thread,
        between steps — the frontend's EngineRunner queues cross-thread
        aborts and applies them at the next step boundary.
        """
        # flush the pipeline first: an in-flight launch may hold this
        # very request as a packed row, and completing it leaves pool
        # and queues in the consistent between-steps state the abort
        # paths (and their callers) assume.  The victim's own rows are
        # DROPPED unapplied — the caller decided to abort against the
        # state it could observe (tokens through the last completed
        # step), so the in-flight step's token for this request is
        # discarded and the abort output reports exactly the observable
        # prefix, same as a synchronous abort.  Other rows commit and
        # retire as usual; their outputs surface from the next step().
        if self._inflight is not None:
            self._complete(self.tracer, self._pending_finished,
                           drop_rid=request_id)
        self._spec_pages.pop(request_id, None)
        req = None
        for r in self._running:
            if r.rid == request_id:
                req = r
                self._running.remove(r)
                self._release_slot(r)
                break
        else:
            for r in self._waiting:
                if r.rid == request_id:
                    req = r
                    self._waiting.remove(r)
                    break
        if req is None:
            self.stats.record_abort_noop()
            return None
        # a waiting request normally holds no pages — unless it was
        # preempted after generating (pages freed then) or never admitted
        # (never allocated); release() covers the running/mid-prefill case
        if self.blocks.has(req.rid):
            self.blocks.release(req.rid)
        if self.drafter is not None:
            self.drafter.release(req.rid)
        out = RequestOutput(rid=req.rid, prompt=list(req.prompt),
                            generated=list(req.generated),
                            finish_reason=finish_reason)
        if self.retain_outputs:
            self._finished[req.rid] = out
        self.stats.record_abort(finish_reason)
        if self.stats.windows is not None:
            self.stats.record_finish_quality(False)
            self.stats.record_request_latency(
                time.perf_counter() - req.t_arrival)
        fl = self.flight
        if fl is not None:
            fl.finished(req.rid, reason=finish_reason,
                        generated=len(req.generated),
                        tier=self._tier())
        tr = self.tracer
        if tr is not None:
            tr.async_end("req", f"{self._trace_track}:{req.rid}",
                         args={"finish_reason": finish_reason,
                               "generated": len(req.generated)})
        if req.on_finish is not None:
            req.on_finish(out)
        return out

    def _notify_tokens(self, req, toks) -> None:
        if req.on_token is not None:
            for t in toks:
                req.on_token(req.rid, int(t))

    @property
    def num_decode_programs(self) -> int:
        """Ragged programs at the decode-sized bucket (Tq == max_num_seqs)."""
        return sum(1 for Tq in self._ragged_progs
                   if Tq <= self.max_num_seqs)

    @property
    def num_prefill_programs(self) -> int:
        """Ragged programs at prefill-sized buckets (Tq > max_num_seqs)."""
        return sum(1 for Tq in self._ragged_progs
                   if Tq > self.max_num_seqs)

    def precompile_buckets(self) -> tuple:
        """Register the ragged-launch program for every reachable
        flat-token bucket, so no jit build ever lands inside the
        serving path.  The ladder is closed-form from the launch
        geometry: the decode-sized bucket, the speculation tier when a
        drafter is attached, and every prefill_token_bucket multiple up
        to the worst packable launch (a full max_prefill_tokens chunk
        budget plus every running row's tokens).  Idempotent; returns
        the ladder.  ``compile_counts`` lands at the ladder size and —
        because every later launch hits a registered bucket — stays
        there for the engine's whole life, which is what lets an A/B
        harness assert that a code path under test (e.g. the KV spill
        tier's restores) introduced no programs of its own."""
        tb = self.prefill_token_bucket
        ceiling = self.max_prefill_tokens + self._Lq
        ladder = {self.max_num_seqs}
        if self._with_logits and self.max_num_seqs < self._Lq < tb:
            ladder.add(self._Lq)
        ladder.update(range(tb, (-(-ceiling // tb) + 1) * tb, tb))
        for Tq in sorted(ladder):
            self._get_ragged_prog(Tq)
        return tuple(sorted(ladder))

    def run(self) -> dict:
        """Drive step() until every queued request finishes.  Outputs by
        rid; the run's metrics (incl. cache hits/misses, CoW copies,
        evictions, chunked-prefill queue depth) are in ``summary()``."""
        while self.has_unfinished():
            self.step()
        return dict(self._finished)

    def _resolve_tuning(self) -> dict:
        """Consult the kernel tuning cache once for this engine's launch
        geometry — per registered kernel: the bucket key queried, the
        config chosen, and whether a cache entry (exact or nearest
        bucket) answered.  Lookups are pure host-side dict reads; the
        kernels re-resolve the same keys at trace time, so this report
        is the provenance of the geometry the programs actually run."""
        from ..tune import cache_path, device_kind, kernel_config_with_meta
        dt = jnp.dtype(self._act_dtype).name
        d = self._hd
        shapes = {
            "flash_attention": {
                "seq_q": self.max_model_len, "seq_k": self.max_model_len,
                "head_dim": d, "dtype": dt},
            "flash_attention_varlen": {
                "seq_q": self.max_prefill_tokens,
                "seq_k": self.max_model_len, "head_dim": d, "dtype": dt},
            "fused_norms": {
                "rows": self.max_prefill_tokens,
                "hidden": self.config.hidden_size, "dtype": dt},
            "paged_attention": {
                "tq": self.prefill_token_bucket,
                "kv_heads": self._kvh // self.tp, "head_dim": d,
                "page": self.block_size, "nblk": self.nblk,
                "dtype": self.kv_dtype},
        }
        if self.weight_dtype != "float32":
            # the decode-shaped MLP projection — the step's biggest
            # weight stream and the shape the sweep's llama-class
            # buckets answer for
            shapes["quant_matmul"] = {
                "m": self.max_num_seqs, "k": self.config.hidden_size,
                "n": self.config.intermediate_size,
                "dtype": self.weight_dtype}
        kernels = {}
        for name, shape in shapes.items():
            config, meta = kernel_config_with_meta(name, shape)
            self.stats.record_tuning(name, bool(meta["hit"]))
            kernels[name] = {"hit": bool(meta["hit"]),
                             "source": meta["source"], "config": config,
                             "key": meta["key"]}
        return {"path": cache_path(), "device": device_kind(),
                "kernels": kernels}

    def summary(self) -> dict:
        """One dict of serving metrics + block-pool state for this run."""
        out = self.stats.summary()
        out["block_pool"] = self.blocks.stats()
        if self.kv_tier is not None:
            out["kv_tier"] = self.kv_tier.stats()
        out["kv_dtype"] = self.kv_dtype
        out["tp"] = self.tp
        out["kv_bytes_resident"] = self.kv_bytes_resident()
        out["kv_bytes_resident_per_shard"] = \
            self.kv_bytes_resident_per_shard()
        out["weight_dtype"] = self.weight_dtype
        out["weight_bytes_resident"] = self.weight_bytes_resident()
        out["weight_bytes_resident_per_shard"] = \
            self.weight_bytes_resident_per_shard()
        out["peak_resident_seqs"] = self.peak_resident_seqs
        out["tuning_cache"] = {
            "path": self._tuning_report["path"],
            "device": self._tuning_report["device"],
            "kernels": {k: dict(v) for k, v in
                        self._tuning_report["kernels"].items()},
        }
        return out

    def kv_page_bytes(self) -> int:
        """MESH-TOTAL device bytes one KV page costs: K and V slabs
        across every layer, plus the page's scale-pool rows in int8
        mode, summed over every tp shard."""
        L = self.config.num_hidden_layers
        per = (2 * L * self._kvh * self.block_size * self._hd
               * np.dtype(self._kc.dtype).itemsize)
        if self.kv_dtype == "int8":
            per += 2 * L * self._kvh * np.dtype(np.float32).itemsize
        return per

    def kv_page_bytes_per_shard(self) -> int:
        """Bytes one KV page costs ON ONE CHIP.  Pools shard along the
        H_kv axis (tp divides kvh, so page and scale slabs split
        exactly) — per-chip HBM is the binding capacity constraint, so
        pool sizing and pressure thresholds must use this figure under
        tp, not the mesh total."""
        return self.kv_page_bytes() // self.tp

    def kv_bytes_resident(self) -> int:
        """Device bytes holding real KV content: pages backing live
        sequences plus parked prefix pages (retained in HBM precisely so
        a prefix hit skips recompute; ``evict_parked`` reclaims them).
        Mesh-total under tp; the per-chip figure is
        ``kv_bytes_resident_per_shard``."""
        return ((self.blocks.num_used + self.blocks.num_cached)
                * self.kv_page_bytes())

    def kv_bytes_resident_per_shard(self) -> int:
        """Resident KV bytes on ONE chip of the tp mesh (equals the
        mesh total at tp=1) — the number a per-chip HBM budget or
        DegradationController threshold should be compared against."""
        return ((self.blocks.num_used + self.blocks.num_cached)
                * self.kv_page_bytes_per_shard())

    def weight_bytes_resident(self) -> int:
        """MESH-TOTAL device bytes holding the decode weights: the
        quantized pools + their f32 scales + the f32 norms (or the full
        f32 tree for weight_dtype='float32').  The other half of
        resident HBM next to ``kv_bytes_resident`` — int8 pools land
        ~4x under f32, int4 ~8x."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(self.params):
            total += int(np.prod(np.shape(leaf))) \
                * np.dtype(leaf.dtype).itemsize
        return total

    def weight_bytes_resident_per_shard(self) -> int:
        """Resident weight bytes on ONE chip of the tp mesh: sharded
        leaves (q/k/v pools + scales, and the head when vocab divides)
        contribute 1/tp of their mesh total, replicated leaves their
        full size — the per-chip HBM figure budgets compare against."""
        if self.tp == 1:
            return self.weight_bytes_resident()
        total = 0

        def add(spec, x):
            nonlocal total
            b = int(np.prod(np.shape(x))) * np.dtype(x.dtype).itemsize
            sharded = any(a is not None for a in spec)
            total += b // self.tp if sharded else b
            return x

        jax.tree_util.tree_map(add, self._param_specs(), self.params,
                               is_leaf=lambda x: isinstance(x, P))
        return total

    @property
    def degradation_tier_entries(self) -> int:
        """Escalating degradation-controller transitions (0 when no
        pressure controller is installed)."""
        return 0 if self.pressure is None else self.pressure.tier_entries

    def program_specs(self, *, large_bytes: int = 1 << 20) -> list:
        """Every program this engine compiles, as analysis ProgramSpecs.

        Arguments are ShapeDtypeStructs (nothing allocates or runs) and
        donate_argnums is the INTENDED device donation — the analyzer
        audits the TPU contract even when the process runs on CPU, where
        the builders drop donation.  ``graftlint --audit-serving`` and
        tests/test_serving_audit.py consume this.
        """
        from ..analysis import ProgramSpec

        sds = jax.ShapeDtypeStruct
        i32 = jnp.int32
        params = jax.tree_util.tree_map(
            lambda x: sds(np.shape(x), x.dtype), self.params)
        kc = sds(self._kc.shape, self._kc.dtype)
        vc = sds(self._vc.shape, self._vc.dtype)
        dt = self._act_dtype
        declared = dt if np.dtype(dt).name in ("bfloat16", "float16") \
            else None
        V = self.config.vocab_size
        B = self.max_num_seqs
        # representative token bucket: the smallest prefill-sized launch
        # (every other bucket traces the same fn at another Tq)
        Tq = max(self.prefill_token_bucket, B)

        rag_fn, rag_donate = self._make_ragged_fn(Tq)
        cow_fn, cow_donate = self._make_cow_fn()
        # a tp>1 engine compiles the SAME program kinds laid over the
        # mesh; the suffix keeps its audit entries distinct in reports.
        # Weight-quantized engines likewise keep the same kinds with a
        # dequant routed through the fused kernel path — their suffix
        # keeps the regenerated serving report's names collision-free
        # against the f32 engine's.
        sfx = {"int8": "_w8", "int4": "_w4"}.get(self.weight_dtype, "")
        sfx += f"_tp{self.tp}" if self.tp > 1 else ""

        def seqs(n):      # [n] i32 token/pos/index vectors
            return sds((n,), i32)

        # decode-window driver args: the [B]-wide carry seeds plus the
        # per-row freeze/key inputs (shared tail of both kv dtypes)
        win_tail = (seqs(B), seqs(B), sds((B,), jnp.bool_), seqs(B),
                    seqs(B), seqs(B), sds((B, 2), jnp.uint32),
                    sds((B + 1, self.nblk), i32), samp_structs(B, V))

        if self.kv_dtype == "int8":
            # the quantized step threads the scale pools (donated along
            # with the page pools) plus the per-launch fresh-page mask
            ks = sds(self._ks.shape, self._ks.dtype)
            vs = sds(self._vs.shape, self._vs.dtype)
            fresh = sds((self._kc.shape[1],), jnp.bool_)
            out = [
                ProgramSpec(
                    "serving.ragged_step_q8" + sfx, rag_fn,
                    (params, kc, vc, ks, vs, fresh, seqs(Tq), seqs(B + 1),
                     seqs(B), sds((B + 1, self.nblk), i32),
                     seqs(self._Lq), samp_structs(self._Lq, V)),
                    donate_argnums=rag_donate, declared_dtype=declared,
                    large_bytes=large_bytes),
                ProgramSpec(
                    "serving.cow_copy_q8" + sfx, cow_fn,
                    (kc, vc, ks, vs, sds((), i32), sds((), i32)),
                    donate_argnums=cow_donate, declared_dtype=declared,
                    large_bytes=large_bytes),
            ]
            if self.decode_window > 1:
                win_fn, win_donate = self._make_window_fn()
                out.append(ProgramSpec(
                    "serving.decode_window_q8" + sfx, win_fn,
                    (params, kc, vc, ks, vs, fresh) + win_tail,
                    donate_argnums=win_donate, declared_dtype=declared,
                    large_bytes=large_bytes))
            return out
        out = [
            ProgramSpec(
                "serving.ragged_step" + sfx, rag_fn,
                (params, kc, vc, seqs(Tq), seqs(B + 1), seqs(B),
                 sds((B + 1, self.nblk), i32), seqs(self._Lq),
                 samp_structs(self._Lq, V)),
                donate_argnums=rag_donate, declared_dtype=declared,
                large_bytes=large_bytes),
            ProgramSpec(
                "serving.cow_copy" + sfx, cow_fn,
                (kc, vc, sds((), i32), sds((), i32)),
                donate_argnums=cow_donate, declared_dtype=declared,
                large_bytes=large_bytes),
        ]
        if self.decode_window > 1:
            win_fn, win_donate = self._make_window_fn()
            out.append(ProgramSpec(
                "serving.decode_window" + sfx, win_fn,
                (params, kc, vc) + win_tail,
                donate_argnums=win_donate, declared_dtype=declared,
                large_bytes=large_bytes))
        return out

    # ------------------------------------------------------------------
    # scheduler
    # ------------------------------------------------------------------

    def _decode_ready(self, req) -> bool:
        """Prefill complete and exactly the last generated token's KV is
        still unwritten (the decode step writes it and samples the next)."""
        return (req.cached >= len(req.tokens)
                and req.cached == len(req.prompt) + len(req.generated) - 1)

    def step(self) -> list:
        """One engine iteration.  With ``overlap`` on (the default) this
        is one turn of the dispatch/completion PIPELINE: pre-stage the
        next pack while the previous call's launch is still on-device,
        block on and commit that launch, then dispatch this call's
        launch without materializing it.  Returns the requests that
        finished — under overlap these are the completions of the
        PREVIOUS call's dispatch (the pipeline's one-step latency).
        With ``overlap`` off the dispatch completes in the same call and
        the step is the classic synchronous admit -> schedule -> launch
        -> apply -> retire iteration.

        With a tracer installed every phase lands in the step timeline
        (dispatch: admit / schedule / pack / block-table stage / device
        launch; complete: block-on-result / sample-commit / retire; plus
        prestage and the device in-flight window); with none the phase
        seams are single attribute checks."""
        tr = self.tracer
        if tr is None:
            return self._step(None)
        self._trace_steps += 1
        t0 = tr.now()
        finished = self._step(tr)
        tr.complete("engine.step", t0, track=self._trace_track,
                    args={"step": self._trace_steps,
                          "finished": len(finished)})
        return finished

    def _step(self, tr) -> list:
        # outputs that finished inside an abort()'s pipeline flush
        # surface here, so the step()-return channel never drops one
        finished = self._pending_finished
        self._pending_finished = []
        if self._inflight is not None:
            # the launch from the previous step() call is (possibly)
            # still running on-device: do next step's speculative host
            # work first, INSIDE that window, then block on the ticket
            self._prestage(tr)
            self._complete(tr, finished)
        if self.kv_tier is not None:
            # step boundary: no launch is in flight (completion above
            # materialized the pools), and restores land before this
            # step's admission packs only the residual prefill suffix
            self._drain_kv_tier(tr)
        self._dispatch(tr)
        if not self.overlap and self._inflight is not None:
            self._complete(tr, finished)

        ev = self.blocks.eviction_count
        if ev != self._evictions_seen:
            self.stats.record_evictions(ev - self._evictions_seen)
            self._evictions_seen = ev
        return finished

    def _dispatch(self, tr) -> None:
        """Admission + scheduling + packing + block-table staging + the
        ragged launch, WITHOUT materializing results: the returned
        device arrays ride an in-flight ``_StepTicket`` (JAX async
        dispatch — nothing in this path forces a host sync on them).
        ``_complete`` blocks on the ticket and commits."""
        plan = self.fault_plan
        if plan is not None:
            # fault seams fire BEFORE any scheduler mutation, so a crash
            # leaves queues and pool in the consistent between-steps
            # state recovery replays from.  advance() here keys the plan
            # step on DISPATCH order, which equals completion order (the
            # depth-1 pipeline completes ticket N before dispatching
            # N+1), so a schedule means the same thing overlap on or off.
            plan.advance()
            if plan.take_pool_entry():
                self.stats.record_fault("pool")
            slow = plan.take_slow()
            if slow > 0.0:
                self.stats.record_fault("slow")
                time.sleep(slow)
            if plan.take_crash():
                self.stats.record_fault("crash")
                raise InjectedFault(
                    f"injected step crash at plan step {plan.step}")

        if self.pressure is not None:
            # pages the prestage reserved for rows still alive are
            # credited back: at this point in the SYNC engine's step
            # they would not have been taken yet, so the free-page
            # signal (and every tier decision derived from it) sees the
            # identical per-step timeline
            prev_tier = self.pressure.state
            self.pressure.update(
                self.blocks,
                spec_reserved=sum(self._spec_pages.values()))
            self.stats.set_degradation_state(self.pressure.state)
            if tr is not None and self.pressure.state != prev_tier:
                tr.instant("pressure.tier", track=self._trace_track,
                           args={"from": prev_tier,
                                 "to": self.pressure.state,
                                 "name": _TIER_NAMES.get(
                                     self.pressure.state,
                                     str(self.pressure.state))})
            if self.pressure.evict_now:
                n = self.blocks.evict_parked(self.pressure.evict_batch)
                if n:
                    self.stats.record_parked_evictions(n)

        if tr is not None:
            t_d = tr.now()
            t = tr.now()
        admitted = self._admit()
        if admitted:
            self.stats.record_admission(len(admitted))
        if tr is not None:
            tr.complete("engine.admit", t, track=self._trace_track,
                        args={"admitted": len(admitted),
                              "running": len(self._running),
                              "waiting": len(self._waiting)})
        self.peak_resident_seqs = max(self.peak_resident_seqs,
                                      len(self._running))
        self.stats.record_prefill_queue(
            sum(1 for r in self._running if r.cached < len(r.tokens))
            + len(self._waiting))

        if tr is not None:
            t = tr.now()
        chunks = self._schedule_prefill_chunks()

        # decode-ready set (chunk owners are still mid-prefill, so the
        # row classes are disjoint by construction)
        batch = [r for r in self._running if self._decode_ready(r)]
        # speculative sequences pack a [last_token, drafts...] window;
        # everything else packs a single decode token in the same launch
        spec, batch = self._split_spec(batch)
        spec, demoted = self._reserve_verify_pages(spec)
        batch.extend(demoted)
        # verify reservation/CoW may have preempted plain-decode members
        batch = [r for r in batch
                 if r in self._running and self._decode_ready(r)]
        batch = self._reserve_decode_pages(batch)
        # every reservation above can preempt a chunk owner or an
        # already-reserved row: re-filter each class against the
        # surviving running set before packing the launch
        chunks = [(r, n) for r, n in chunks if r in self._running]
        spec = [(r, d, q) for r, d, q in spec if r in self._running]
        batch = [r for r in batch if r in self._running]
        batch.sort(key=lambda r: r.slot)
        if tr is not None:
            tr.complete("engine.schedule", t, track=self._trace_track,
                        args={"chunks": len(chunks), "spec": len(spec),
                              "decode": len(batch)})

        if chunks or spec or batch:
            t0 = time.perf_counter()
            launched = False
            if (self.decode_window > 1 and not chunks and not spec
                    and self._window_eligible(batch)):
                launched = self._dispatch_window(batch, tr, t0)
            if not launched:
                with RecordEvent("llm_engine.ragged_step"):
                    sampled, logits, fin, spec_slices, chunk_slots, \
                        batch_slots = self._run_ragged(chunks, spec,
                                                       batch)
                now = time.perf_counter()
                self._inflight = _StepTicket(
                    chunks=chunks, spec=spec, batch=batch,
                    sampled=sampled, logits=logits, fin=fin,
                    spec_slices=spec_slices, chunk_slots=chunk_slots,
                    batch_slots=batch_slots, dispatch_s=now - t0,
                    t_launch=now,
                    launch_ns=tr.now() if tr is not None else 0,
                    inflight=self.overlap)
        # prestage page credit expires: every reserved page is now
        # either owned by a row this dispatch packed (its ensure() saw
        # the page already in place) or was freed with its retired row
        self._spec_pages.clear()
        if tr is not None:
            tr.complete("engine.dispatch", t_d, track=self._trace_track,
                        args={"chunks": len(chunks), "spec": len(spec),
                              "decode": len(batch),
                              "launched": self._inflight is not None})

    def _complete(self, tr, finished: list, drop_rid=None) -> None:
        """Block on the in-flight ticket and commit it: materialize the
        sampled tokens / finiteness flags / verify logits, run the NaN
        seam over the live rows, split the step timing into its
        dispatch/block halves, and apply + retire.

        ``drop_rid`` (abort-while-in-flight) discards that request's
        packed rows unapplied: no token commit, no retirement, leaving
        the request holding exactly the tokens the aborting caller
        could observe."""
        ticket = self._inflight
        self._inflight = None
        plan = self.fault_plan
        if plan is not None and ticket.inflight:
            # completion-order seams: fire while the ticket is genuinely
            # in flight (overlap on), between launch and materialize —
            # the window a real device fault or host stall would hit
            slow = plan.take_inflight_slow()
            if slow > 0.0:
                self.stats.record_fault("inflight_slow")
                time.sleep(slow)
            if plan.take_inflight_crash():
                self.stats.record_fault("inflight_crash")
                raise InjectedFault(
                    f"injected in-flight crash at plan step {plan.step}")
        if tr is not None:
            t_c = tr.now()
            t = t_c
        t0 = time.perf_counter()
        sampled = np.asarray(ticket.sampled)
        ok = np.asarray(ticket.fin)
        logits = np.asarray(ticket.logits) if ticket.spec else None
        block_s = time.perf_counter() - t0
        # ONE host round-trip per completion, whether the launch carried
        # a single step or a whole K-token decode window — the ratio of
        # this counter to emitted tokens is the win the window buys
        self.stats.record_round_trip()
        if tr is not None:
            tr.complete("engine.block_on_result", t,
                        track=self._trace_track)
            if ticket.launch_ns and ticket.inflight:
                # X event spanning launch -> materialized: the window
                # host work can hide inside (step_timeline.py intersects
                # host-phase spans with these to report overlap ACHIEVED).
                # Synchronous tickets (overlap off, or the drain path)
                # emit no window: nothing host ran while they flew.
                tr.complete("engine.device_inflight", ticket.launch_ns,
                            track=self._trace_track,
                            args={"rows": len(ticket.chunks)
                                  + len(ticket.spec)
                                  + len(ticket.batch)})
        if ticket.window:
            # window outputs are [K, B]: the NaN seam corrupts one live
            # row's FIRST iteration (the device kept looping; the drain
            # quarantines at the poisoned step and drops the rest of
            # that row's column)
            ok0 = self._inject_nan(ok[0], list(ticket.batch_slots))
            if ok0 is not ok[0]:
                ok = np.array(ok)
                ok[0] = ok0
        else:
            ok = self._inject_nan(ok, ticket.chunk_slots
                                  + ticket.batch_slots
                                  + [o for o, _ in ticket.spec_slices])
        chunks, spec, batch = ticket.chunks, ticket.spec, ticket.batch
        chunk_slots = ticket.chunk_slots
        batch_slots = ticket.batch_slots
        spec_slices = ticket.spec_slices
        if drop_rid is not None:
            kc = [i for i, (r, _) in enumerate(chunks) if r.rid != drop_rid]
            chunks = [chunks[i] for i in kc]
            chunk_slots = [chunk_slots[i] for i in kc]
            ks = [i for i, (r, _, _) in enumerate(spec)
                  if r.rid != drop_rid]
            spec = [spec[i] for i in ks]
            spec_slices = [spec_slices[i] for i in ks]
            kb = [i for i, r in enumerate(batch) if r.rid != drop_rid]
            batch = [batch[i] for i in kb]
            batch_slots = [batch_slots[i] for i in kb]
        spec_ok = [bool(ok[o:o + n].all())
                   for o, n in spec_slices]
        spec_logits = None
        if spec:
            spec_logits = [logits[o:o + n]
                           for o, n in spec_slices]
        # dur is the engine's ACTIVE time on this launch (host packing +
        # the residual block); the device time hidden under prestage and
        # the inter-call gap is exactly what the overlap bought
        dur = ticket.dispatch_s + block_s
        self.stats.record_step(dur, dispatch_s=ticket.dispatch_s,
                               block_s=block_s)
        if tr is not None:
            t = tr.now()
        if ticket.window:
            self._apply_window(batch, batch_slots, sampled, ok, dur,
                               finished, ticket.window)
        else:
            self._apply_ragged(chunks, spec, batch, sampled, ok, spec_ok,
                               spec_logits, chunk_slots, batch_slots,
                               dur, finished)
        if tr is not None:
            tr.complete("engine.sample_commit", t,
                        track=self._trace_track,
                        args={"finished": len(finished)})
            tr.complete("engine.complete", t_c, track=self._trace_track,
                        args={"finished": len(finished)})

    def _prestage(self, tr) -> None:
        """Speculatively stage the NEXT dispatch's pure-decode pack
        while the in-flight ticket runs on-device.

        A surviving decode row's next position is known before the
        ticket's sampled token is: it packs exactly kv_len+1 next step.
        So page reservation (``ensure``), the block-table rows, the
        kv-length column, and the per-row sampling keys all pre-stage
        into the idle decode buffer; only the token-id column (and the
        repetition-penalty masks) are patched in at dispatch.  The
        prestage NEVER preempts — a short pool abandons it, and the
        partial row-local writes are idempotent (the normal incremental
        path redoes them).  Rollback rides the existing machinery: a row
        the completion retires/quarantines (or a later preemption)
        returns its speculatively reserved page with the rest of its
        table through free()/release(), and the layout-signature check
        at dispatch discards the stale pack."""
        if not self.overlap:
            return
        ticket = self._inflight
        if ticket.window:
            return                      # the window advanced K positions;
                                        # its drain re-schedules from live
                                        # request state, not a prestage
        if ticket.chunks or ticket.spec or not ticket.batch:
            return                      # only pure-decode launches
        if self._waiting:
            return                      # next step admits -> mixed pack
        for r in self._running:
            if r.cached < len(r.tokens):
                return                  # mid-prefill row -> mixed pack
        if self.drafter is not None:
            for r in ticket.batch:
                if not r.spec_disabled and r.spec_k > 0:
                    return              # next step may pack verify rows
        batch = ticket.batch            # already slot-sorted at dispatch
        self._prestaged = None
        if tr is not None:
            t_p = tr.now()
        # reserve each row's next write: pre-apply cached+2 is exactly
        # the post-apply cached+1 the dispatch's ensure() will ask for,
        # so that ensure becomes a no-op.  Newly taken pages are
        # tracked per rid so the pressure signal credits them back
        # until this dispatch (or a retirement) owns them.
        abandoned = False
        for req in batch:
            before = self.blocks.num_free
            try:
                if not self.blocks.ensure(req.rid, req.cached + 2):
                    abandoned = True
            except BlockPoolExhausted:
                abandoned = True
            if abandoned:
                break
            took = before - self.blocks.num_free
            if took > 0:
                self._spec_pages[req.rid] = \
                    self._spec_pages.get(req.rid, 0) + took
        if abandoned:
            if tr is not None:
                tr.complete("engine.prestage", t_p,
                            track=self._trace_track,
                            args={"abandoned": "pool"})
            return
        bi = 1 - self._d_cur            # the buffer NOT in flight
        buf = self._dbufs[bi]
        samp = buf.samp
        n = len(batch)
        layout = tuple(r.rid for r in batch)
        if layout != buf.layout:
            buf.layout = layout
            buf.bt_ver.clear()
            buf.bt[:] = NULL_BLOCK
            buf.kvl[:] = 0
            buf.cu[:n + 1] = np.arange(n + 1)
            buf.cu[n + 1:] = n
            samp["temps"][:] = 0.0
            samp["top_k"][:] = 0
            samp["top_p"][:] = 1.0
            samp["penalty"][:] = 1.0
            samp["seen"][:] = False
            for s, req in enumerate(batch):
                samp["temps"][s] = req.temperature
                samp["top_k"][s] = req.top_k
                samp["top_p"][s] = req.top_p
                samp["penalty"][s] = req.repetition_penalty
        if tr is not None:
            t = tr.now()
        for s, req in enumerate(batch):
            buf.kvl[s] = req.cached + 2      # post-apply cached+1
            if req.temperature > 0.0:
                # the key for the NEXT position: len(generated) will
                # have advanced by one when this buffer launches
                samp["keys"][s] = self._req_key(req, ahead=1)
        if tr is not None:
            tr.complete("engine.pack", t, track=self._trace_track,
                        args={"rows": n, "prestage": True})
            t = tr.now()
        for s, req in enumerate(batch):
            ver = self.blocks.table_version(req.rid)
            if buf.bt_ver.get(req.rid) != ver:
                buf.bt[s] = self.blocks.padded_table(req.rid, self.nblk)
                buf.bt_ver[req.rid] = ver
        if tr is not None:
            tr.complete("engine.block_table_stage", t,
                        track=self._trace_track,
                        args={"rows": n, "prestage": True})
        self._prestaged = (bi, layout)
        if tr is not None:
            tr.complete("engine.prestage", t_p, track=self._trace_track,
                        args={"rows": n})

    def _invalidate_bt(self, rid: int) -> None:
        """Drop both decode buffers' staged block-table rows for rid.
        Called whenever a rid's staged table can go stale without a
        version bump: admission re-acquires reset the version counter,
        and preemption frees the table outright."""
        for buf in self._dbufs:
            buf.bt_ver.pop(rid, None)

    def _break_decode_layout(self) -> None:
        """Invalidate the decode fast path entirely: any mixed launch
        (and post-verify truncate) rewrites tables and row order, so
        both buffers full-restage at their next pure-decode launch and
        any pre-staged pack is discarded."""
        for buf in self._dbufs:
            buf.layout = ()
            buf.bt_ver.clear()
        self._prestaged = None

    def _apply_ragged(self, chunks, spec, batch, sampled, ok, spec_ok,
                      spec_logits, chunk_slots, batch_slots, dur,
                      finished):
        """Advance every packed row from the launch's outputs: chunk rows
        commit their prefix (emitting a first token when the prompt
        completes), spec rows run host-side draft acceptance, decode rows
        emit one token.  A row whose logits came back non-finite is
        QUARANTINED before any of its state commits — the offending
        sequence retires with finish_reason="numerical_error" and its
        pages leave through the abort-hardened release path (never the
        cache-registering free path), so one poison row cannot spread
        through the prefix cache or take down its batchmates.  The
        launch duration splits across the stats channels pro-rata by
        packed tokens."""
        chunk_tokens = sum(n for _, n in chunks)
        spec_tokens = sum(len(d) + 1 for _, d, _ in spec)
        total = max(chunk_tokens + spec_tokens + len(batch), 1)
        occ = len(self._running) / self.max_num_seqs
        tr = self.tracer

        done = 0
        for (req, n), s in zip(chunks, chunk_slots):
            if not ok[s]:
                self._quarantine(req, finished)
                continue
            req.cached += n
            if self.enable_prefix_caching:
                self.blocks.commit_prefill(req.rid, n)
            if tr is not None:
                tr.instant("request.prefill_chunk",
                           track=self._trace_track,
                           args={"rid": req.rid, "tokens": n,
                                 "done": req.cached >= len(req.tokens)})
            fl = self.flight
            if fl is not None:
                fl.prefill_chunk(req.rid, n)
            if req.cached == len(req.tokens):
                done += 1
                tok = int(sampled[s])
                req.generated.append(tok)
                if req.seen is not None:
                    req.seen[tok] = True
                if len(req.generated) == 1:
                    ttft = time.perf_counter() - req.t_arrival
                    self.stats.record_ttft(ttft)
                    if fl is not None:
                        fl.first_token(req.rid, ttft)
                    if tr is not None:
                        tr.instant("request.first_token",
                                   track=self._trace_track,
                                   args={"rid": req.rid})
                self._notify_tokens(req, (tok,))
                self._maybe_retire(req, finished)
        if chunks:
            self.stats.record_prefill(dur * chunk_tokens / total,
                                      chunk_tokens, done)

        if spec:
            n_emitted = 0
            for i, ((req, drafts, qd), lg) in enumerate(
                    zip(spec, spec_logits)):
                if not spec_ok[i]:
                    self._quarantine(req, finished)
                    continue
                n_emitted += self._apply_spec_result(req, drafts, qd, lg,
                                                     finished)
            self.stats.record_verify(dur * spec_tokens / total,
                                     n_emitted, occ)

        for req, s in zip(batch, batch_slots):
            if not ok[s]:
                self._quarantine(req, finished)
                continue
            if self.enable_prefix_caching:
                self.blocks.commit_decode_token(req.rid,
                                                req.generated[-1])
            req.cached += 1
            tok = int(sampled[s])
            req.generated.append(tok)
            if req.seen is not None:
                req.seen[tok] = True
            self._notify_tokens(req, (tok,))
            self._maybe_retire(req, finished)
        if batch:
            self.stats.record_decode(dur * len(batch) / total,
                                     len(batch), occ)

    # ------------------------------------------------------------------
    # device-resident decode window (decode_window > 1)
    # ------------------------------------------------------------------

    def _window_eligible(self, batch: list) -> bool:
        """True when this step's pack may run as a K-step device window:
        a STEADY pure-decode state — every runner decode-ready, nobody
        waiting for a slot (a window would delay their admission by up
        to K steps), and no row about to carry a verify window.  The
        caller already established there are no chunk/spec rows this
        step; the per-step path remains the universal fallback."""
        if not batch or self._waiting:
            return False
        if len(batch) != len(self._running):
            return False                # a runner is still mid-prefill
        if self.drafter is not None:
            for r in batch:
                if not r.spec_disabled and r.spec_k > 0:
                    return False        # next rounds pack verify rows
        return True

    def _reserve_window_pages(self, batch: list, k: int):
        """Pre-reserve each row's k tokens of page slack before the
        window launches (clamped to the row's remaining generation
        budget — a row the active-mask will freeze after m < k tokens
        writes only m positions).  All-or-nothing AT THIS k: a pool
        that cannot cover the whole window rolls every grow back and
        returns None — the dispatcher then retries at a smaller k'
        before surrendering to K=1; it NEVER preempts for a window.

        No copy-on-write resolution is needed here: the per-step
        reservation that already ran this dispatch privatized the page
        holding the first write position, and every page boundary the
        window crosses past it lands on a freshly allocated (private)
        page."""
        rows = []
        for req in batch:
            m = min(k, req.max_new_tokens - len(req.generated))
            rows.append((req.rid, req.cached + m))
        return self.blocks.reserve_window(rows)

    def _dispatch_window(self, batch: list, tr, t0: float) -> bool:
        """Reserve, pack, and launch one K-step decode window over
        ``batch`` (slot-sorted, first-write pages already ensured).
        Returns True with the window ticket in flight, or False when
        the pool could not cover even a 2-token window (the caller runs
        the per-step path for this step).  Between those extremes the
        window ADAPTS: when K tokens of slack don't fit, the dispatch
        retries the reservation at K-1, K-2, ... and runs the largest
        feasible K' device-resident — the per-row generation budgets
        handed to the launch freeze every row after K' tokens, so the
        compiled driver (still built at static K) exits the while_loop
        early instead of the host surrendering the whole round-trip
        amortization."""
        K = self.decode_window
        kp = 0
        for k_try in range(K, 1, -1):
            if self._reserve_window_pages(batch, k_try) is not None:
                kp = k_try
                break
        if kp == 0:
            self.stats.record_window_fallback()
            if tr is not None:
                tr.instant("engine.window_fallback",
                           track=self._trace_track,
                           args={"rows": len(batch), "k": K})
            return False
        if kp < K:
            self.stats.record_window_shrink()
            if tr is not None:
                tr.instant("engine.window_shrink",
                           track=self._trace_track,
                           args={"rows": len(batch), "k": K, "kp": kp})
        B = self.max_num_seqs
        n = len(batch)
        toks = np.zeros((B,), np.int32)
        kvl = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        gen = np.zeros((B,), np.int32)
        budgets = np.zeros((B,), np.int32)
        eos_ids = np.full((B,), -1, np.int32)   # no token id is < 0, so
        base_keys = np.zeros((B, 2), np.uint32)  # -1 == "no eos" rows
        bt = np.full((B + 1, self.nblk), NULL_BLOCK, np.int32)
        samp = make_samp(B, self.config.vocab_size)
        if tr is not None:
            t = tr.now()
        for s, req in enumerate(batch):
            toks[s] = req.generated[-1]
            kvl[s] = req.cached + 1
            active[s] = True
            gen[s] = len(req.generated)
            # the K'-shrunk budget: the device active-mask freezes the
            # row after exactly kp tokens (kp == K leaves the row's own
            # generation budget in charge, same as before)
            budgets[s] = min(req.max_new_tokens,
                             len(req.generated) + kp)
            if req.eos_token_id is not None:
                eos_ids[s] = int(req.eos_token_id)
            self._fill_samp(samp, s, req)
            if req.temperature > 0.0:
                # the loop body re-derives fold_in(base, generated)
                # per iteration — the identical threefry derivation
                # _req_key performs host-side at K=1
                base_keys[s] = np.asarray(
                    jax.random.PRNGKey(req.seed), np.uint32)
        if tr is not None:
            tr.complete("engine.pack", t, track=self._trace_track,
                        args={"rows": n, "window": kp})
            t = tr.now()
        for s, req in enumerate(batch):
            bt[s] = self.blocks.padded_table(req.rid, self.nblk)
        if tr is not None:
            tr.complete("engine.block_table_stage", t,
                        track=self._trace_track,
                        args={"rows": n, "window": kp})
        # the window grows tables past anything the per-step buffers
        # staged; force full restages at the next per-step launch
        self._break_decode_layout()
        if tr is not None:
            t = tr.now()
        with RecordEvent("llm_engine.window_step"):
            toks_out, fin_out = self._launch_window(
                toks, kvl, active, gen, budgets, eos_ids, base_keys,
                bt, samp)
        if tr is not None:
            tr.complete("engine.device_launch", t,
                        track=self._trace_track,
                        args={"rows": n, "window": kp})
        now = time.perf_counter()
        self._inflight = _StepTicket(
            chunks=[], spec=[], batch=list(batch), sampled=toks_out,
            logits=None, fin=fin_out, spec_slices=[], chunk_slots=[],
            batch_slots=list(range(n)), dispatch_s=now - t0,
            t_launch=now, launch_ns=tr.now() if tr is not None else 0,
            inflight=self.overlap, window=kp)
        return True

    def _apply_window(self, batch, batch_slots, sampled, ok, dur,
                      finished, window):
        """Drain one completed K-step window: ONE materialized [K, B]
        token (and finiteness) grid commits as up to K per-token steps
        per row, in iteration-major order — the exact per-token sequence
        (cache commit of the previous token, clock advance, append,
        penalty mask, stream callback, retire check) the per-step path
        runs, so prefix-cache content, retirement timing, and callbacks
        are indistinguishable from K=1.  The host replays the device's
        freeze logic: a row leaves the walk when it retires (eos/length
        — the same predicates the active-mask evaluated on device) or
        quarantines on a non-finite iteration; its later columns are the
        frozen filler values the loop carried and are never committed.
        ``window`` is the ticket's launched K' — a shrunk window's grid
        still arrives [decode_window, B] wide (the compiled driver's
        static K), so the drain MUST stop at K' or the budget-frozen
        rows would commit their repeated filler columns."""
        K = min(int(sampled.shape[0]), int(window))
        occ = len(self._running) / self.max_num_seqs
        alive = {req.rid for req in batch}
        committed = 0
        iters = 0
        for i in range(K):
            if not alive:
                break
            iters += 1
            for req, s in zip(batch, batch_slots):
                if req.rid not in alive:
                    continue
                if not ok[i, s]:
                    alive.discard(req.rid)
                    self._quarantine(req, finished)
                    continue
                if self.enable_prefix_caching:
                    self.blocks.commit_decode_token(req.rid,
                                                    req.generated[-1])
                req.cached += 1
                tok = int(sampled[i, s])
                req.generated.append(tok)
                if req.seen is not None:
                    req.seen[tok] = True
                committed += 1
                self._notify_tokens(req, (tok,))
                self._maybe_retire(req, finished)
                if req not in self._running:
                    alive.discard(req.rid)
        self.pad_stats["real"] += committed
        self.pad_stats["padded"] += iters * self.max_num_seqs
        self.pad_stats["legacy_padded"] += iters * self.max_num_seqs
        if committed:
            self.stats.record_decode(dur, committed, occ, rounds=iters)
        self.stats.set_decode_window(K)

    def _quarantine(self, req, finished: list) -> None:
        """Retire one sequence whose step logits came back non-finite.

        The sequence's pages leave through ``release`` (decref-only:
        pages shared with healthy neighbours survive, and the possibly-
        corrupt unshared tail is dropped WITHOUT registering in the
        prefix cache — corrupt K/V must never become a future cache
        hit).  Clients see finish_reason="numerical_error"; the rest of
        the batch is untouched."""
        self.blocks.release(req.rid)
        self._spec_pages.pop(req.rid, None)
        self._running.remove(req)
        self._release_slot(req)
        if self.drafter is not None:
            self.drafter.release(req.rid)
        out = RequestOutput(rid=req.rid, prompt=list(req.prompt),
                            generated=list(req.generated),
                            finish_reason="numerical_error")
        if self.retain_outputs:
            self._finished[req.rid] = out
        finished.append(out)
        self.stats.record_quarantine()
        self.stats.record_abort("numerical_error")
        if self.stats.windows is not None:
            self.stats.record_finish_quality(False)
            self.stats.record_request_latency(
                time.perf_counter() - req.t_arrival)
        fl = self.flight
        if fl is not None:
            fl.finished(req.rid, reason="numerical_error",
                        generated=len(req.generated),
                        tier=self._tier(), quarantined=True)
        tr = self.tracer
        if tr is not None:
            tr.instant("engine.quarantine", track=self._trace_track,
                       args={"rid": req.rid})
            tr.async_end("req", f"{self._trace_track}:{req.rid}",
                         args={"finish_reason": "numerical_error"})
        if req.on_finish is not None:
            req.on_finish(out)

    # ------------------------------------------------------------------
    # hierarchical KV tier (host-DRAM spill pool, inference/kv_tier.py)
    # ------------------------------------------------------------------

    def prefetch_hint(self, hashes) -> None:
        """Pre-stage a returning user's spilled pages: queue the prefix
        chain hashes of a prompt about to be submitted so the next
        step-boundary drain restores them before the prefill is packed.
        THREAD-SAFE (the tier's hint deque is locked) — the one engine
        entry point the frontend router may call off-thread.  No-op
        without a tier."""
        tier = self.kv_tier
        if tier is not None:
            tier.hint(hashes)

    def _drain_kv_tier(self, tr) -> None:
        """Step-boundary tier drain — the ONLY place spill/restore bytes
        cross the HBM/host boundary (graft-lint's host-copy-in-step-path
        keeps it out of the dispatch/prestage/complete hot phases).
        Spill: pages evict_parked quarantined copy out to the host pool
        and their HBM blocks free.  Restore: router prefetch hints, then
        the waiting queue's prompt chains, pull tier-resident pages back
        into free HBM blocks, re-registered content-addressed — from
        admission's point of view they are ordinary prefix-cache
        content.  Everything is eager array ops on materialized pools:
        ``compile_counts`` is untouched and restored bytes are the exact
        spilled bytes (the A/B byte-identity pin)."""
        tier = self.kv_tier
        int8 = self.kv_dtype == "int8"
        pending = self.blocks.take_spill_pending()
        if pending:
            blks = np.array([b for b, _ in pending], np.int32)
            kc = np.asarray(self._kc[:, blks])
            vc = np.asarray(self._vc[:, blks])
            if int8:
                ks = np.asarray(self._ks[:, blks])
                vs = np.asarray(self._vs[:, blks])
            stored = 0
            for i, (blk, hashes) in enumerate(pending):
                arrays = {"kc": kc[:, i], "vc": vc[:, i]}
                if int8:
                    arrays["ks"] = ks[:, i]
                    arrays["vs"] = vs[:, i]
                if tier.insert(hashes, arrays):
                    stored += 1
                # these hashes left HBM: a past restore no longer backs
                # a future admission hit
                self._staged_hashes.difference_update(hashes)
            self.stats.record_kv_spill(len(pending), stored)
            if tr is not None:
                tr.instant("kv_tier.spill", track=self._trace_track,
                           args={"pages": len(pending), "stored": stored})

        restored = []                     # [(block, tier entry)]
        for h in self._tier_wanted_hashes(tier):
            if not self.blocks.num_free:
                break                     # opportunistic: never evict
            if self.blocks.has_hash(h):
                continue                  # covered earlier this drain
            entry = tier.take(h)
            if entry is None:
                continue
            blk = self.blocks.adopt_restored(entry["hashes"])
            if blk is None:               # unreachable given the guards
                tier.insert(entry["hashes"], entry["arrays"])
                break
            restored.append((blk, entry))
            self._staged_hashes.update(entry["hashes"])
        if restored:
            blks = np.array([b for b, _ in restored], np.int32)
            kc = np.stack([e["arrays"]["kc"] for _, e in restored], axis=1)
            vc = np.stack([e["arrays"]["vc"] for _, e in restored], axis=1)
            self._kc = self._kc.at[:, blks].set(kc)
            self._vc = self._vc.at[:, blks].set(vc)
            if int8:
                # scale rows travel with their pages; restored blocks are
                # NOT fresh (adopt_restored discarded them), so the
                # launch's fresh-mask reset cannot zero these rows
                ks = np.stack([e["arrays"]["ks"] for _, e in restored],
                              axis=1)
                vs = np.stack([e["arrays"]["vs"] for _, e in restored],
                              axis=1)
                self._ks = self._ks.at[:, blks].set(ks)
                self._vs = self._vs.at[:, blks].set(vs)
            if self.tp > 1:
                # keep the pools' mesh layout exactly as constructed so
                # the compiled step sees identically-sharded donations
                self._kc = jax.device_put(self._kc, self._kv_sharding)
                self._vc = jax.device_put(self._vc, self._kv_sharding)
                if int8:
                    self._ks = jax.device_put(self._ks, self._kv_sharding)
                    self._vs = jax.device_put(self._vs, self._kv_sharding)
            self.stats.record_kv_restore(len(restored))
            if tr is not None:
                tr.instant("kv_tier.restore", track=self._trace_track,
                           args={"pages": len(restored)})
        self.stats.set_spill_tier(tier.stats())

    def _tier_wanted_hashes(self, tier) -> list:
        """Chain hashes worth restoring this drain, in chain order,
        deduped: router prefetch hints first (pre-staging a returning
        user), then the waiting queue's front prompts (admission's tier
        consult on a prefix-cache miss, one-shot per waiting episode).
        Each chain walks while its prefix stays servable — HBM-resident
        hashes skip, tier-resident ones restore, and the walk stops at
        the first hash neither holds (a contiguous prefix match can
        never reach later pages)."""
        chains = tier.drain_hints()
        n = 0
        for req in self._waiting:
            if n >= self.max_num_seqs:
                break
            n += 1
            if req.tier_checked == tier.gen:
                continue          # nothing new spilled since last consult
            req.tier_checked = tier.gen
            chains.append(prefix_chain_hashes(req.tokens, self.block_size))
        wanted: list = []
        seen: set = set()
        for chain in chains:
            for h in chain:
                if self.blocks.has_hash(h) or h in seen:
                    continue
                if tier.lookup(h):
                    seen.add(h)
                    wanted.append(h)
                else:
                    break
        return wanted

    def _claim_slot(self, req) -> None:
        req.slot = self._slot_used.index(False)
        self._slot_used[req.slot] = True

    def _release_slot(self, req) -> None:
        if req.slot >= 0:
            self._slot_used[req.slot] = False
            req.slot = -1

    def _admit(self) -> list:
        """Pull waiting requests into the running set while batch slots
        and pool pages allow.  With prefix caching, admission matches the
        prompt's token chain against the cache and allocates only the
        miss suffix; chunked prefill means admission is no longer gated
        on the per-step token budget."""
        if self.pressure is not None and self.pressure.admission_paused:
            return []
        admitted = []
        while self._waiting and len(self._running) < self.max_num_seqs:
            req = self._waiting[0]
            if self.enable_prefix_caching:
                hit = self.blocks.acquire(req.rid, req.tokens)
                if hit is None:
                    break
                req.cached = hit
                self.stats.record_cache_lookup(hit, len(req.tokens) - hit)
                if hit and self._staged_hashes:
                    # prefetch-hit attribution: hit pages whose chain
                    # hashes a tier restore staged (by hash, so block
                    # reuse cannot misattribute); each staged hash pays
                    # out at most once
                    used = [h for h in self.blocks.chain_hashes(req.rid)
                            if h in self._staged_hashes]
                    if used:
                        self._staged_hashes.difference_update(used)
                        self.stats.record_prefetch_hits(len(used))
            else:
                if not self.blocks.allocate(req.rid, len(req.tokens)):
                    break
                req.cached = 0
            self._waiting.popleft()
            req.arrival = self._arrival
            self._arrival += 1
            self._invalidate_bt(req.rid)
            self._claim_slot(req)
            self._running.append(req)
            admitted.append(req)
            # queue wait = arrival -> this admission (for a preempted
            # request that re-admits, arrival -> LATEST admission: the
            # whole stall was service latency)
            qw = time.perf_counter() - req.t_arrival
            self.stats.record_queue_wait(qw)
            fl = self.flight
            if fl is not None:
                fl.admitted(req.rid, queue_wait_s=qw,
                            cache_hit_tokens=req.cached,
                            tier=self._tier())
        return admitted

    def _schedule_prefill_chunks(self) -> list:
        """Pack at most max_prefill_tokens pending prompt tokens into this
        step, FCFS, resuming partially-prefilled requests first.  The
        budget rule itself is ``policy.pack_prefill_chunks`` (shared with
        the fleet simulator); the engine hangs copy-on-write resolution
        for each chunk's first write position (the only spot a chunk can
        touch a shared page) on its admit hook, so a CoW preemption skips
        the victim without consuming budget."""
        chunks: list = []

        def admit(req):
            if req not in self._running:
                return False
            if self.enable_prefix_caching:
                # may preempt req (False) or drop an earlier chunk's
                # owner from the accumulator (drop_from)
                return self._resolve_cow(req, req.cached, drop_from=chunks)
            return True

        ordered = sorted(list(self._running), key=lambda r: r.arrival)
        return pack_prefill_chunks(
            ((r, len(r.tokens) - r.cached) for r in ordered),
            self.max_prefill_tokens, admit=admit, out=chunks)

    def _resolve_cow(self, req, pos: int, drop_from: list | None = None) \
            -> bool:
        """Privatize the page holding ``pos`` if it is shared, preempting
        victims while the pool has no page for the copy.  False when req
        itself had to be preempted."""
        while True:
            try:
                cw = self.blocks.cow_if_shared(req.rid, pos)
            except BlockPoolExhausted:
                victim = self._pick_victim(exclude=req)
                if victim is None:
                    self._preempt(req)
                    return False
                self._preempt(victim)
                if drop_from is not None:
                    drop_from[:] = [c for c in drop_from
                                    if c[0] is not victim]
                continue
            if cw is not None:
                self._apply_cow(*cw)
                self.stats.record_cow()
            return True

    def _reserve_decode_pages(self, batch: list) -> list:
        """Grow each sequence's table for the token this step will write
        (plus a private copy of a still-shared tail page); preempt the
        youngest runner whenever the pool comes up short."""
        ok = []
        for req in sorted(batch, key=lambda r: r.arrival):
            if req not in self._running:   # evicted as a victim earlier
                continue
            while req is not None:
                if not self.blocks.ensure(req.rid, req.cached + 1):
                    victim = self._pick_victim(exclude=req)
                    if victim is None:
                        self._preempt(req)
                        req = None
                        break
                    self._preempt(victim)
                    ok = [r for r in ok if r is not victim]
                    continue
                if self.enable_prefix_caching:
                    if not self._resolve_cow(req, req.cached):
                        req = None
                        break
                    ok = [r for r in ok if r in self._running]
                break
            if req is not None:
                ok.append(req)
        return ok

    def _pick_victim(self, exclude):
        """Youngest-arrival running sequence other than ``exclude``."""
        cands = [r for r in self._running if r is not exclude]
        if not cands:
            return None
        return max(cands, key=lambda r: r.arrival)

    def _preempt(self, req) -> None:
        """Return req's pages and requeue it (front of the line) for
        recomputation: its next prefill covers prompt + tokens generated
        so far, which rebuilds the exact KV state — greedy decoding
        resumes token-identically.  With prefix caching the freed full
        pages park in the cache, so the recompute's admission hits the
        very pages this preemption returned and re-prefills only the
        tail."""
        self.blocks.free(req.rid)
        self._spec_pages.pop(req.rid, None)
        self._running.remove(req)
        self._release_slot(req)
        req.tokens = list(req.prompt) + list(req.generated)
        req.cached = 0
        # its freed pages may spill while it waits: re-consult the tier
        req.tier_checked = -1
        self._invalidate_bt(req.rid)
        self._waiting.appendleft(req)
        if self.drafter is not None:
            self.drafter.release(req.rid)
        self.stats.record_preemption()
        fl = self.flight
        if fl is not None:
            fl.preempted(req.rid)
        if self.tracer is not None:
            self.tracer.instant("request.preempted",
                                track=self._trace_track,
                                args={"rid": req.rid})

    def _maybe_retire(self, req, finished: list) -> None:
        eos = req.eos_token_id
        if eos is not None and req.generated[-1] == int(eos):
            reason = "eos"
        elif len(req.generated) >= req.max_new_tokens:
            reason = "length"
        else:
            return
        tr = self.tracer
        if tr is not None:
            t = tr.now()
        self.blocks.free(req.rid)
        self._spec_pages.pop(req.rid, None)
        self._running.remove(req)
        self._release_slot(req)
        out = RequestOutput(rid=req.rid, prompt=list(req.prompt),
                            generated=list(req.generated),
                            finish_reason=reason)
        if self.retain_outputs:
            self._finished[req.rid] = out
        finished.append(out)
        if self.drafter is not None:
            self.drafter.release(req.rid)
        self.stats.record_retirement()
        if self.stats.windows is not None:
            self.stats.record_finish_quality(True)
            self.stats.record_request_latency(
                time.perf_counter() - req.t_arrival)
        fl = self.flight
        if fl is not None:
            fl.finished(req.rid, reason=reason,
                        generated=len(req.generated),
                        tier=self._tier())
        if tr is not None:
            tr.complete("engine.retire", t, track=self._trace_track,
                        args={"rid": req.rid, "finish_reason": reason})
            tr.async_end("req", f"{self._trace_track}:{req.rid}",
                         args={"finish_reason": reason,
                               "generated": len(req.generated)})
        if req.on_finish is not None:
            req.on_finish(out)

    # ------------------------------------------------------------------
    # speculative decoding: propose -> verify -> accept/rollback
    # ------------------------------------------------------------------

    def _split_spec(self, batch: list):
        """Ask the drafter for up to spec_k tokens per eligible sequence.
        Sequences with no proposal (or speculation off/disabled/cut to
        zero by length limits) fall through to plain decode."""
        if self.drafter is None:
            return [], batch
        spec, plain = [], []
        cap = self.max_spec_k
        if self.pressure is not None:
            # under pressure, shrinking drafts is the cheapest lever:
            # verify windows are the largest transient page consumers
            cap = self.pressure.spec_k_cap(self.max_spec_k)
        for req in batch:
            k = 0 if req.spec_disabled else min(req.spec_k, cap)
            # the verify step writes K/V at cached..cached+k, so the
            # sequence may hold at most max_model_len tokens afterwards;
            # drafting past max_new_tokens (plus the bonus token) is waste
            k = min(k,
                    self.max_model_len - len(req.prompt) - len(req.generated),
                    req.max_new_tokens - len(req.generated) - 1)
            if k <= 0:
                plain.append(req)
                continue
            context = list(req.prompt) + list(req.generated)
            drafts, qd = self.drafter.propose(req.rid, context, k)
            if not drafts:
                plain.append(req)
                continue
            spec.append((req, [int(t) for t in drafts[:k]], qd))
        return spec, plain

    def _page_starts(self, a: int, b: int) -> list:
        """First written position in each page the write window [a, b]
        (inclusive) touches — the positions _resolve_cow must privatize."""
        bs = self.block_size
        out = [a]
        p = (a // bs + 1) * bs
        while p <= b:
            out.append(p)
            p += bs
        return out

    def _reserve_verify_pages(self, spec: list):
        """Grow each speculative sequence's table for its K+1 writes and
        privatize every shared page in the window.  The pool is never
        preempted FOR speculation: when ensure() comes up short the draft
        shrinks (k -> k-1 -> ... -> plain decode) instead.  CoW of the
        first write position is required for plain decode too, so that
        path keeps the usual victim-preemption behaviour."""
        ok, demoted = [], []
        for req, drafts, qd in spec:
            if req not in self._running:
                continue
            k = len(drafts)
            while k > 0 and not self.blocks.ensure(req.rid,
                                                   req.cached + k + 1):
                k -= 1
            if k == 0:
                demoted.append(req)
                continue
            drafts = drafts[:k]
            if self.enable_prefix_caching:
                alive = True
                for pos in self._page_starts(req.cached, req.cached + k):
                    if not self._resolve_cow(req, pos):
                        alive = False           # req itself was preempted
                        break
                ok = [it for it in ok if it[0] in self._running]
                if not alive:
                    continue
            ok.append((req, drafts, qd))
        return ok, demoted

    def _apply_spec_result(self, req, drafts, qd, lg, finished) -> int:
        """Turn one sequence's verify logits into emitted tokens: run
        rejection-sampling acceptance, commit the accepted prefix's K/V,
        truncate the rejected tail out of the page table (scrubbing its
        content hashes), and advance the request exactly as that many
        plain decode steps would have.  Returns tokens emitted."""
        from .spec_decode import verify_and_accept

        k = len(drafts)
        rng = None
        if req.temperature > 0.0:
            # keyed by (seed, position): reproducible across scheduling
            # orders and preemptions, like _req_key on the device path
            rng = np.random.Generator(np.random.Philox(
                key=[req.seed & 0xFFFFFFFF, len(req.generated)]))
        n_acc, emitted = verify_and_accept(
            lg, drafts, q_dists=qd, temperature=req.temperature,
            top_k=req.top_k, top_p=req.top_p,
            penalty=req.repetition_penalty, seen=req.seen, rng=rng)
        # cut to the generation budget, and at the first eos token
        room = req.max_new_tokens - len(req.generated)
        emitted = emitted[:room]
        if req.eos_token_id is not None:
            eos = int(req.eos_token_id)
            if eos in emitted:
                emitted = emitted[:emitted.index(eos) + 1]
        m = len(emitted)                              # >= 1: room >= 1
        # K/V validity: positions cached..cached+n_acc hold
        # [generated[-1], accepted drafts]; m <= n_acc + 1 tokens advance
        # the clock, and when the m-th is the bonus/resample its K/V is
        # written by the NEXT step (decode invariant), not this one.
        if self.enable_prefix_caching:
            for tok in [req.generated[-1]] + emitted[:m - 1]:
                self.blocks.commit_decode_token(req.rid, tok)
        req.cached += m
        # roll the speculative tail (rejected drafts + over-reserved
        # pages) back out of the table; prefix-cache hashes covering
        # rolled-back K/V are scrubbed inside truncate
        rolled = self.blocks.truncate(req.rid, req.cached)
        req.generated.extend(emitted)
        if req.seen is not None:
            req.seen[emitted] = True
        self._notify_tokens(req, emitted)
        j = m - 1 if m == n_acc + 1 else m            # emitted draft count
        if k:                                         # zero-draft rows are
            req.spec_proposed += k                    # plain decode riding
            req.spec_accepted += min(j, n_acc)        # the verify launch
            self.stats.record_spec(proposed=k, accepted=min(j, n_acc),
                                   emitted=m, rollback=k - j,
                                   pages_rolled=rolled)
            fl = self.flight
            if fl is not None:
                fl.spec_round(req.rid, min(j, n_acc), k - j)
            if (not req.spec_disabled
                    and req.spec_proposed >= self.spec_window
                    and req.spec_accepted
                    < self.spec_accept_floor * req.spec_proposed):
                req.spec_disabled = True
                self.stats.record_spec_disable()
            self.drafter.commit(
                req.rid, len(req.prompt) + len(req.generated) - (m - j))
        self._maybe_retire(req, finished)
        return m

    # ------------------------------------------------------------------
    # copy-on-write page copy (device side)
    # ------------------------------------------------------------------

    def _make_cow_fn(self):
        """(unjitted page-copy fn, intended donate_argnums) — the spec the
        analyzer sees; _apply_cow jits it (CPU drops donation: the CPU
        runtime cannot alias and would warn every call).  In int8 mode
        the copy carries the page's scale-pool rows along with its data
        — the dst page is a live replica, so BlockManager excludes it
        from the fresh-page scale reset."""
        if self.kv_dtype == "int8":
            def run(kc, vc, ks, vs, s, d):
                kc = kc.at[:, d].set(kc[:, s])
                vc = vc.at[:, d].set(vc[:, s])
                ks = ks.at[:, d].set(ks[:, s])
                vs = vs.at[:, d].set(vs[:, s])
                return kc, vc, ks, vs

            return run, (0, 1, 2, 3)

        def run(kc, vc, s, d):
            kc = kc.at[:, d].set(kc[:, s])
            vc = vc.at[:, d].set(vc[:, s])
            return kc, vc

        return run, (0, 1)

    def _apply_cow(self, src: int, dst: int) -> None:
        """Copy page src -> dst across every layer's K and V cache.  The
        copy is dispatched immediately so device program order keeps it
        ahead of any later prefill/decode write into dst."""
        if self._cow_prog is None:
            run, donate = self._make_cow_fn()
            if jax.default_backend() == "cpu":
                donate = ()
            self._cow_prog = jax.jit(run, donate_argnums=donate)
            self.compile_counts["cow"] += 1
        if self.kv_dtype == "int8":
            self._kc, self._vc, self._ks, self._vs = self._cow_prog(
                self._kc, self._vc, self._ks, self._vs,
                np.int32(src), np.int32(dst))
        else:
            self._kc, self._vc = self._cow_prog(
                self._kc, self._vc, np.int32(src), np.int32(dst))

    # ------------------------------------------------------------------
    # the compiled ragged step
    # ------------------------------------------------------------------

    def _ragged_bucket(self, n_tokens: int) -> int:
        """Flat-token bucket for a launch: pure-decode-sized launches pad
        to max_num_seqs; with a drafter, speculation-sized launches (every
        running row carrying a full draft) stop at the static logit-row
        width max_num_seqs * (max_spec_k + 1) when that sits below the
        prefill bucket — otherwise a verify round of B*(k+1) rows would
        pad all the way up to prefill_token_bucket every step; anything
        larger rounds up to a multiple of prefill_token_bucket.  The
        tiers bound the program count at 2 + (max launch size) / bucket."""
        if n_tokens <= self.max_num_seqs:
            return self.max_num_seqs
        if self._with_logits and \
                n_tokens <= self._Lq < self.prefill_token_bucket:
            return self._Lq
        tb = self.prefill_token_bucket
        return -(-n_tokens // tb) * tb

    def _get_ragged_prog(self, Tq: int):
        prog = self._ragged_progs.get(Tq)
        if prog is None:
            run, donate = self._make_ragged_fn(Tq)
            if jax.default_backend() == "cpu":
                donate = ()
            prog = jax.jit(run, donate_argnums=donate)
            self._ragged_progs[Tq] = prog
            self.compile_counts["ragged"] += 1
        return prog

    def _make_ragged_fn(self, Tq: int):
        """The one serving step program: Tq flat query tokens from up to
        max_num_seqs ragged rows.  A prefill chunk, a resumed chunk, a
        decode token, and a k-draft verify window are all rows of the
        same launch, differing only in query length — each layer writes
        the packed tokens' K/V into the paged cache at their absolute
        positions, then ragged paged attention lets every token attend
        to its own row's pages causally.  Sampled tokens come back for
        the logit rows in ``lidx``; with a drafter the raw [Lq, V]
        logits ride along for host-side draft acceptance."""
        nh, kvh, d = self._nh, self._kvh, self._hd
        bs = self.block_size
        B = self.max_num_seqs
        with_logits = self._with_logits
        eps = self.config.rms_norm_eps
        theta = self.config.rope_theta
        dt = self._act_dtype
        if self.kv_dtype == "int8":
            return self._make_ragged_fn_q8(Tq)
        # under tp the body runs on PER-SHARD shapes: a contiguous block
        # of nh/tp query heads attending over kvh/tp KV heads (GQA
        # groups never straddle shards — tp divides kvh)
        tp = self.tp
        nh, kvh = nh // tp, kvh // tp
        shard_head = self._shard_head
        mm, embed, head_logits = self._weight_ops()
        # the interpreted kernel costs a Python step per (Tq, H_kv, nblk)
        # grid cell EVERY launch — serving on CPU uses the XLA reference
        # path (term-identical math) unless a test forces the interpreter
        use_pallas = _pa.INTERPRET is True or (
            jax.default_backend() == "tpu"
            and _pa.ragged_supports(Tq, nh, kvh, d, bs, B + 1,
                                    self.nblk, dt))

        def run(params, kc, vc, toks, cu, kvl, bt, lidx, samp):
            # toks [Tq] i32, rows packed back-to-back (tail padding maps
            # to the sentinel row); cu [B+1] i32 row offsets; kvl [B] i32
            # valid KV per row AFTER this launch's writes; bt [B+1, nblk]
            # i32 (row B: the null row pads resolve to); lidx [Lq] i32
            # flat index of each logit row; samp the make_samp pytree,
            # one row per logit row.  Under tp>1 this traces per shard:
            # kc/vc and the q/k/v projections arrive head-sliced, toks..
            # samp arrive replicated.
            seg, rel = _pa.ragged_segments(cu, kvl, Tq)
            x = embed(params, toks)                           # [Tq, H]

            def body(x, inp):
                p, kcl, vcl = inp
                h = _rms_weight(x, p["ln1"], eps)
                q = mm(h, p, "wq").reshape(Tq, nh, d)
                k = mm(h, p, "wk").reshape(Tq, kvh, d)
                v = mm(h, p, "wv").reshape(Tq, kvh, d)
                q = _rope_positions(q, rel, theta)
                k = _rope_positions(k, rel, theta)
                blk = bt[seg, rel // bs]                      # [Tq]
                slot = rel % bs
                kcl = kcl.at[blk, :, slot, :].set(k.astype(kcl.dtype))
                vcl = vcl.at[blk, :, slot, :].set(v.astype(vcl.dtype))
                if use_pallas:
                    # the host packing path owns these buffers: bt is the
                    # int32 NULL_BLOCK-padded pool table ([B+1] rows, so
                    # the seg pad sentinel B is the valid null row) and
                    # seg/rel come int32 from ragged_segments — the
                    # packed entry skips the per-launch re-clip/re-cast
                    att = _pa.ragged_paged_attention_segrel_packed(
                        q, kcl, vcl, bt, seg, rel)
                else:
                    att = _pa.ragged_paged_reference_segrel(
                        q, kcl, vcl, bt, seg, rel)
                if tp > 1:
                    # tiled gather concatenates shard head blocks in
                    # mesh order — exactly the tp=1 head layout, so the
                    # replicated wo matmul is byte-identical
                    att = lax.all_gather(att, "tp", axis=1, tiled=True)
                x = x + mm(att.reshape(Tq, tp * nh * d), p, "wo")
                h2 = _rms_weight(x, p["ln2"], eps)
                a = jax.nn.silu(mm(h2, p, "gate").astype(jnp.float32)
                                ).astype(h2.dtype) * mm(h2, p, "up")
                return x + mm(a, p, "down"), (kcl, vcl)

            x, (kc, vc) = lax.scan(body, x, (params["layers"], kc, vc))
            h = _rms_weight(x, params["norm_f"], eps)
            hsel = h[lidx]                                    # [Lq, H]
            logits = head_logits(params, hsel)                # [Lq, V]
            if shard_head:
                # vocab-sliced logits -> one gather; sampling then runs
                # replicated on identical full-width rows
                logits = lax.all_gather(logits, "tp", axis=1, tiled=True)
            sampled = sample_tokens(logits, samp)
            # per-row finiteness flag: the quarantine guard retires a
            # poisoned row host-side without touching its batchmates
            # (padded rows may be legitimately non-finite; the host only
            # consults live slots)
            fin = jnp.all(jnp.isfinite(logits), axis=-1)      # [Lq]
            if with_logits:
                return sampled, fin, logits, kc, vc
            return sampled, fin, kc, vc

        # donation reuses the pool buffers in place; _get_ragged_prog
        # drops it on CPU (that runtime cannot alias and warns per call)
        return self._wrap_tp(run, 6), (1, 2)

    def _make_ragged_fn_q8(self, Tq: int):
        """Int8-page variant of the one serving step program: identical
        row semantics, but each layer QUANTIZES its packed tokens' K/V
        at commit time and attention dequantizes at read time.

        Quantize-at-commit, per layer, per launch:
        1. zero the scale rows of ``fresh`` pages (pages BlockManager
           handed out since the last launch: their old content AND old
           scales are dead; CoW destinations are excluded — the CoW
           program copied their scale rows with their data);
        2. scatter-max each touched page's scale with the incoming
           tokens' per-head amax/127 (scales only grow while a page is
           live, so previously committed int8 values never overflow);
        3. re-encode the touched pages' existing int8 content from the
           old scale to the grown scale (one extra rounding per growth
           event — the accepted precision cost of page-granular scales);
        4. quantize the new tokens at the settled scale and scatter them
           into their slots.
        Duplicate page indices across tokens are safe throughout: the
        scatter-max makes every duplicate observe the same settled
        scale, so duplicate re-encodes write identical bytes.
        """
        nh, kvh, d = self._nh, self._kvh, self._hd
        bs = self.block_size
        B = self.max_num_seqs
        with_logits = self._with_logits
        eps = self.config.rms_norm_eps
        theta = self.config.rope_theta
        dt = self._act_dtype
        # per-shard head counts under tp (see _make_ragged_fn): the
        # scale pools slice along the same H_kv axis as the page pools,
        # so quantize-at-commit stays a purely per-head-local transform
        tp = self.tp
        nh, kvh = nh // tp, kvh // tp
        shard_head = self._shard_head
        mm, embed, head_logits = self._weight_ops()
        use_pallas = _pa.INTERPRET is True or (
            jax.default_backend() == "tpu"
            and _pa.ragged_quant_supports(Tq, nh, kvh, d, bs, B + 1,
                                          self.nblk, dt))

        def run(params, kc, vc, ks, vs, fresh, toks, cu, kvl, bt, lidx,
                samp):
            # args as the float step, plus: ks/vs [L, num_blocks, H_kv]
            # f32 scale pools (donated with the page pools) and fresh
            # [num_blocks] bool (pages whose scales reset this launch)
            seg, rel = _pa.ragged_segments(cu, kvl, Tq)
            x = embed(params, toks)                           # [Tq, H]

            def body(x, inp):
                p, kcl, vcl, ksl, vsl = inp
                h = _rms_weight(x, p["ln1"], eps)
                q = mm(h, p, "wq").reshape(Tq, nh, d)
                k = mm(h, p, "wk").reshape(Tq, kvh, d)
                v = mm(h, p, "wv").reshape(Tq, kvh, d)
                q = _rope_positions(q, rel, theta)
                k = _rope_positions(k, rel, theta)
                blk = bt[seg, rel // bs]                      # [Tq]
                slot = rel % bs
                kf = k.astype(jnp.float32)
                vf = v.astype(jnp.float32)
                ksl = jnp.where(fresh[:, None], 0.0, ksl)
                vsl = jnp.where(fresh[:, None], 0.0, vsl)
                ks_old = ksl[blk]                             # [Tq, kvh]
                vs_old = vsl[blk]
                ksl = ksl.at[blk].max(jnp.max(jnp.abs(kf), axis=-1)
                                      / 127.0)
                vsl = vsl.at[blk].max(jnp.max(jnp.abs(vf), axis=-1)
                                      / 127.0)
                ks_new = ksl[blk]
                vs_new = vsl[blk]
                rk = jnp.where(ks_new > 0.0,
                               ks_old / jnp.maximum(ks_new, 1e-30), 0.0)
                rv = jnp.where(vs_new > 0.0,
                               vs_old / jnp.maximum(vs_new, 1e-30), 0.0)
                kp = jnp.round(kcl[blk].astype(jnp.float32)
                               * rk[:, :, None, None])
                vp = jnp.round(vcl[blk].astype(jnp.float32)
                               * rv[:, :, None, None])
                kcl = kcl.at[blk].set(
                    jnp.clip(kp, -127, 127).astype(jnp.int8))
                vcl = vcl.at[blk].set(
                    jnp.clip(vp, -127, 127).astype(jnp.int8))
                kq = jnp.round(kf / jnp.maximum(ks_new, 1e-30)[:, :, None])
                vq = jnp.round(vf / jnp.maximum(vs_new, 1e-30)[:, :, None])
                kcl = kcl.at[blk, :, slot, :].set(
                    jnp.clip(kq, -127, 127).astype(jnp.int8))
                vcl = vcl.at[blk, :, slot, :].set(
                    jnp.clip(vq, -127, 127).astype(jnp.int8))
                if use_pallas:
                    # packed-entry invariant as in the float step; the
                    # scale pools are born f32 on the host
                    att = _pa.ragged_paged_attention_quant_segrel_packed(
                        q, kcl, vcl, ksl, vsl, bt, seg, rel)
                else:
                    att = _pa.ragged_paged_reference_quant_segrel(
                        q, kcl, vcl, ksl, vsl, bt, seg, rel)
                att = att.astype(x.dtype)
                if tp > 1:
                    att = lax.all_gather(att, "tp", axis=1, tiled=True)
                x = x + mm(att.reshape(Tq, tp * nh * d), p, "wo")
                h2 = _rms_weight(x, p["ln2"], eps)
                a = jax.nn.silu(mm(h2, p, "gate").astype(jnp.float32)
                                ).astype(h2.dtype) * mm(h2, p, "up")
                return x + mm(a, p, "down"), (kcl, vcl, ksl, vsl)

            x, (kc, vc, ks, vs) = lax.scan(body, x,
                                           (params["layers"], kc, vc,
                                            ks, vs))
            h = _rms_weight(x, params["norm_f"], eps)
            hsel = h[lidx]                                    # [Lq, H]
            logits = head_logits(params, hsel)                # [Lq, V]
            if shard_head:
                logits = lax.all_gather(logits, "tp", axis=1, tiled=True)
            sampled = sample_tokens(logits, samp)
            fin = jnp.all(jnp.isfinite(logits), axis=-1)      # [Lq]
            if with_logits:
                return sampled, fin, logits, kc, vc, ks, vs
            return sampled, fin, kc, vc, ks, vs

        # donate the page pools AND scale pools; fresh is input-only
        return self._wrap_tp(run, 7), (1, 2, 3, 4)

    def _consume_fresh(self):
        """Accumulate BlockManager's freshly handed-out pages into the
        persistent mask, hand a snapshot to the launch, and clear — the
        launch's in-program scale reset consumes the batch."""
        for b in self.blocks.drain_fresh():
            self._fresh_np[b] = True
        out = self._fresh_np.copy()
        self._fresh_np[:] = False
        return out

    def _launch_ragged(self, Tq, toks, cu, kvl, bt, lidx, samp,
                       real_tokens):
        self.pad_stats["real"] += int(real_tokens)
        self.pad_stats["padded"] += int(Tq)
        prog = self._get_ragged_prog(Tq)
        if self.kv_dtype == "int8":
            fresh = self._consume_fresh()
            if self._with_logits:
                sampled, fin, logits, self._kc, self._vc, self._ks, \
                    self._vs = prog(
                        self.params, self._kc, self._vc, self._ks,
                        self._vs, fresh, toks, cu, kvl, bt, lidx, samp)
            else:
                sampled, fin, self._kc, self._vc, self._ks, self._vs = \
                    prog(self.params, self._kc, self._vc, self._ks,
                         self._vs, fresh, toks, cu, kvl, bt, lidx, samp)
                logits = None
            return sampled, logits, fin
        if self._with_logits:
            sampled, fin, logits, self._kc, self._vc = prog(
                self.params, self._kc, self._vc, toks, cu, kvl, bt,
                lidx, samp)
        else:
            sampled, fin, self._kc, self._vc = prog(
                self.params, self._kc, self._vc, toks, cu, kvl, bt,
                lidx, samp)
            logits = None
        return sampled, logits, fin

    def _get_window_prog(self):
        """The compiled K-step decode window driver (one per engine —
        its shapes are fixed at [B] rows / K iterations, so unlike the
        ragged step it never re-specializes).  Compiling it adds exactly
        one new ``compile_counts`` key, ``"scan"``, and only for engines
        actually running decode_window > 1."""
        if self._window_prog is None:
            run, donate = self._make_window_fn()
            if jax.default_backend() == "cpu":
                donate = ()
            self._window_prog = jax.jit(run, donate_argnums=donate)
            self.compile_counts["scan"] = \
                self.compile_counts.get("scan", 0) + 1
        return self._window_prog

    def _wrap_tp_window(self, run, n_host_args: int):
        """shard_map for the window driver (identity at tp=1).  Same
        sharding contract as ``_step_specs``: pools slice along H_kv,
        host-packed operands replicate, and both non-pool outputs (the
        [K, B] token and finiteness grids) are replicated after the
        in-body all-gathers — every shard's while_loop sees identical
        replicated logits, so the active-mask and the early-exit
        condition agree across shards by construction."""
        if self.tp == 1:
            return run
        kv = P(None, None, "tp")
        pools = (kv, kv) if self.kv_dtype == "float32" else (kv,) * 4
        in_specs = (self._param_specs(), *pools) + (P(),) * n_host_args
        out_specs = (P(), P()) + pools
        return shard_map(run, mesh=self._mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)

    def _make_window_fn(self):
        """The device-resident K-step decode window program.

        One launch runs up to K = ``decode_window`` full decode steps
        without a host round-trip: a ``lax.while_loop`` whose body is
        EXACTLY the per-step decode program at Tq = B (same layer scan,
        same paged K/V commit, same ragged attention entry, same
        LogitProcessor chain) plus the carry bookkeeping the host does
        between per-step launches — advance kv_lens, re-derive sampler
        keys as fold_in(base, generated), update the repetition-penalty
        ``seen`` mask, and freeze rows whose sampled token hits eos or
        whose generation budget fills (the same predicates
        ``_maybe_retire`` applies host-side).  Frozen rows redirect to
        the sentinel block-table row via ``decode_window_segments`` so
        their writes land in the null page like ragged padding; the
        loop exits early once every row froze.  The host drains the
        [K, B] token grid afterwards — logits and tokens never leave
        the device mid-window, which is the whole point."""
        nh, kvh, d = self._nh, self._kvh, self._hd
        bs = self.block_size
        B = self.max_num_seqs
        K = self.decode_window
        eps = self.config.rms_norm_eps
        theta = self.config.rope_theta
        dt = self._act_dtype
        if self.kv_dtype == "int8":
            return self._make_window_fn_q8()
        tp = self.tp
        nh, kvh = nh // tp, kvh // tp
        shard_head = self._shard_head
        mm, embed, head_logits = self._weight_ops()
        use_pallas = _pa.INTERPRET is True or (
            jax.default_backend() == "tpu"
            and _pa.ragged_supports(B, nh, kvh, d, bs, B + 1,
                                    self.nblk, dt))

        def run(params, kc, vc, toks, kvl, active, gen, budgets,
                eos_ids, base_keys, bt, samp):
            # toks [B] i32 last committed token per row; kvl [B] i32
            # valid KV AFTER iteration 0's write; active [B] bool;
            # gen [B] i32 tokens generated so far (the sampler-key
            # counter); budgets [B] i32 max_new_tokens; eos_ids [B] i32
            # (-1: no eos); base_keys [B,2] u32 PRNGKey(seed) per row;
            # bt [B+1, nblk]; samp the make_samp pytree (its "keys"
            # field is dead — the body derives keys from base_keys).
            rows = jnp.arange(B, dtype=jnp.int32)

            def step(carry):
                (i, tok, kvl, active, gen, seen, kc, vc, touts,
                 fouts) = carry
                seg, rel = _pa.decode_window_segments(active, kvl)
                x = embed(params, tok)                        # [B, H]

                def body(x, inp):
                    p, kcl, vcl = inp
                    h = _rms_weight(x, p["ln1"], eps)
                    q = mm(h, p, "wq").reshape(B, nh, d)
                    k = mm(h, p, "wk").reshape(B, kvh, d)
                    v = mm(h, p, "wv").reshape(B, kvh, d)
                    q = _rope_positions(q, rel, theta)
                    k = _rope_positions(k, rel, theta)
                    blk = bt[seg, rel // bs]                  # [B]
                    slot = rel % bs
                    kcl = kcl.at[blk, :, slot, :].set(k.astype(kcl.dtype))
                    vcl = vcl.at[blk, :, slot, :].set(v.astype(vcl.dtype))
                    if use_pallas:
                        att = _pa.ragged_paged_attention_segrel_packed(
                            q, kcl, vcl, bt, seg, rel)
                    else:
                        att = _pa.ragged_paged_reference_segrel(
                            q, kcl, vcl, bt, seg, rel)
                    if tp > 1:
                        att = lax.all_gather(att, "tp", axis=1,
                                             tiled=True)
                    x = x + mm(att.reshape(B, tp * nh * d), p, "wo")
                    h2 = _rms_weight(x, p["ln2"], eps)
                    a = jax.nn.silu(mm(h2, p, "gate").astype(jnp.float32)
                                    ).astype(h2.dtype) * mm(h2, p, "up")
                    return x + mm(a, p, "down"), (kcl, vcl)

                x, (kc, vc) = lax.scan(body, x,
                                       (params["layers"], kc, vc))
                h = _rms_weight(x, params["norm_f"], eps)
                # every row is its own logit row (lidx == identity)
                logits = head_logits(params, h)
                if shard_head:
                    logits = lax.all_gather(logits, "tp", axis=1,
                                            tiled=True)
                keys = advance_keys(base_keys, gen)
                sampled = sample_tokens(
                    logits, {"temps": samp["temps"],
                             "top_k": samp["top_k"],
                             "top_p": samp["top_p"],
                             "penalty": samp["penalty"],
                             "seen": seen, "keys": keys})
                fin = jnp.all(jnp.isfinite(logits), axis=-1)  # [B]
                # frozen rows carry their last committed token so the
                # grid's dead columns hold committed values, never
                # null-page garbage
                sampled = jnp.where(active, sampled, tok)
                touts = touts.at[i].set(sampled)
                fouts = fouts.at[i].set(fin | ~active)
                seen = seen.at[rows, sampled].set(
                    seen[rows, sampled] | active)
                nxt = active & (sampled != eos_ids) \
                    & (gen + 1 < budgets)
                adv = active.astype(jnp.int32)
                return (i + 1, sampled, kvl + adv, nxt, gen + adv,
                        seen, kc, vc, touts, fouts)

            def cond(carry):
                return (carry[0] < K) & jnp.any(carry[3])

            carry = (jnp.int32(0), toks, kvl, active, gen,
                     samp["seen"], kc, vc,
                     jnp.zeros((K, B), jnp.int32),
                     jnp.ones((K, B), jnp.bool_))
            carry = lax.while_loop(cond, step, carry)
            return carry[8], carry[9], carry[6], carry[7]

        return self._wrap_tp_window(run, 9), (1, 2)

    def _make_window_fn_q8(self):
        """Int8-page variant of the decode window: the per-step q8 body
        verbatim, except the fresh-page scale reset HOISTS out of the
        loop.  The per-step program zeroes fresh pages' scale rows
        inside every layer body because each launch consumes one fresh
        batch; here the whole window's pages are handed out before
        launch, and an in-body reset would wipe scales grown by earlier
        window iterations — so the reset runs ONCE, before iteration 0,
        when every fresh page is still unwritten (byte-equivalent)."""
        nh, kvh, d = self._nh, self._kvh, self._hd
        bs = self.block_size
        B = self.max_num_seqs
        K = self.decode_window
        eps = self.config.rms_norm_eps
        theta = self.config.rope_theta
        dt = self._act_dtype
        tp = self.tp
        nh, kvh = nh // tp, kvh // tp
        shard_head = self._shard_head
        mm, embed, head_logits = self._weight_ops()
        use_pallas = _pa.INTERPRET is True or (
            jax.default_backend() == "tpu"
            and _pa.ragged_quant_supports(B, nh, kvh, d, bs, B + 1,
                                          self.nblk, dt))

        def run(params, kc, vc, ks, vs, fresh, toks, kvl, active, gen,
                budgets, eos_ids, base_keys, bt, samp):
            rows = jnp.arange(B, dtype=jnp.int32)
            ks = jnp.where(fresh[None, :, None], 0.0, ks)
            vs = jnp.where(fresh[None, :, None], 0.0, vs)

            def step(carry):
                (i, tok, kvl, active, gen, seen, kc, vc, ks, vs, touts,
                 fouts) = carry
                seg, rel = _pa.decode_window_segments(active, kvl)
                x = embed(params, tok)                        # [B, H]

                def body(x, inp):
                    p, kcl, vcl, ksl, vsl = inp
                    h = _rms_weight(x, p["ln1"], eps)
                    q = mm(h, p, "wq").reshape(B, nh, d)
                    k = mm(h, p, "wk").reshape(B, kvh, d)
                    v = mm(h, p, "wv").reshape(B, kvh, d)
                    q = _rope_positions(q, rel, theta)
                    k = _rope_positions(k, rel, theta)
                    blk = bt[seg, rel // bs]                  # [B]
                    slot = rel % bs
                    kf = k.astype(jnp.float32)
                    vf = v.astype(jnp.float32)
                    ks_old = ksl[blk]                         # [B, kvh]
                    vs_old = vsl[blk]
                    ksl = ksl.at[blk].max(jnp.max(jnp.abs(kf), axis=-1)
                                          / 127.0)
                    vsl = vsl.at[blk].max(jnp.max(jnp.abs(vf), axis=-1)
                                          / 127.0)
                    ks_new = ksl[blk]
                    vs_new = vsl[blk]
                    rk = jnp.where(ks_new > 0.0,
                                   ks_old / jnp.maximum(ks_new, 1e-30),
                                   0.0)
                    rv = jnp.where(vs_new > 0.0,
                                   vs_old / jnp.maximum(vs_new, 1e-30),
                                   0.0)
                    kp = jnp.round(kcl[blk].astype(jnp.float32)
                                   * rk[:, :, None, None])
                    vp = jnp.round(vcl[blk].astype(jnp.float32)
                                   * rv[:, :, None, None])
                    kcl = kcl.at[blk].set(
                        jnp.clip(kp, -127, 127).astype(jnp.int8))
                    vcl = vcl.at[blk].set(
                        jnp.clip(vp, -127, 127).astype(jnp.int8))
                    kq = jnp.round(kf / jnp.maximum(ks_new,
                                                    1e-30)[:, :, None])
                    vq = jnp.round(vf / jnp.maximum(vs_new,
                                                    1e-30)[:, :, None])
                    kcl = kcl.at[blk, :, slot, :].set(
                        jnp.clip(kq, -127, 127).astype(jnp.int8))
                    vcl = vcl.at[blk, :, slot, :].set(
                        jnp.clip(vq, -127, 127).astype(jnp.int8))
                    if use_pallas:
                        att = \
                            _pa.ragged_paged_attention_quant_segrel_packed(
                                q, kcl, vcl, ksl, vsl, bt, seg, rel)
                    else:
                        att = _pa.ragged_paged_reference_quant_segrel(
                            q, kcl, vcl, ksl, vsl, bt, seg, rel)
                    att = att.astype(x.dtype)
                    if tp > 1:
                        att = lax.all_gather(att, "tp", axis=1,
                                             tiled=True)
                    x = x + mm(att.reshape(B, tp * nh * d), p, "wo")
                    h2 = _rms_weight(x, p["ln2"], eps)
                    a = jax.nn.silu(mm(h2, p, "gate").astype(jnp.float32)
                                    ).astype(h2.dtype) * mm(h2, p, "up")
                    return x + mm(a, p, "down"), (kcl, vcl, ksl, vsl)

                x, (kc, vc, ks, vs) = lax.scan(body, x,
                                               (params["layers"], kc,
                                                vc, ks, vs))
                h = _rms_weight(x, params["norm_f"], eps)
                logits = head_logits(params, h)
                if shard_head:
                    logits = lax.all_gather(logits, "tp", axis=1,
                                            tiled=True)
                keys = advance_keys(base_keys, gen)
                sampled = sample_tokens(
                    logits, {"temps": samp["temps"],
                             "top_k": samp["top_k"],
                             "top_p": samp["top_p"],
                             "penalty": samp["penalty"],
                             "seen": seen, "keys": keys})
                fin = jnp.all(jnp.isfinite(logits), axis=-1)  # [B]
                sampled = jnp.where(active, sampled, tok)
                touts = touts.at[i].set(sampled)
                fouts = fouts.at[i].set(fin | ~active)
                seen = seen.at[rows, sampled].set(
                    seen[rows, sampled] | active)
                nxt = active & (sampled != eos_ids) \
                    & (gen + 1 < budgets)
                adv = active.astype(jnp.int32)
                return (i + 1, sampled, kvl + adv, nxt, gen + adv,
                        seen, kc, vc, ks, vs, touts, fouts)

            def cond(carry):
                return (carry[0] < K) & jnp.any(carry[3])

            carry = (jnp.int32(0), toks, kvl, active, gen,
                     samp["seen"], kc, vc, ks, vs,
                     jnp.zeros((K, B), jnp.int32),
                     jnp.ones((K, B), jnp.bool_))
            carry = lax.while_loop(cond, step, carry)
            return (carry[10], carry[11], carry[6], carry[7], carry[8],
                    carry[9])

        return self._wrap_tp_window(run, 10), (1, 2, 3, 4)

    def _launch_window(self, toks, kvl, active, gen, budgets, eos_ids,
                       base_keys, bt, samp):
        prog = self._get_window_prog()
        if self.kv_dtype == "int8":
            fresh = self._consume_fresh()
            touts, fouts, self._kc, self._vc, self._ks, self._vs = \
                prog(self.params, self._kc, self._vc, self._ks,
                     self._vs, fresh, toks, kvl, active, gen, budgets,
                     eos_ids, base_keys, bt, samp)
        else:
            touts, fouts, self._kc, self._vc = prog(
                self.params, self._kc, self._vc, toks, kvl, active,
                gen, budgets, eos_ids, base_keys, bt, samp)
        return touts, fouts

    def _fill_samp(self, samp, s, req):
        samp["temps"][s] = req.temperature
        samp["top_k"][s] = req.top_k
        samp["top_p"][s] = req.top_p
        samp["penalty"][s] = req.repetition_penalty
        if req.seen is not None:
            np.copyto(samp["seen"][s], req.seen)
        if req.temperature > 0.0:
            # greedy rows never touch their key: an all-greedy launch
            # skips per-step key derivation entirely
            samp["keys"][s] = self._req_key(req)

    def _run_ragged(self, chunks: list, spec: list, batch: list):
        """Pack this step's whole mix as ONE ragged launch.

        Row order: prefill chunks (scheduler order), speculative
        [last_token, drafts...] windows, plain decode tokens (slot
        order).  Returns (sampled tokens, per-spec-row logits or None,
        per-logit-row finite flags, spec row slices, chunk logit slots,
        decode logit slots) — the first three are UNMATERIALIZED device
        arrays the caller's completion ticket blocks on later."""
        total = sum(n for _, n in chunks) \
            + sum(len(d) + 1 for _, d, _ in spec) + len(batch)
        Tq = self._ragged_bucket(total)

        # decode fast path: steady pure-decode steps reuse the
        # persistent host buffers instead of repacking from scratch
        if not chunks and not spec:
            return self._run_ragged_decode(batch, Tq)

        rows = [(req, req.tokens[req.cached:req.cached + n], "c")
                for req, n in chunks]
        rows += [(req, [req.generated[-1]] + list(d), "s")
                 for req, d, _ in spec]
        rows += [(req, [req.generated[-1]], "d") for req in batch]

        B = self.max_num_seqs
        toks = np.zeros((Tq,), np.int32)
        cu = np.zeros((B + 1,), np.int32)
        kvl = np.zeros((B,), np.int32)
        bt = np.full((B + 1, self.nblk), NULL_BLOCK, np.int32)
        lidx = np.zeros((self._Lq,), np.int32)
        samp = make_samp(self._Lq, self.config.vocab_size)
        spec_slices, chunk_slots, batch_slots = [], [], []

        tr = self.tracer
        if tr is not None:
            t = tr.now()
        off = 0      # flat-token cursor
        ls = 0       # logit-row cursor
        for i, (req, window, kind) in enumerate(rows):
            n = len(window)
            toks[off:off + n] = window
            cu[i + 1] = off + n
            kvl[i] = req.cached + n
            if kind == "s":
                # every window position is scored; acceptance is
                # sequential on host, so the device-sampled rows for
                # these slots go unused (samp defaults)
                lidx[ls:ls + n] = np.arange(off, off + n)
                spec_slices.append((ls, n))
                ls += n
            else:
                lidx[ls] = off + n - 1
                self._fill_samp(samp, ls, req)
                (chunk_slots if kind == "c" else batch_slots).append(ls)
                ls += 1
            off += n
        cu[len(rows) + 1:] = off
        if tr is not None:
            tr.complete("engine.pack", t, track=self._trace_track,
                        args={"rows": len(rows), "tokens": total,
                              "bucket": int(Tq)})
            t = tr.now()
        for i, (req, _w, _k) in enumerate(rows):
            bt[i] = self.blocks.padded_table(req.rid, self.nblk)
        if tr is not None:
            tr.complete("engine.block_table_stage", t,
                        track=self._trace_track,
                        args={"rows": len(rows)})

        # padding a four-program step would have cost: a token-bucketed
        # chunk launch, plus the full-width verify launch when anything
        # speculates (folding decode rows), else the decode bucket
        tb = self.prefill_token_bucket
        ct = sum(n for _, n in chunks)
        legacy = max(tb, -(-ct // tb) * tb) if ct else 0
        if spec:
            legacy += B * (self.max_spec_k + 1)
        elif batch:
            legacy += B
        self.pad_stats["legacy_padded"] += legacy

        # the launch (re)packed every row's table fresh, and post-verify
        # truncate changes tables again — break the decode fast path's
        # layout reuse and force full restages next step
        self._break_decode_layout()

        if tr is not None:
            t = tr.now()
        sampled, logits, fin = self._launch_ragged(Tq, toks, cu, kvl, bt,
                                                   lidx, samp, total)
        if tr is not None:
            tr.complete("engine.device_launch", t,
                        track=self._trace_track,
                        args={"bucket": int(Tq)})
        # NO materialization here: sampled/logits/fin return as async
        # device arrays; _complete blocks on them (the dispatch path
        # must never force a host sync on step-program outputs)
        if not spec:
            logits = None
        return sampled, logits, fin, spec_slices, chunk_slots, batch_slots

    def _run_ragged_decode(self, batch: list, Tq: int):
        """Pure-decode launch over the persistent host buffers.  Rows
        repack incrementally ONLY while the layout signature — the rid
        order of the packed rows — is unchanged since the last pure-
        decode step through THIS buffer; retirement, admission,
        preemption, or any mixed launch in between changes the
        signature and forces a full repack, so ragged packing never
        reuses a stale row order.  Within a stable layout, block-table
        rows still refresh whenever the sequence's table version bumped
        (page growth/CoW).

        With overlap on, launches ALTERNATE between the two buffer sets
        (the previous launch may still be in flight and CPU PJRT can
        alias its input arrays) and a valid ``_prestage`` pack for this
        buffer+layout shrinks the incremental work to patching the
        token-id column and the penalty masks."""
        n = len(batch)
        bi = (1 - self._d_cur) if self.overlap else 0
        buf = self._dbufs[bi]
        samp = buf.samp
        layout = tuple(r.rid for r in batch)
        pre = self._prestaged == (bi, layout)
        self._prestaged = None              # single-use
        if layout != buf.layout:
            pre = False
            buf.layout = layout
            buf.bt[:] = NULL_BLOCK
            buf.kvl[:] = 0
            buf.cu[:n + 1] = np.arange(n + 1)
            buf.cu[n + 1:] = n
            samp["temps"][:] = 0.0
            samp["top_k"][:] = 0
            samp["top_p"][:] = 1.0
            samp["penalty"][:] = 1.0
            samp["seen"][:] = False
            for s, req in enumerate(batch):
                samp["temps"][s] = req.temperature
                samp["top_k"][s] = req.top_k
                samp["top_p"][s] = req.top_p
                samp["penalty"][s] = req.repetition_penalty
            buf.bt_ver.clear()               # force table repacks below
        tr = self.tracer
        if tr is not None:
            t = tr.now()
        if pre:
            # prestage already wrote kvl and the sampling keys; only
            # the column that depends on the completed step's SAMPLED
            # token needs patching
            for s, req in enumerate(batch):
                buf.toks[s] = req.generated[-1]
                if req.seen is not None:
                    np.copyto(samp["seen"][s], req.seen)
        else:
            for s, req in enumerate(batch):
                buf.toks[s] = req.generated[-1]
                buf.kvl[s] = req.cached + 1
                if req.seen is not None:
                    np.copyto(samp["seen"][s], req.seen)
                if req.temperature > 0.0:
                    samp["keys"][s] = self._req_key(req)
        if tr is not None:
            tr.complete("engine.pack", t, track=self._trace_track,
                        args={"rows": n, "tokens": n, "bucket": int(Tq),
                              "fast_path": True, "prestaged": pre})
            t = tr.now()
        for s, req in enumerate(batch):
            ver = self.blocks.table_version(req.rid)
            if buf.bt_ver.get(req.rid) != ver:
                buf.bt[s] = self.blocks.padded_table(req.rid, self.nblk)
                buf.bt_ver[req.rid] = ver
        if tr is not None:
            tr.complete("engine.block_table_stage", t,
                        track=self._trace_track, args={"rows": n})
        self.pad_stats["legacy_padded"] += self.max_num_seqs
        if tr is not None:
            t = tr.now()
        sampled, _, fin = self._launch_ragged(Tq, buf.toks, buf.cu,
                                              buf.kvl, buf.bt,
                                              self._d_lidx, samp, n)
        if tr is not None:
            tr.complete("engine.device_launch", t,
                        track=self._trace_track,
                        args={"bucket": int(Tq)})
        self._d_cur = bi
        return sampled, None, fin, [], [], list(range(n))

    def _inject_nan(self, ok, live_slots: list):
        """FaultPlan NaN seam: corrupt one LIVE logit row's finiteness
        flag, as if the device had produced a non-finite row there.
        Flipping the host-side flag (rather than the device logits)
        keeps the injection exact and free when no plan is set; the
        quarantine path downstream is the same either way."""
        plan = self.fault_plan
        if plan is None or not live_slots:
            return ok
        j = plan.take_nan_row(len(live_slots))
        if j is None:
            return ok
        ok = ok.copy()
        ok[live_slots[j]] = False
        self.stats.record_fault("nan")
        return ok

    def _req_key(self, req, ahead: int = 0):
        # key for token i of request r depends only on (seed, i): sampling
        # is reproducible across scheduling orders and preemptions.
        # ahead=1 derives the NEXT position's key (the prestage path:
        # len(generated) will have advanced by one at dispatch time)
        key = jax.random.fold_in(jax.random.PRNGKey(req.seed),
                                 len(req.generated) + ahead)
        return np.asarray(key, np.uint32)



# graft-lint import-of-engine hook: PT_ANALYSIS=strict refuses to import a
# serving module whose source carries ERROR-severity tracer hazards (the
# default 'off' mode is a single flag read).
from ..analysis import enforce_import as _enforce_import  # noqa: E402

_enforce_import(__name__, __file__)
