"""Logit-processor chain shared by every serving sampling site.

``LLMEngine`` used to own a private ``_sample_tokens`` (argmax vs plain
temperature categorical).  Speculative decoding needs the SAME
distribution math in two places — on device inside the decode/prefill
programs, and on host when the verify step turns draft logits into
accept/reject decisions — so the chain lives here, written against an
``xp`` array namespace that is ``jax.numpy`` inside compiled programs
and ``numpy`` on the host.  One implementation, byte-identical greedy
behaviour on both paths.

The chain order mirrors ``LlamaForCausalLM.generate``:

    repetition penalty (CTRL rule) -> [greedy rows: argmax here]
    -> temperature -> top-k -> top-p -> categorical

Per-sequence parameters ride in a ``samp`` dict of batch-wide arrays
(``make_samp``) so one compiled program serves any mix of greedy and
sampled requests:

    temps   [B] f32   (<= 0 -> greedy argmax, generate()-compatible)
    top_k   [B] i32   (0 -> off)
    top_p   [B] f32   (1.0 -> off; top token always kept)
    penalty [B] f32   (1.0 -> off)
    seen    [B,V] bool (prompt + generated token mask for the penalty)
    keys    [B,2] u32  (per-sequence PRNG keys; unused by greedy rows)
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "LogitProcessor", "RepetitionPenaltyProcessor", "TemperatureProcessor",
    "TopKProcessor", "TopPProcessor", "DEFAULT_CHAIN", "advance_keys",
    "make_samp", "samp_structs", "sample_tokens", "target_dist",
]

_NEG_INF = float("-inf")


def _softmax(lg, xp):
    m = xp.max(lg, axis=-1, keepdims=True)
    e = xp.exp(lg - m)
    return e / xp.sum(e, axis=-1, keepdims=True)


class LogitProcessor:
    """One stage of the chain: ``(lg [B,V] f32, samp, xp) -> lg``.

    ``greedy_visible`` stages apply before the greedy/sampled split —
    greedy rows argmax their output; the rest only shape the sampled
    distribution (temperature scaling and truncation never change an
    argmax, matching generate()'s temperature==0 branch).
    """

    greedy_visible = False

    def __call__(self, lg, samp, xp):  # pragma: no cover - interface
        raise NotImplementedError


class RepetitionPenaltyProcessor(LogitProcessor):
    """CTRL rule: logits of seen tokens divide by the penalty when
    positive, multiply when negative.  penalty == 1.0 is the identity."""

    greedy_visible = True

    def __call__(self, lg, samp, xp):
        pen = samp["penalty"][:, None]
        pl = xp.where(lg > 0, lg / pen, lg * pen)
        return xp.where(samp["seen"] & (pen != 1.0), pl, lg)


class TemperatureProcessor(LogitProcessor):
    def __call__(self, lg, samp, xp):
        return lg / xp.maximum(samp["temps"], 1e-6)[:, None]


class TopKProcessor(LogitProcessor):
    """Keep each row's top_k logits (ties at the k-th value survive,
    generate()-compatible); top_k == 0 disables the stage for the row."""

    def __call__(self, lg, samp, xp):
        k = samp["top_k"]
        V = lg.shape[-1]
        srt = -xp.sort(-lg, axis=-1)                       # descending
        idx = xp.clip(k - 1, 0, V - 1).astype(xp.int32)
        kth = xp.take_along_axis(srt, idx[:, None], axis=-1)
        return xp.where((k > 0)[:, None] & (lg < kth), _NEG_INF, lg)


class TopPProcessor(LogitProcessor):
    """Nucleus sampling: smallest prefix of the sorted distribution with
    mass >= top_p (the top token is always kept); top_p >= 1.0 keeps
    every token, disabling the stage for the row."""

    def __call__(self, lg, samp, xp):
        p = samp["top_p"]
        order = xp.argsort(-lg, axis=-1, kind="stable") \
            if xp is np else xp.argsort(-lg, axis=-1)
        srt = xp.take_along_axis(lg, order, axis=-1)
        sp = _softmax(srt, xp)
        cum = xp.cumsum(sp, axis=-1)
        keep_sorted = cum - sp <= p[:, None]               # top always kept
        inv = xp.argsort(order, axis=-1, kind="stable") \
            if xp is np else xp.argsort(order, axis=-1)
        keep = xp.take_along_axis(keep_sorted, inv, axis=-1)
        return xp.where(keep, lg, _NEG_INF)


DEFAULT_CHAIN = (RepetitionPenaltyProcessor(), TemperatureProcessor(),
                 TopKProcessor(), TopPProcessor())


def make_samp(B: int, V: int) -> dict:
    """Host-side samp arrays at their 'off' defaults (greedy, no
    penalty/truncation) — the engine mutates rows in place per slot."""
    return {
        "temps": np.zeros((B,), np.float32),
        "top_k": np.zeros((B,), np.int32),
        "top_p": np.ones((B,), np.float32),
        "penalty": np.ones((B,), np.float32),
        "seen": np.zeros((B, V), bool),
        "keys": np.zeros((B, 2), np.uint32),
    }


def samp_structs(B: int, V: int) -> dict:
    """ShapeDtypeStruct mirror of ``make_samp`` for program_specs."""
    sds = jax.ShapeDtypeStruct
    return {
        "temps": sds((B,), jnp.float32),
        "top_k": sds((B,), jnp.int32),
        "top_p": sds((B,), jnp.float32),
        "penalty": sds((B,), jnp.float32),
        "seen": sds((B, V), jnp.bool_),
        "keys": sds((B, 2), jnp.uint32),
    }


def advance_keys(base_keys, offsets):
    """Scan-carried sampler keys for the device-resident decode window.

    The per-step host path derives each row's key as
    ``fold_in(PRNGKey(seed), len(generated))`` immediately before launch;
    inside a multi-step window the host is absent, so the loop carries
    each row's base key (``PRNGKey(seed)``, [B,2] u32) plus a generated-
    token counter and re-derives ``fold_in(base, counter)`` per iteration
    — the identical threefry derivation, so any K-window slicing of the
    decode stream samples from byte-identical keys.
    """
    return jax.vmap(jax.random.fold_in)(base_keys, offsets)


def sample_tokens(logits, samp, chain=DEFAULT_CHAIN):
    """Device-side per-sequence sampling over [B, V] logits.

    Greedy rows (temps <= 0) argmax after the greedy-visible stages —
    byte-compatible with generate()'s greedy branch — while sampled rows
    run the full chain into a per-row categorical draw.
    """
    lg = logits.astype(jnp.float32)
    for proc in chain:
        if proc.greedy_visible:
            lg = proc(lg, samp, jnp)
    greedy_tok = jnp.argmax(lg, -1).astype(jnp.int32)
    for proc in chain:
        if not proc.greedy_visible:
            lg = proc(lg, samp, jnp)

    def one(key, row):
        return jax.random.categorical(key, row)

    sampled = jax.vmap(one)(samp["keys"], lg).astype(jnp.int32)
    return jnp.where(samp["temps"] <= 0.0, greedy_tok, sampled)


def target_dist(logits_row, *, temperature=0.0, top_k=0, top_p=1.0,
                penalty=1.0, seen=None, chain=DEFAULT_CHAIN):
    """Host-side target distribution for ONE position: the probabilities
    the device sampler would draw from (one-hot argmax for greedy rows).
    The verify step's rejection sampling is exact only because this runs
    the very same chain the compiled programs do.
    """
    lg = np.asarray(logits_row, np.float32)[None]
    V = lg.shape[-1]
    samp = {
        "temps": np.asarray([temperature], np.float32),
        "top_k": np.asarray([top_k], np.int32),
        "top_p": np.asarray([top_p], np.float32),
        "penalty": np.asarray([penalty], np.float32),
        "seen": (np.zeros((1, V), bool) if seen is None
                 else np.asarray(seen, bool).reshape(1, V)),
    }
    with np.errstate(invalid="ignore", over="ignore"):
        for proc in chain:
            if proc.greedy_visible:
                lg = proc(lg, samp, np)
        if temperature <= 0.0:
            out = np.zeros((V,), np.float32)
            out[int(np.argmax(lg[0]))] = 1.0
            return out
        for proc in chain:
            if not proc.greedy_visible:
                lg = proc(lg, samp, np)
        return _softmax(lg, np)[0]
