"""Version metadata (reference python/paddle/version/__init__.py —
full_version/major/minor/patch/rc, commit, cuda()/cudnn()/nccl() probes,
show())."""
from __future__ import annotations

import subprocess

full_version = "0.1.0"
major, minor, patch = (int(x) for x in full_version.split("."))
rc = 0
istaged = False

__all__ = ["full_version", "commit", "show", "cuda", "cudnn", "nccl",
           "xpu", "tpu"]


def _git_commit() -> str:
    import os
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        if out.returncode == 0:
            return out.stdout.strip()
    except Exception:
        pass
    return "unknown"


_commit_cache = None


def __getattr__(name):
    # lazy: resolving the commit forks a git subprocess — do it on first
    # access, not at `import paddle_tpu` (which every worker process pays)
    if name == "commit":
        global _commit_cache
        if _commit_cache is None:
            _commit_cache = _git_commit()
        return _commit_cache
    raise AttributeError(name)


def cuda():
    """False: this build targets TPU via XLA (reference returns the CUDA
    version string on GPU builds)."""
    return False


def cudnn():
    return False


def nccl():
    """Collectives ride XLA over ICI/DCN, not NCCL."""
    return False


def xpu():
    return False


def tpu() -> str:
    """TPU runtime identification: the jax/PJRT versions doing CINN+CUDA's
    job in this build."""
    import jax
    return f"jax {jax.__version__}"


def show() -> None:
    """(reference version/__init__.py show())"""
    print(f"full_version: {full_version}")
    print(f"major: {major}")
    print(f"minor: {minor}")
    print(f"patch: {patch}")
    print(f"rc: {rc}")
    print(f"commit: {__getattr__('commit')}")
    print(f"tpu: {tpu()}")
