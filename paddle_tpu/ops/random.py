"""Random sampling ops.

Parity with /root/reference/python/paddle/tensor/random.py, built on JAX's
counter-based PRNG: the global generator hands each op a fresh fold of the
root key, so results are reproducible under paddle_tpu.seed() and safe under
async dispatch (no hidden mutable state on device).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dispatch as D
from ..core import random_state
from ..core.dtype import convert_dtype, to_jax_dtype
from ..core.tensor import Tensor

__all__ = [
    "seed", "get_rng_state", "set_rng_state", "rand", "randn", "randint",
    "randint_like", "uniform", "normal", "standard_normal", "gaussian",
    "randperm", "bernoulli", "poisson", "multinomial", "exponential_",
    "binomial", "standard_gamma", "log_normal", "cauchy_", "geometric_",
    "uniform_", "normal_",
]


def seed(value):
    random_state.seed(value)
    return value


def get_rng_state():
    return random_state.get_rng_state()


def set_rng_state(state):
    random_state.set_rng_state(state)


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def _dt(dtype, default="float32"):
    return to_jax_dtype(convert_dtype(dtype if dtype is not None else default))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = random_state.next_key()
    return D.apply("uniform",
                   lambda k, shape, dtype, mn, mx: jax.random.uniform(
                       k, shape, np.dtype(dtype), mn, mx),
                   (key,), {"shape": _shape(shape), "dtype": str(_dt(dtype)),
                            "mn": float(min), "mx": float(max)})


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype)


def standard_normal(shape, dtype=None, name=None):
    key = random_state.next_key()
    return D.apply("standard_normal",
                   lambda k, shape, dtype: jax.random.normal(k, shape, np.dtype(dtype)),
                   (key,), {"shape": _shape(shape), "dtype": str(_dt(dtype))})


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        key = random_state.next_key()
        m = mean if isinstance(mean, Tensor) else jnp.asarray(float(mean))
        s = std if isinstance(std, Tensor) else jnp.asarray(float(std))
        return D.apply("normal_t",
                       lambda k, m, s: m + s * jax.random.normal(
                           k, jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s)),
                           jnp.result_type(m, s) if jnp.issubdtype(jnp.result_type(m, s), jnp.floating) else jnp.float32),
                       (key, m, s))
    out = standard_normal(shape if shape is not None else [1])
    from . import math as _m
    return _m.add(_m.scale(out, float(std)), float(mean))


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = random_state.next_key()
    return D.apply("gaussian",
                   lambda k, shape, dtype, mean, std: mean + std * jax.random.normal(
                       k, shape, np.dtype(dtype)),
                   (key,), {"shape": _shape(shape), "dtype": str(_dt(dtype)),
                            "mean": float(mean), "std": float(std)})


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    g = gaussian(shape if shape is not None else [1], float(mean), float(std))
    from . import math as _m
    return _m.exp(g)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    key = random_state.next_key()
    # random *creation* ops keep the reference's int64 default via the same
    # scoped-x64 policy as ops/creation.py (core.dtype.x64_scope)
    from ..core.dtype import x64_scope
    dt = _dt(dtype, "int64")
    with x64_scope(dt):
        return D.apply("randint",
                       lambda k, shape, dtype, lo, hi: jax.random.randint(
                           k, shape, lo, hi, np.dtype(dtype)),
                       (key,), {"shape": _shape(shape), "dtype": str(dt),
                                "lo": int(low), "hi": int(high)})


def randint_like(x, low=0, high=None, dtype=None, name=None):
    # reference contract: output dtype follows x (may be FLOAT) — integer
    # dtypes pass straight through; float targets draw ints then cast
    # (jax randint rejects float dtypes)
    dt = str(dtype or x.dtype.name)
    if dt.startswith(("int", "uint")):
        return randint(low, high, x.shape, dt)
    return randint(low, high, x.shape, "int64").astype(dt)


def randperm(n, dtype="int64", name=None):
    key = random_state.next_key()
    from ..core.dtype import x64_scope
    dt = _dt(dtype, "int64")
    with x64_scope(dt):
        return D.apply("randperm",
                       lambda k, n, dtype: jax.random.permutation(k, n).astype(np.dtype(dtype)),
                       (key,), {"n": int(n), "dtype": str(dt)})


def bernoulli(x, p=None, name=None):
    key = random_state.next_key()
    return D.apply("bernoulli",
                   lambda k, probs: jax.random.bernoulli(k, probs).astype(probs.dtype),
                   (key, x))


def poisson(x, name=None):
    key = random_state.next_key()
    return D.apply("poisson",
                   lambda k, lam: jax.random.poisson(k, lam).astype(lam.dtype),
                   (key, x))


def binomial(count, prob, name=None):
    key = random_state.next_key()
    return D.apply("binomial",
                   lambda k, n, p: jax.random.binomial(k, n.astype(jnp.float32),
                                                       p.astype(jnp.float32)).astype(jnp.int64),
                   (key, count, prob))


def standard_gamma(x, name=None):
    key = random_state.next_key()
    return D.apply("standard_gamma",
                   lambda k, alpha: jax.random.gamma(k, alpha),
                   (key, x))


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = random_state.next_key()
    return D.apply("multinomial",
                   lambda k, probs, n, replace: jax.random.choice(
                       k, probs.shape[-1], shape=(probs.shape[0], n) if probs.ndim == 2 else (n,),
                       replace=replace,
                       p=None if probs.ndim == 2 else probs / jnp.sum(probs)
                   ).astype(jnp.int64) if probs.ndim == 1 else
                   jnp.stack([jax.random.choice(jax.random.fold_in(k, i), probs.shape[-1],
                                                shape=(n,), replace=replace,
                                                p=probs[i] / jnp.sum(probs[i])).astype(jnp.int64)
                              for i in range(probs.shape[0])]),
                   (key, x), {"n": int(num_samples), "replace": bool(replacement)})


def exponential_(x, lam=1.0, name=None):
    key = random_state.next_key()
    out = D.apply("exponential",
                  lambda k, a, lam: jax.random.exponential(k, a.shape, a.dtype) / lam,
                  (key, x), {"lam": float(lam)})
    x._data = out._data
    return x


def cauchy_(x, loc=0, scale=1, name=None):
    key = random_state.next_key()
    out = D.apply("cauchy",
                  lambda k, a, loc, scale: loc + scale * jax.random.cauchy(k, a.shape, a.dtype),
                  (key, x), {"loc": float(loc), "scale": float(scale)})
    x._data = out._data
    return x


def geometric_(x, probs, name=None):
    key = random_state.next_key()
    out = D.apply("geometric",
                  lambda k, a, probs: jax.random.geometric(k, probs, a.shape).astype(a.dtype),
                  (key, x), {"probs": float(probs)})
    x._data = out._data
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    out = uniform(x.shape, x.dtype, min, max)
    x._data = out._data
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    out = gaussian(x.shape, mean, std, dtype=x.dtype)
    x._data = out._data
    return x
