"""jnp kernels for kernel-driven schema ops.

Adding an op to the framework = one entry in ops/ops.yaml with a
``kernel: paddle_tpu.ops.kernels:<fn>`` field + the jnp kernel here; then
``python -m paddle_tpu.codegen`` regenerates the public wrapper, registry,
Tensor-method binding and typing stub (the reference's five-generator
pipeline, SURVEY.md §2.2, collapsed to one).

Kernels receive raw jax arrays (the dispatcher unwraps Tensors) plus the
schema's non-Tensor attrs as keyword arguments, and return arrays.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def sinc(x):
    # normalized sinc (reference paddle.sinc): sin(pi x)/(pi x), 1 at 0
    return jnp.sinc(x)


def trapezoid(y, *maybe_x, dx=1.0, axis=-1, _has_x=False):
    if _has_x:
        return jnp.trapezoid(y, x=maybe_x[0], axis=axis)
    return jnp.trapezoid(y, dx=dx, axis=axis)


def cumulative_trapezoid(y, *maybe_x, dx=1.0, axis=-1, _has_x=False):
    x = maybe_x[0] if _has_x else None
    # cumulative integral with len-1 along axis (matches
    # scipy.integrate.cumulative_trapezoid / reference semantics)
    n = y.shape[axis]
    ya = jnp.moveaxis(y, axis, -1)
    mids = (ya[..., 1:] + ya[..., :-1]) * 0.5
    if x is not None:
        xa = jnp.moveaxis(jnp.broadcast_to(x, y.shape), axis, -1) \
            if x.ndim == y.ndim else x
        if xa.ndim == 1:
            d = xa[1:] - xa[:-1]
        else:
            d = xa[..., 1:] - xa[..., :-1]
        out = jnp.cumsum(mids * d, axis=-1)
    else:
        out = jnp.cumsum(mids * dx, axis=-1)
    del n
    return jnp.moveaxis(out, -1, axis)


def polygamma(x, n=1):
    from jax.scipy.special import polygamma as _pg
    return _pg(n, x)


def i0e(x):
    from jax.scipy.special import i0e as _i0e
    return _i0e(x)


def i1e(x):
    from jax.scipy.special import i1e as _i1e
    return _i1e(x)


def pdist(x, p=2.0):
    # pairwise distances, condensed upper-triangular form [n*(n-1)/2].
    # select the strict upper triangle BEFORE the root so the zero diagonal
    # never feeds sqrt's gradient (0 * inf -> nan in the vjp otherwise)
    n = x.shape[0]
    diff = x[:, None, :] - x[None, :, :]
    iu = jnp.triu_indices(n, k=1)
    if p == 2.0:
        sq = jnp.sum(diff * diff, axis=-1)[iu]
        return jnp.sqrt(sq)
    ab = jnp.sum(jnp.abs(diff) ** p, axis=-1)[iu]
    return ab ** (1.0 / p)


# ---------------------------------------------------------------------------
# Migrated hand-op kernels (VERDICT r3 item 6: yaml as the true source).
# One jnp function per schema op; the public wrapper, Tensor method, registry
# row and stub are generated from ops.yaml.
# ---------------------------------------------------------------------------

# -- unary elementwise ------------------------------------------------------
def abs(x): return jnp.abs(x)                                   # noqa: E704
def neg(x): return jnp.negative(x)                              # noqa: E704
def exp(x): return jnp.exp(x)                                   # noqa: E704
def expm1(x): return jnp.expm1(x)                               # noqa: E704
def log(x): return jnp.log(x)                                   # noqa: E704
def log2(x): return jnp.log2(x)                                 # noqa: E704
def log10(x): return jnp.log10(x)                               # noqa: E704
def log1p(x): return jnp.log1p(x)                               # noqa: E704
def sqrt(x): return jnp.sqrt(x)                                 # noqa: E704
def rsqrt(x): return jax.lax.rsqrt(x)                           # noqa: E704
def square(x): return jnp.square(x)                             # noqa: E704
def sin(x): return jnp.sin(x)                                   # noqa: E704
def cos(x): return jnp.cos(x)                                   # noqa: E704
def tan(x): return jnp.tan(x)                                   # noqa: E704
def asin(x): return jnp.arcsin(x)                               # noqa: E704
def acos(x): return jnp.arccos(x)                               # noqa: E704
def atan(x): return jnp.arctan(x)                               # noqa: E704
def sinh(x): return jnp.sinh(x)                                 # noqa: E704
def cosh(x): return jnp.cosh(x)                                 # noqa: E704
def asinh(x): return jnp.arcsinh(x)                             # noqa: E704
def acosh(x): return jnp.arccosh(x)                             # noqa: E704
def atanh(x): return jnp.arctanh(x)                             # noqa: E704
def tanh(x): return jnp.tanh(x)                                 # noqa: E704
def floor(x): return jnp.floor(x)                               # noqa: E704
def ceil(x): return jnp.ceil(x)                                 # noqa: E704
def round(x, decimals=0):                                       # noqa: E704
    return jnp.round(x, decimals)
def trunc(input): return jnp.trunc(input)                       # noqa: E704
def frac(x): return x - jnp.trunc(x)                            # noqa: E704
def sign(x): return jnp.sign(x)                                 # noqa: E704
def sgn(x): return jnp.sign(x)                                  # noqa: E704
def reciprocal(x): return jnp.reciprocal(x)                     # noqa: E704
def erf(x): return jax.scipy.special.erf(x)                     # noqa: E704
def erfinv(x): return jax.scipy.special.erfinv(x)               # noqa: E704
def isnan(x): return jnp.isnan(x)                               # noqa: E704
def isinf(x): return jnp.isinf(x)                               # noqa: E704
def isfinite(x): return jnp.isfinite(x)                         # noqa: E704
def isposinf(x): return jnp.isposinf(x)                         # noqa: E704
def isneginf(x): return jnp.isneginf(x)                         # noqa: E704
def isreal(x): return jnp.isreal(x)                             # noqa: E704
def signbit(x): return jnp.signbit(x)                           # noqa: E704
def deg2rad(x): return jnp.deg2rad(x)                           # noqa: E704
def rad2deg(x): return jnp.rad2deg(x)                           # noqa: E704
def angle(x): return jnp.angle(x)                               # noqa: E704
def conj(x): return jnp.conj(x)                                 # noqa: E704
def real(x): return jnp.real(x)                                 # noqa: E704
def imag(x): return jnp.imag(x)                                 # noqa: E704
def i0(x): return jnp.i0(x)                                     # noqa: E704
def i1(x): return jax.scipy.special.i1(x)                       # noqa: E704
def digamma(x): return jax.scipy.special.digamma(x)             # noqa: E704
def lgamma(x): return jax.scipy.special.gammaln(x)              # noqa: E704
def gammaln(x): return jax.scipy.special.gammaln(x)             # noqa: E704


# -- binary elementwise -----------------------------------------------------
def add(x, y): return jnp.add(x, y)                             # noqa: E704
def subtract(x, y): return jnp.subtract(x, y)                   # noqa: E704
def multiply(x, y): return jnp.multiply(x, y)                   # noqa: E704
def divide(x, y): return jnp.true_divide(x, y)                  # noqa: E704
def floor_divide(x, y): return jnp.floor_divide(x, y)           # noqa: E704
def remainder(x, y): return jnp.remainder(x, y)                 # noqa: E704
def mod(x, y): return jnp.remainder(x, y)                       # noqa: E704
def pow(x, y): return jnp.power(x, y)                           # noqa: E704
def maximum(x, y): return jnp.maximum(x, y)                     # noqa: E704
def minimum(x, y): return jnp.minimum(x, y)                     # noqa: E704
def fmax(x, y): return jnp.fmax(x, y)                           # noqa: E704
def fmin(x, y): return jnp.fmin(x, y)                           # noqa: E704
def atan2(x, y): return jnp.arctan2(x, y)                       # noqa: E704
def logaddexp(x, y): return jnp.logaddexp(x, y)                 # noqa: E704
def hypot(x, y): return jnp.hypot(x, y)                         # noqa: E704
def copysign(x, y): return jnp.copysign(x, y)                   # noqa: E704
def nextafter(x, y): return jnp.nextafter(x, y)                 # noqa: E704
def heaviside(x, y): return jnp.heaviside(x, y)                 # noqa: E704
def gcd(x, y): return jnp.gcd(x, y)                             # noqa: E704
def lcm(x, y): return jnp.lcm(x, y)                             # noqa: E704
def ldexp(x, y): return jnp.ldexp(x, y.astype(jnp.int32))       # noqa: E704
def bitwise_left_shift(x, y, is_arithmetic=True):               # noqa: E704
    return jnp.left_shift(x, y)
def bitwise_right_shift(x, y, is_arithmetic=True):              # noqa: E704
    return jnp.right_shift(x, y)


# -- comparisons / logic ----------------------------------------------------
def equal(x, y): return jnp.equal(x, y)                         # noqa: E704
def not_equal(x, y): return jnp.not_equal(x, y)                 # noqa: E704
def less_than(x, y): return jnp.less(x, y)                      # noqa: E704
def less_equal(x, y): return jnp.less_equal(x, y)               # noqa: E704
def greater_than(x, y): return jnp.greater(x, y)                # noqa: E704
def greater_equal(x, y): return jnp.greater_equal(x, y)         # noqa: E704
def logical_and(x, y): return jnp.logical_and(x, y)             # noqa: E704
def logical_or(x, y): return jnp.logical_or(x, y)               # noqa: E704
def logical_xor(x, y): return jnp.logical_xor(x, y)             # noqa: E704
def logical_not(x): return jnp.logical_not(x)                   # noqa: E704
def bitwise_and(x, y): return jnp.bitwise_and(x, y)             # noqa: E704
def bitwise_or(x, y): return jnp.bitwise_or(x, y)               # noqa: E704
def bitwise_xor(x, y): return jnp.bitwise_xor(x, y)             # noqa: E704
def bitwise_not(x): return jnp.bitwise_not(x)                   # noqa: E704


# -- matmul family ----------------------------------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


def mm(input, mat2): return jnp.matmul(input, mat2)             # noqa: E704
def bmm(x, y): return jnp.matmul(x, y)                          # noqa: E704
def dot(x, y): return jnp.sum(x * y, axis=-1)                   # noqa: E704
def inner(x, y): return jnp.inner(x, y)                         # noqa: E704
def outer(x, y): return jnp.outer(x, y)                         # noqa: E704
def kron(x, y): return jnp.kron(x, y)                           # noqa: E704


def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


# -- small attr ops ---------------------------------------------------------
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


def log_normalize(x, axis=-1):
    return x - jax.scipy.special.logsumexp(x, axis=axis, keepdims=True)


def reduce_as(x, target):
    if x.shape == target.shape:
        return x
    nlead = x.ndim - target.ndim
    axes = tuple(range(nlead)) + tuple(
        nlead + i for i, d in enumerate(target.shape)
        if x.shape[nlead + i] != d)
    return jnp.sum(x, axis=axes, keepdims=False).reshape(target.shape)


# -- reductions / scans (tranche 2) -----------------------------------------
# NOTE: several names shadow python builtins at THIS module's top level
# (sum/max/min/all/any). Do not call bare builtins below — use builtins.*
# (the shadowing bug class caught twice by the op sweep).

def _axis_t(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False):
    if dtype is not None:
        # framework alias table ('float' -> float32, paddle dtype objects)
        from ..core.dtype import convert_dtype, to_jax_dtype
        dt = to_jax_dtype(convert_dtype(dtype))
    elif jnp.issubdtype(x.dtype, jnp.bool_):
        dt = jnp.int64
    else:
        dt = None
    return jnp.sum(x, axis=_axis_t(axis), keepdims=keepdim, dtype=dt)


def nansum(x, axis=None, dtype=None, keepdim=False):
    return jnp.nansum(x, axis=_axis_t(axis), keepdims=keepdim)


def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=_axis_t(axis), keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=_axis_t(axis), keepdims=keepdim)


def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=_axis_t(axis), keepdims=keepdim)


def max(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis_t(axis), keepdims=keepdim)


def min(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis_t(axis), keepdims=keepdim)


def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis_t(axis), keepdims=keepdim)


def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis_t(axis), keepdims=keepdim)


def argmax(x, axis=None, keepdim=False, dtype="int64"):
    a = None if axis is None else int(axis)
    return jnp.argmax(x, axis=a, keepdims=keepdim).astype(jnp.int64)


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    a = None if axis is None else int(axis)
    return jnp.argmin(x, axis=a, keepdims=keepdim).astype(jnp.int64)


def all(x, axis=None, keepdim=False):
    return jnp.all(x, axis=_axis_t(axis), keepdims=keepdim)


def any(x, axis=None, keepdim=False):
    return jnp.any(x, axis=_axis_t(axis), keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=_axis_t(axis),
                                       keepdims=keepdim)


def cumsum(x, axis=None, dtype=None):
    if axis is None:
        return jnp.cumsum(x.ravel())
    return jnp.cumsum(x, axis=int(axis))


def cumprod(x, dim=None, dtype=None):
    if dim is None:
        return jnp.cumprod(x.ravel())
    return jnp.cumprod(x, axis=int(dim))


def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_axis_t(axis),
                             keepdims=keepdim).astype(jnp.int64)


# -- manipulation (third tranche: shape/axis/indexing ops; attr
#    normalization — Tensor shapes to host ints, lists to tuples — happens
#    in the generated wrapper's _hashable, so kernels see plain values) ----
def reshape(x, shape):
    return jnp.reshape(x, shape)


def transpose(x, perm=None):
    if perm is None:
        perm = tuple(reversed(range(x.ndim)))
    return jnp.transpose(x, tuple(int(p) for p in perm))


def moveaxis(x, source, destination):
    s = tuple(source) if isinstance(source, tuple) else (int(source),)
    d = tuple(destination) if isinstance(destination, tuple) \
        else (int(destination),)
    return jnp.moveaxis(x, s, d)


def swapaxes(x, axis1, axis2):
    return jnp.swapaxes(x, int(axis1), int(axis2))


def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    ax = axis if isinstance(axis, tuple) else (axis,)
    ax = tuple(int(a) for a in ax if x.shape[int(a)] == 1)
    if not ax:
        # no squeezable dim: identity that still records on the tape
        return x * 1 if jnp.issubdtype(x.dtype, jnp.number) else x
    return jnp.squeeze(x, axis=ax)


def unsqueeze(x, axis):
    ax = axis if isinstance(axis, tuple) else (int(axis),)
    return jnp.expand_dims(x, axis=tuple(int(a) for a in ax))


def flatten(x, start_axis=0, stop_axis=-1):
    if x.ndim == 0:
        return jnp.reshape(x, (1,))
    start, stop = start_axis % x.ndim, stop_axis % x.ndim
    shape = tuple(x.shape)
    return jnp.reshape(x, shape[:start] + (-1,) + shape[stop + 1:])


def unflatten(x, axis, shape):
    axis = int(axis) % x.ndim
    cur = tuple(x.shape)
    return jnp.reshape(x, cur[:axis] + tuple(shape) + cur[axis + 1:])


def flip(x, axis):
    ax = axis if isinstance(axis, tuple) else (int(axis),)
    return jnp.flip(x, axis=tuple(int(a) for a in ax))


def fliplr(x):
    return jnp.flip(x, axis=1)


def flipud(x):
    return jnp.flip(x, axis=0)


def roll(x, shifts, axis=None):
    sh = shifts if isinstance(shifts, tuple) else int(shifts)
    ax = axis if (axis is None or isinstance(axis, tuple)) else int(axis)
    return jnp.roll(x, sh, axis=ax)


def tile(x, repeat_times):
    return jnp.tile(x, tuple(int(r) for r in repeat_times))


def _expand_shape(cur, tgt):
    full, pad = [], len(tgt) - len(cur)
    for i, s in enumerate(tgt):
        if s == -1:
            full.append(cur[i - pad] if i >= pad else 1)
        else:
            full.append(int(s))
    return tuple(full)


def expand(x, shape):
    return jnp.broadcast_to(x, _expand_shape(tuple(x.shape), shape))


def broadcast_to(x, shape):
    return expand(x, shape)


def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


def gather(x, index, axis=0):
    if index.ndim == 0:
        index = index[None]
    return jnp.take(x, index, axis=int(axis))


def gather_nd(x, index):
    return x[tuple(jnp.moveaxis(index, -1, 0))]


def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=int(axis))


def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


def index_add(x, index, value, axis):
    a_m = jnp.moveaxis(x, int(axis), 0)
    v_m = jnp.moveaxis(value, int(axis), 0)
    return jnp.moveaxis(a_m.at[index].add(v_m), 0, int(axis))


def index_fill(x, index, value, axis):
    a_m = jnp.moveaxis(x, int(axis), 0)
    out = a_m.at[index].set(jnp.asarray(value).astype(x.dtype))
    return jnp.moveaxis(out, 0, int(axis))


def masked_fill(x, mask, value):
    return jnp.where(mask, jnp.asarray(value).astype(x.dtype), x)


def masked_scatter(x, mask, value):
    flat_m = mask.ravel()
    pos = jnp.cumsum(flat_m.astype(jnp.int32)) - 1
    gathered = value.ravel()[jnp.clip(pos, 0, value.size - 1)]
    return jnp.where(flat_m, gathered, x.ravel()).reshape(x.shape)


def take_along_axis(arr, indices, axis, broadcast=True):
    return jnp.take_along_axis(arr, indices, axis=int(axis))


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True):
    axis = int(axis)
    if jnp.ndim(values) == 0:
        values = jnp.broadcast_to(values, indices.shape)
    moved = jnp.moveaxis(arr, axis, 0)
    idx_m = jnp.moveaxis(indices, axis, 0)
    v_m = jnp.moveaxis(
        jnp.broadcast_to(values.astype(arr.dtype), indices.shape), axis, 0)
    grids = jnp.meshgrid(*[jnp.arange(s) for s in idx_m.shape],
                         indexing="ij")
    grids[0] = idx_m
    if not include_self and reduce != "assign":
        # reference semantics (put_along_axis include_self=False): the
        # original values at targeted positions are excluded from the
        # reduction — reset them to the reduce identity first
        if jnp.issubdtype(arr.dtype, jnp.floating):
            lo, hi = -jnp.inf, jnp.inf
        else:
            info = jnp.iinfo(arr.dtype)
            lo, hi = info.min, info.max
        ident = {"add": 0, "sum": 0, "mul": 1, "multiply": 1,
                 "amax": lo, "amin": hi, "mean": 0}.get(reduce)
        if ident is None:
            raise ValueError(f"unknown reduce {reduce}")
        moved = moved.at[tuple(grids)].set(
            jnp.asarray(ident, arr.dtype))
    at = moved.at[tuple(grids)]
    if reduce == "assign":
        out = at.set(v_m)
    elif reduce in ("add", "sum"):
        out = at.add(v_m)
    elif reduce in ("mul", "multiply"):
        out = at.multiply(v_m)
    elif reduce == "amax":
        out = at.max(v_m)
    elif reduce == "amin":
        out = at.min(v_m)
    elif reduce == "mean":
        cnt = jnp.zeros(moved.shape, jnp.float32).at[tuple(grids)].add(1.0)
        summed = at.add(v_m)
        denom = cnt + (1.0 if include_self else 0.0)
        out = jnp.where(cnt > 0,
                        (summed / jnp.maximum(denom, 1.0)).astype(arr.dtype),
                        summed)
    else:
        raise ValueError(f"unknown reduce {reduce}")
    return jnp.moveaxis(out, 0, axis)


def scatter(x, index, updates, overwrite=True):
    if index.ndim == 2 and index.shape[1] == 1:
        index = index[:, 0]
    if overwrite:
        return x.at[index].set(updates)
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


def scatter_nd(index, updates, shape):
    zeros = jnp.zeros(shape, updates.dtype)
    return zeros.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def scatter_nd_add(x, index, updates):
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def repeat_interleave(x, repeats, axis=None):
    ax = None if axis is None else int(axis)
    if isinstance(repeats, tuple):
        import numpy as _np
        reps = _np.asarray(repeats, _np.int32)
        return jnp.repeat(x, reps, axis=ax,
                          total_repeat_length=int(reps.sum()))
    return jnp.repeat(x, int(repeats), axis=ax)


def _sort_desc_stable(x, axis):
    """Stable descending sort -> (values, indices).

    Ascending lax.sort keyed by (x, reversed-iota) then flipped: equal
    keys tie-break on DESCENDING original index before the flip, so the
    flipped result lists equal elements in original order (the stable
    contract flip-of-ascending violates), while NaN placement still
    matches flip-of-ascending (reference semantics)."""
    ax = int(axis) % x.ndim
    n = x.shape[ax]
    rev = (n - 1) - jax.lax.broadcasted_iota(jnp.int32, x.shape, ax)
    sv, srev = jax.lax.sort((x, rev), dimension=ax, num_keys=2,
                            is_stable=True)
    return (jnp.flip(sv, axis=ax),
            jnp.flip((n - 1) - srev, axis=ax))


def sort(x, axis=-1, descending=False, stable=False):
    # values-only output: equal elements are indistinguishable, so the
    # cheap flip is already "stable" — only argsort needs the index
    # tie-break machinery
    out = jnp.sort(x, axis=int(axis), stable=True)
    return jnp.flip(out, axis=int(axis)) if descending else out


def argsort(x, axis=-1, descending=False, stable=False):
    if descending:
        out = _sort_desc_stable(x, axis)[1]
    else:
        out = jnp.argsort(x, axis=int(axis), stable=True)
    return out.astype(jnp.int64)


def topk(x, k, axis=-1, largest=True, sorted=True):
    k, axis = int(k), int(axis)
    if largest:
        vals, idx = jax.lax.top_k(jnp.moveaxis(x, axis, -1), k)
    else:
        vals, idx = jax.lax.top_k(-jnp.moveaxis(x, axis, -1), k)
        vals = -vals
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(idx, -1, axis).astype(jnp.int64))


def cross(x, y, axis=9):
    ax = 9 if axis is None else int(axis)
    if ax == 9:     # reference sentinel: first dim of size 3
        ax = next((i for i, s in enumerate(x.shape) if s == 3), None)
        if ax is None:
            raise ValueError(
                f"cross: no dimension of size 3 in shape {tuple(x.shape)}; "
                "pass axis explicitly")
    return jnp.cross(x, y, axis=ax)


# -- activations (fourth tranche; reference nn/functional/activation.py) ----
def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jax.nn.relu6(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def silu(x):
    return jax.nn.silu(x)


def swish(x):
    return jax.nn.silu(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


def tanhshrink(x):
    return x - jnp.tanh(x)


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def elu(x, alpha=1.0):
    return jax.nn.elu(x, float(alpha))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def celu(x, alpha=1.0):
    return jax.nn.celu(x, float(alpha))


def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=bool(approximate))


def hardsigmoid(x, slope=0.1666667, offset=0.5):
    return jnp.clip(float(slope) * x + float(offset), 0.0, 1.0)


def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


def hardtanh(x, min=-1.0, max=1.0):
    return jnp.clip(x, float(min), float(max))


def hardshrink(x, threshold=0.5):
    t = float(threshold)
    return jnp.where(jnp.abs(x) > t, x, jnp.zeros((), x.dtype))


def softshrink(x, threshold=0.5):
    t = float(threshold)
    return jnp.where(x > t, x - t,
                     jnp.where(x < -t, x + t, jnp.zeros((), x.dtype)))


def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, float(negative_slope))


def _maybe_cast(x, dtype):
    if dtype is None:
        return x
    from ..core.dtype import convert_dtype, to_jax_dtype
    return x.astype(to_jax_dtype(convert_dtype(dtype)))


def softmax(x, axis=-1, dtype=None):
    # reference softmax casts to `dtype` BEFORE the op when given
    return jax.nn.softmax(_maybe_cast(x, dtype), axis=int(axis))


def log_softmax(x, axis=-1, dtype=None):
    return jax.nn.log_softmax(_maybe_cast(x, dtype), axis=int(axis))


def softplus(x, beta=1.0, threshold=20.0):
    beta, threshold = float(beta), float(threshold)
    return jnp.where(beta * x > threshold, x,
                     jax.nn.softplus(beta * x) / beta)


def thresholded_relu(x, threshold=1.0, value=0.0):
    return jnp.where(x > float(threshold), x,
                     jnp.asarray(float(value), x.dtype))


def maxout(x, groups, axis=1):
    groups, axis = int(groups), int(axis)
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def prelu(x, weight, data_format="NCHW"):
    if weight.size == 1:
        w_b = weight.reshape(())
    else:
        shape = [1] * x.ndim
        ch_axis = 1 if data_format[1] == "C" else x.ndim - 1
        shape[ch_axis] = weight.size
        w_b = weight.reshape(shape)
    return jnp.where(x > 0, x, w_b * x)


def glu(x, axis=-1):
    return jax.nn.glu(x, axis=int(axis))


# -- linalg (fifth tranche; jnp.linalg / lax.linalg lower natively on XLA) --
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


def cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


def cholesky_inverse(x, upper=False):
    Lf = x.astype(jnp.float32)
    eye = jnp.eye(Lf.shape[-1], dtype=jnp.float32)
    # cho_solve's tuple is (c, LOWER): paddle's upper flag is inverted
    return jax.scipy.linalg.cho_solve((Lf, not upper), eye)


def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=bool(rowvar))


def cov(x, *maybe_w, rowvar=True, ddof=True,
        _has_fweights=False, _has_aweights=False):
    it = iter(maybe_w)
    fw = next(it) if _has_fweights else None
    aw = next(it) if _has_aweights else None
    if fw is not None:
        # reference contract: fweights must be integral (np.cov raises
        # TypeError); dtype is static under tracing so this raises eagerly
        if not jnp.issubdtype(fw.dtype, jnp.integer):
            raise TypeError("cov: fweights must be an integer tensor")
        fw = fw.astype(jnp.int32)
    return jnp.cov(x, rowvar=bool(rowvar), ddof=1 if ddof else 0,
                   fweights=fw, aweights=aw)


def det(x):
    return jnp.linalg.det(x)


def dist(x, y, p=2):
    d = jnp.abs(x - y)
    import math as _math
    if p == _math.inf:
        return jnp.max(d)
    if p == -_math.inf:
        return jnp.min(d)
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    p = float(p)
    return jnp.sum(d ** p) ** (1.0 / p)


def eigh(x, UPLO="L"):
    return tuple(jnp.linalg.eigh(x, symmetrize_input=True))


def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x)


def inv(x):
    return jnp.linalg.inv(x)


def matrix_exp(x):
    return jax.scipy.linalg.expm(x)


def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, int(n))


def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=float(rcond), hermitian=bool(hermitian))


def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logabs])


def solve(x, y):
    return jnp.linalg.solve(x, y)


def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=bool(full_matrices))


def svdvals(x):
    return jnp.linalg.svd(x, compute_uv=False)


def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, trans=1 if transpose else 0, lower=not upper,
        unit_diagonal=bool(unitriangular))


def tensordot(x, y, axes=2):
    return jnp.tensordot(x, y, axes=axes)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary"):
    diff = x[..., :, None, :] - y[..., None, :, :]
    if float(p) == 2.0:
        return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 0.0)
    return jnp.sum(jnp.abs(diff) ** float(p), axis=-1) ** (1.0 / float(p))


# -- logic ------------------------------------------------------------------
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(x, y, rtol=float(rtol), atol=float(atol),
                       equal_nan=bool(equal_nan))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(x, y, rtol=float(rtol), atol=float(atol),
                        equal_nan=bool(equal_nan))


def equal_all(x, y):
    if x.shape != y.shape:       # static at trace time
        return jnp.asarray(False)
    return jnp.all(x == y)


# -- math (fifth tranche) ---------------------------------------------------
def float_power(x, y):
    return jnp.power(x.astype(jnp.float64), y)


def lerp(x, y, weight):
    return x + weight * (y - x)


def logcumsumexp(x, axis=None):
    if axis is None:
        x, axis = x.ravel(), 0
    return jax.lax.associative_scan(jnp.logaddexp, x, axis=int(axis))


def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0,
                   keepdims=bool(keepdim))


def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0,
                   keepdims=bool(keepdim))


def numel(x):
    return jnp.asarray(x.size, jnp.int64)


def take(x, index, mode="raise"):
    flat = x.ravel()
    n = flat.shape[0]
    if mode == "wrap":
        index = jnp.mod(index, n)
    elif mode == "clip":
        index = jnp.clip(index, -n, n - 1)
    index = jnp.where(index < 0, index + n, index)
    return flat[index]


def vander(x, n=None, increasing=False):
    return jnp.vander(x, N=None if n is None else int(n),
                      increasing=bool(increasing))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    s, b = float(scale), float(bias)
    return x * s + b if bias_after_scale else (x + b) * s


def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    return jnp.quantile(x, jnp.asarray(q), axis=axis, keepdims=bool(keepdim),
                        method=interpolation)


def nanquantile(x, q, axis=None, keepdim=False):
    return jnp.nanquantile(x, jnp.asarray(q), axis=axis,
                           keepdims=bool(keepdim))


def kthvalue(x, k, axis=-1, keepdim=False):
    k, axis = int(k), int(axis)
    sorted_a = jnp.sort(x, axis=axis)
    idx_a = jnp.argsort(x, axis=axis)
    sel = jnp.asarray([k - 1])
    vals = jnp.take(sorted_a, sel, axis=axis)
    idxs = jnp.take(idx_a, sel, axis=axis)
    if not keepdim:
        vals, idxs = vals.squeeze(axis), idxs.squeeze(axis)
    return vals, idxs.astype(jnp.int64)


def _cum_extreme(x, axis, op):
    if axis is None:
        x, axis = x.ravel(), 0
    axis = int(axis)
    vals = jax.lax.associative_scan(op, x, axis=axis)
    n = x.shape[axis]
    ar = jnp.arange(n).reshape(
        [-1 if i == (axis % x.ndim) else 1 for i in range(x.ndim)])
    idx = jax.lax.associative_scan(jnp.maximum,
                                   jnp.where(x == vals, ar, -1), axis=axis)
    return vals, idx.astype(jnp.int64)


def cummax(x, axis=None, dtype="int64"):
    return _cum_extreme(x, axis, jnp.maximum)


def cummin(x, axis=None, dtype="int64"):
    return _cum_extreme(x, axis, jnp.minimum)


def renorm(x, p, axis, max_norm):
    p, max_norm = float(p), float(max_norm)
    axis = int(axis) % x.ndim          # normalize negative axis
    dims = tuple(i for i in range(x.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(x) ** p, axis=dims, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor


def combinations(x, r=2, with_replacement=False):
    import itertools
    import numpy as _np
    n = x.shape[0]
    idx = (itertools.combinations_with_replacement(range(n), int(r))
           if with_replacement else itertools.combinations(range(n), int(r)))
    idx = _np.asarray(list(idx), dtype=_np.int64)
    if idx.size == 0:
        return jnp.zeros((0, int(r)), x.dtype)
    return jnp.take(x, jnp.asarray(idx.ravel()), axis=0).reshape(-1, int(r))


# -- variadic tensor-list ops (Tensor[] codegen support) --------------------
def concat(*xs, axis=0):
    return jnp.concatenate(xs, axis=int(axis))


def stack(*xs, axis=0):
    return jnp.stack(xs, axis=int(axis))


def vstack(*xs):
    return jnp.vstack(xs)


def hstack(*xs):
    return jnp.hstack(xs)


def dstack(*xs):
    return jnp.dstack(xs)


def multi_dot(*xs):
    return jnp.linalg.multi_dot(xs)


# -- losses (sixth tranche; bodies transcribed from the hand wrappers,
#    protected by tests/test_loss_oracle.py's 68 torch/numpy checks.
#    Optional-weight losses use the generated wrapper's opt-tensor
#    convention: trailing *maybe tensors + _has_<name> attrs) ------------
def _reduce_loss(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def mse_loss(input, label, reduction="mean"):
    return _reduce_loss(jnp.square(input - label), reduction)


def l1_loss(input, label, reduction="mean"):
    return _reduce_loss(jnp.abs(input - label), reduction)


def square_error_cost(input, label):
    return jnp.square(input - label)


def log_loss(input, label, epsilon=1e-4):
    return (-label * jnp.log(input + epsilon)
            - (1 - label) * jnp.log(1 - input + epsilon))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    d = input - label
    abs_d = jnp.abs(d)
    loss = jnp.where(abs_d < delta, 0.5 * d * d / delta,
                     abs_d - 0.5 * delta)
    return _reduce_loss(loss * delta, reduction)   # paddle scales by delta


def kl_div(input, label, reduction="mean", log_target=False):
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        loss = label * (jnp.log(jnp.clip(label, 1e-12, None)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce_loss(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    return _reduce_loss(
        jnp.clip(-label * (input - other) + margin, 0, None), reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    return _reduce_loss(
        jnp.where(label == 1, input, jnp.clip(margin - input, 0, None)),
        reduction)


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean"):
    cos = jnp.sum(input1 * input2, -1) / (
        jnp.linalg.norm(input1, axis=-1)
        * jnp.linalg.norm(input2, axis=-1) + 1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.clip(cos - margin, 0, None))
    return _reduce_loss(loss, reduction)


def soft_margin_loss(input, label, reduction="mean"):
    return _reduce_loss(jnp.log1p(jnp.exp(-label * input)), reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def dist(u, v):
        return jnp.sum(jnp.abs(u - v + epsilon) ** p, axis=-1) ** (1.0 / p)
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    return _reduce_loss(jnp.clip(d_pos - d_neg + margin, 0, None),
                        reduction)


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean"):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        stirling = (label * jnp.log(label) - label
                    + 0.5 * jnp.log(2 * jnp.pi * label))
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce_loss(loss, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    var = jnp.clip(variance, epsilon, None)
    loss = 0.5 * (jnp.log(var) + jnp.square(input - label) / var)
    if full:
        loss = loss + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi, input.dtype))
    return _reduce_loss(loss, reduction)


def dice_loss(input, label, epsilon=1e-5):
    # per-SAMPLE dice averaged over the batch (reference loss.py reduces
    # over axes 1..k then means) — NOT one global dice
    oh = jax.nn.one_hot(jnp.squeeze(label, -1).astype(jnp.int32),
                        input.shape[-1], dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inter = jnp.sum(input * oh, axis=reduce_dims)
    union = jnp.sum(input, axis=reduce_dims) + jnp.sum(oh, axis=reduce_dims)
    return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))


def multi_label_soft_margin_loss(input, label, *maybe_w, reduction="mean",
                                 _has_weight=False):
    loss = -(label * jax.nn.log_sigmoid(input)
             + (1 - label) * jax.nn.log_sigmoid(-input))
    if _has_weight:
        loss = loss * maybe_w[0]
    return _reduce_loss(jnp.mean(loss, axis=-1), reduction)


def binary_cross_entropy(input, label, *maybe_w, reduction="mean",
                         _has_weight=False):
    p = jnp.clip(input, 1e-12, 1.0 - 1e-7)
    loss = -(label * jnp.log(p) + (1 - label) * jnp.log(1 - p))
    if _has_weight:
        loss = loss * maybe_w[0]
    return _reduce_loss(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, *maybe, reduction="mean",
                                     _has_weight=False,
                                     _has_pos_weight=False):
    i = 0
    w = pw = None
    if _has_weight:
        w = maybe[i]; i += 1
    if _has_pos_weight:
        pw = maybe[i]
    max_val = jnp.clip(-logit, 0, None)
    if pw is not None:
        log_w = (pw - 1.0) * label + 1.0
        loss = ((1.0 - label) * logit
                + log_w * (jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val))
    else:
        loss = (jnp.clip(logit, 0, None) - logit * label
                + jnp.log1p(jnp.exp(-jnp.abs(logit))))
    if w is not None:
        loss = loss * w
    return _reduce_loss(loss, reduction)


def nll_loss(input, label, *maybe_w, ignore_index=-100, reduction="mean",
             _has_weight=False):
    l = label.astype(jnp.int32)
    valid = l != ignore_index
    safe = jnp.where(valid, l, 0)
    lp = jnp.moveaxis(input, 1, -1) if input.ndim > 2 else input
    picked = jnp.take_along_axis(lp, safe[..., None], axis=-1)[..., 0]
    loss = -picked
    if _has_weight:
        sw = maybe_w[0][safe]
        loss = jnp.where(valid, loss * sw, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(jnp.where(valid, sw, 0.0)), 1e-12)
        return _reduce_loss(loss, reduction)
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(
            jnp.sum(valid.astype(loss.dtype)), 1.0)
    return _reduce_loss(loss, reduction)


def cross_entropy(input, label, *maybe_w, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, _has_weight=False):
    axis = int(axis)
    if use_softmax:
        logp = jax.nn.log_softmax(input.astype(jnp.float32), axis=axis)
    else:
        logp = jnp.log(jnp.clip(input.astype(jnp.float32), 1e-12, None))
    n_class = input.shape[axis]
    if soft_label or (label.ndim == input.ndim
                      and label.shape[axis] == n_class
                      and jnp.issubdtype(label.dtype, jnp.floating)):
        soft = label.astype(logp.dtype)
        if label_smoothing > 0:
            soft = soft * (1 - label_smoothing) + label_smoothing / n_class
        loss = -jnp.sum(soft * logp, axis=axis)
        if _has_weight:
            wvec = maybe_w[0].astype(logp.dtype)
            shape = [1] * logp.ndim
            shape[axis] = n_class
            loss = loss * jnp.sum(soft * wvec.reshape(shape), axis=axis)
        return _reduce_loss(loss, reduction)
    lbl_i = label
    if lbl_i.ndim == input.ndim:
        lbl_i = jnp.squeeze(lbl_i, axis=axis)
    lbl_i = lbl_i.astype(jnp.int32)
    valid = lbl_i != ignore_index
    safe = jnp.where(valid, lbl_i, 0)
    picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, axis),
                                 axis=axis)
    loss = -jnp.squeeze(picked, axis)
    if label_smoothing > 0:
        smooth_loss = -jnp.mean(logp, axis=axis)
        loss = (1 - label_smoothing) * loss + label_smoothing * smooth_loss
    if _has_weight:
        wvec = maybe_w[0].astype(logp.dtype)
        sample_w = wvec[safe]
        loss = jnp.where(valid, loss * sample_w, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(jnp.where(valid, sample_w, 0.0)), 1e-12)
        return _reduce_loss(loss, reduction)
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(
            jnp.sum(valid.astype(logp.dtype)), 1.0)
    return _reduce_loss(loss, reduction)


def multi_margin_loss(input, label, *maybe_w, p=1, margin=1.0,
                      reduction="mean", _has_weight=False):
    n, c = input.shape
    l = label.astype(jnp.int32)
    correct = jnp.take_along_axis(input, l[:, None], axis=1)
    diff = jnp.clip(margin - correct + input, 0, None) ** p
    if _has_weight:
        diff = diff * maybe_w[0][l][:, None]
    mask = 1.0 - jax.nn.one_hot(l, c, dtype=input.dtype)
    loss = jnp.sum(diff * mask, axis=1) / c
    return _reduce_loss(loss, reduction)


def sigmoid_focal_loss(logit, label, *maybe_norm, alpha=0.25, gamma=2.0,
                       reduction="sum", _has_normalizer=False):
    p = jax.nn.sigmoid(logit)
    ce = -(label * jax.nn.log_sigmoid(logit)
           + (1 - label) * jax.nn.log_sigmoid(-logit))
    a_t = alpha * label + (1 - alpha) * (1 - label)
    p_t = p * label + (1 - p) * (1 - label)
    loss = a_t * (1 - p_t) ** gamma * ce
    if _has_normalizer:
        loss = loss / maybe_norm[0]
    return _reduce_loss(loss, reduction)


# -- r5 tranche: manipulation / misc / math singles migrated from hand
#    wrappers (VERDICT r4 item 5; reference ops.yaml kernel entries)
def one_hot(x, num_classes):
    return jax.nn.one_hot(x, int(num_classes), dtype=jnp.float32)


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    odt = jnp.int32 if out_int32 else jnp.int64
    if sorted_sequence.ndim == 1:
        return jnp.searchsorted(sorted_sequence, values, side=side) \
            .astype(odt)
    return jax.vmap(lambda s, vv: jnp.searchsorted(s, vv, side=side))(
        sorted_sequence.reshape(-1, sorted_sequence.shape[-1]),
        values.reshape(-1, values.shape[-1])
    ).reshape(values.shape).astype(odt)


def bucketize(x, sorted_sequence, out_int32=False, right=False):
    return searchsorted(sorted_sequence, x, out_int32, right)


def crop(x, shape=None, offsets=None):
    shape = tuple(x.shape) if shape is None else tuple(int(s) for s in shape)
    full = tuple(x.shape[i] if s == -1 else s for i, s in enumerate(shape))
    offs = (0,) * x.ndim if offsets is None \
        else tuple(int(o) for o in offsets)
    return jax.lax.dynamic_slice(x, offs, full)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW",
        pad_from_left_axis=True):
    p = tuple(int(v) for v in pad)
    nd = x.ndim
    if len(p) == 2 * nd:
        # full-rank pairs: dim order given by pad_from_left_axis
        # (reference tensor/manipulation.py pad: False = last-dim-first)
        width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        if not pad_from_left_axis:
            width = width[::-1]
    else:
        # conv-style: pairs are LAST-SPATIAL-dim-first (left, right, top,
        # bottom, front, back); the spatial dims depend on data_format
        # (reference nn/functional/common.py pad contract)
        k = len(p) // 2
        width = [(0, 0)] * nd
        if data_format.endswith("C"):   # NHWC / NLC / NDHWC
            spatial = list(range(1, 1 + k))
        else:                           # NCHW / NCL / NCDHW
            spatial = list(range(nd - k, nd))
        for i, dim in enumerate(reversed(spatial)):
            width[dim] = (p[2 * i], p[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, width, mode=jmode,
                       constant_values=jnp.asarray(value, x.dtype))
    return jnp.pad(x, width, mode=jmode)


def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1):
    import builtins
    i = jnp.arange(y.shape[-1])
    rows = i + (0 if offset >= 0 else -offset)
    cols = i + (offset if offset >= 0 else 0)
    a_m = jnp.moveaxis(jnp.moveaxis(x, axis1, 0),
                       axis2 if axis2 > axis1 else axis2 + 1, 1)
    out = a_m.at[rows, cols].set(jnp.moveaxis(y, -1, 0))
    return jnp.moveaxis(
        jnp.moveaxis(out, 1, axis2 if axis2 > axis1 else axis2 + 1),
        0, axis1)


def select_scatter(x, values, axis, index):
    moved = jnp.moveaxis(x, int(axis), 0)
    out = moved.at[int(index)].set(values.astype(x.dtype))
    return jnp.moveaxis(out, 0, int(axis))


def strided_slice(x, axes, starts, ends, strides):
    import builtins
    idx = [builtins.slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[int(ax)] = builtins.slice(int(st), int(en), int(sd))
    return x[tuple(idx)]


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = (int(index_num) + int(nshards) - 1) // int(nshards)
    lo, hi = int(shard_id) * size, (int(shard_id) + 1) * size
    in_range = (input >= lo) & (input < hi)
    return jnp.where(in_range, input - lo, ignore_value)


def cast(x, dtype):
    from ..core.dtype import convert_dtype
    return x.astype(convert_dtype(dtype).np_dtype)


def clip_by_norm(x, max_norm):
    norm = jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2))
    scale = jnp.where(norm > max_norm,
                      max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return (x.astype(jnp.float32) * scale).astype(x.dtype)


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    import builtins
    n = input.shape[-1] + builtins.abs(int(offset))
    base = jnp.zeros(input.shape[:-1] + (n, n), input.dtype)
    di = jnp.arange(input.shape[-1])
    rows = di + builtins.max(0, -int(offset))
    cols = di + builtins.max(0, int(offset))
    out = base.at[..., rows, cols].set(input)
    nd = out.ndim
    d1, d2 = int(dim1) % nd, int(dim2) % nd
    perm = list(range(nd - 2))
    order = sorted([d1, d2])
    for pos, d in zip(order, (nd - 2, nd - 1)):
        perm.insert(pos, d)
    return jnp.transpose(out, perm)


def fill_diagonal(x, value, offset=0, wrap=False):
    n, m = x.shape[-2], x.shape[-1]
    i = jnp.arange(n)[:, None]
    j = jnp.arange(m)[None, :]
    diag = (j - i) == int(offset)
    if wrap and n > m:
        period = m + 1
        diag = ((i * m + j) % period == int(offset) % period) \
            if offset == 0 else diag
    return jnp.where(diag, jnp.asarray(value, x.dtype), x)


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1):
    import numpy as _np
    nd = x.ndim
    d1, d2 = int(dim1) % nd, int(dim2) % nd
    perm = [d for d in range(nd) if d not in (d1, d2)] + [d1, d2]
    ap = jnp.transpose(x, perm)
    n, m = ap.shape[-2], ap.shape[-1]
    i = jnp.arange(n)[:, None]
    j = jnp.arange(m)[None, :]
    mask = (j - i) == int(offset)
    import builtins
    dlen = builtins.min(n, m - offset) if offset >= 0 \
        else builtins.min(n + offset, m)
    di = jnp.arange(dlen)
    rows = di if offset >= 0 else di - int(offset)
    cols = di + builtins.max(0, int(offset))
    carrier = jnp.zeros_like(ap).at[..., rows, cols].set(y.astype(x.dtype))
    out = jnp.where(mask, carrier, ap)
    return jnp.transpose(out, _np.argsort(perm))


def frobenius_norm(x, axis=None, keepdim=False):
    ax = tuple(int(a) for a in axis) if isinstance(axis, (tuple, list)) \
        else (None if axis is None else int(axis))
    af = x.astype(jnp.float32)
    return jnp.sqrt(jnp.sum(af * af, axis=ax, keepdims=keepdim)) \
        .astype(x.dtype)


def gammainc(x, y):
    return jax.scipy.special.gammainc(x.astype(jnp.float32),
                                      y.astype(jnp.float32))


def gammaincc(x, y):
    return jax.scipy.special.gammaincc(x.astype(jnp.float32),
                                       y.astype(jnp.float32))


def inverse(x):
    return jnp.linalg.inv(x)


def mean_all(x):
    return jnp.mean(x)


def multigammaln(x, p):
    af = x.astype(jnp.float32)
    import builtins
    const = int(p) * (int(p) - 1) / 4.0 * jnp.log(jnp.pi).astype(jnp.float32)
    return const + builtins.sum(jax.scipy.special.gammaln(af - i / 2.0)
                                for i in range(int(p)))


def mv(x, vec):
    return x @ vec


def reverse(x, axis):
    return flip(x, tuple(axis) if isinstance(axis, (tuple, list)) else axis)


def slice_scatter(x, value, axes, starts, ends, strides):
    import builtins
    idx = [builtins.slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[int(ax)] = builtins.slice(int(s), int(e), int(st))
    return x.at[tuple(idx)].set(value.astype(x.dtype))


def squared_l2_norm(x):
    return jnp.sum(x.astype(jnp.float32) ** 2)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    a = x
    if data_format == "NHWC":
        a = jnp.transpose(a, (0, 3, 1, 2))
    nt, c, h, w = a.shape
    n = nt // int(seg_num)
    v = a.reshape(n, int(seg_num), c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    back = jnp.pad(v[:, 1:, :c1], ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
    fwd = jnp.pad(v[:, :-1, c1:c2],
                  ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))
    out = jnp.concatenate([back, fwd, v[:, :, c2:]], axis=2).reshape(
        nt, c, h, w)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


def histogram(input, *maybe_w, bins=100, min=0, max=0, density=False,
              _has_weight=False):
    w = maybe_w[0] if _has_weight else None
    mn, mx = min, max
    if mn == 0 and mx == 0:
        mn, mx = jnp.min(input), jnp.max(input)
    h, _ = jnp.histogram(input, bins=int(bins), range=(mn, mx),
                         weights=w, density=density)
    return h if (density or _has_weight) else h.astype(jnp.int64)


def median(x, axis=None, keepdim=False, mode="avg"):
    ax = None if axis is None else int(axis)
    if mode == "avg":
        return jnp.median(x, axis=ax, keepdims=keepdim)
    n = x.shape[ax] if ax is not None else x.size
    k = (n - 1) // 2
    sorted_a = jnp.sort(x, axis=ax) if ax is not None \
        else jnp.sort(x.ravel())
    out = jnp.take(sorted_a, jnp.asarray([k]),
                   axis=ax if ax is not None else 0)
    if not keepdim or ax is None:
        out = jnp.squeeze(out, axis=ax if ax is not None else 0)
    return out


def nanmedian(x, axis=None, keepdim=False, mode="avg"):
    ax = axis if axis is None or isinstance(axis, int) else tuple(axis)
    return jnp.nanmedian(x, axis=ax, keepdims=keepdim)


def mode(x, axis=-1, keepdim=False):
    sorted_a = jnp.sort(x, axis=int(axis))
    idx_a = jnp.argsort(x, axis=int(axis))
    n = x.shape[int(axis)]
    ax = int(axis) % x.ndim
    shape = [n if i == ax else 1 for i in range(x.ndim)]
    pos = jnp.arange(n).reshape(shape)
    first = jnp.take(sorted_a, jnp.asarray([0]), axis=ax)
    is_start = jnp.concatenate(
        [jnp.ones_like(first, dtype=bool),
         jnp.diff(sorted_a, axis=ax) != 0], axis=ax)
    last_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_start, pos, -1), axis=ax)
    run_len = pos - last_start + 1
    best = jnp.argmax(run_len, axis=ax, keepdims=True)
    vals = jnp.take_along_axis(sorted_a, best, axis=ax)
    idxs = jnp.take_along_axis(idx_a, best, axis=ax)
    if not keepdim:
        vals, idxs = vals.squeeze(ax), idxs.squeeze(ax)
    return vals, idxs.astype(jnp.int64)


def diff(x, *maybe, n=1, axis=-1, _has_prepend=False, _has_append=False):
    it = iter(maybe)
    pre = next(it) if _has_prepend else None
    app = next(it) if _has_append else None
    return jnp.diff(x, n=int(n), axis=int(axis), prepend=pre, append=app)
