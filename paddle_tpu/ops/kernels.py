"""jnp kernels for kernel-driven schema ops.

Adding an op to the framework = one entry in ops/ops.yaml with a
``kernel: paddle_tpu.ops.kernels:<fn>`` field + the jnp kernel here; then
``python -m paddle_tpu.codegen`` regenerates the public wrapper, registry,
Tensor-method binding and typing stub (the reference's five-generator
pipeline, SURVEY.md §2.2, collapsed to one).

Kernels receive raw jax arrays (the dispatcher unwraps Tensors) plus the
schema's non-Tensor attrs as keyword arguments, and return arrays.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def sinc(x):
    # normalized sinc (reference paddle.sinc): sin(pi x)/(pi x), 1 at 0
    return jnp.sinc(x)


def trapezoid(y, *maybe_x, dx=1.0, axis=-1, _has_x=False):
    if _has_x:
        return jnp.trapezoid(y, x=maybe_x[0], axis=axis)
    return jnp.trapezoid(y, dx=dx, axis=axis)


def cumulative_trapezoid(y, *maybe_x, dx=1.0, axis=-1, _has_x=False):
    x = maybe_x[0] if _has_x else None
    # cumulative integral with len-1 along axis (matches
    # scipy.integrate.cumulative_trapezoid / reference semantics)
    n = y.shape[axis]
    ya = jnp.moveaxis(y, axis, -1)
    mids = (ya[..., 1:] + ya[..., :-1]) * 0.5
    if x is not None:
        xa = jnp.moveaxis(jnp.broadcast_to(x, y.shape), axis, -1) \
            if x.ndim == y.ndim else x
        if xa.ndim == 1:
            d = xa[1:] - xa[:-1]
        else:
            d = xa[..., 1:] - xa[..., :-1]
        out = jnp.cumsum(mids * d, axis=-1)
    else:
        out = jnp.cumsum(mids * dx, axis=-1)
    del n
    return jnp.moveaxis(out, -1, axis)


def polygamma(x, n=1):
    from jax.scipy.special import polygamma as _pg
    return _pg(n, x)


def i0e(x):
    from jax.scipy.special import i0e as _i0e
    return _i0e(x)


def i1e(x):
    from jax.scipy.special import i1e as _i1e
    return _i1e(x)


def pdist(x, p=2.0):
    # pairwise distances, condensed upper-triangular form [n*(n-1)/2].
    # select the strict upper triangle BEFORE the root so the zero diagonal
    # never feeds sqrt's gradient (0 * inf -> nan in the vjp otherwise)
    n = x.shape[0]
    diff = x[:, None, :] - x[None, :, :]
    iu = jnp.triu_indices(n, k=1)
    if p == 2.0:
        sq = jnp.sum(diff * diff, axis=-1)[iu]
        return jnp.sqrt(sq)
    ab = jnp.sum(jnp.abs(diff) ** p, axis=-1)[iu]
    return ab ** (1.0 / p)
