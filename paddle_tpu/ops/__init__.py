"""Operator library: public op namespace + Tensor method binding.

The reference binds ~400 methods onto Tensor from C++
(/root/reference/paddle/fluid/pybind/eager_method.cc) plus python-side math
patches; here monkey_patch_tensor() attaches the same surface from the op
modules.
"""
from __future__ import annotations

from . import (array, creation, indexing, linalg, logic, manipulation, math,
               misc, random)
from .generated import op_wrappers

_MODULES = (math, manipulation, logic, linalg, creation, random, array,
            misc, op_wrappers)


def _collect():
    ns = {}
    for mod in _MODULES:
        for name in getattr(mod, "__all__", ()):
            fn = getattr(mod, name, None)
            if callable(fn):
                ns.setdefault(name, fn)
    return ns


PUBLIC_OPS = _collect()

# Root-surface completion: `op_` inplace twins (buffer rebinding under
# XLA), extra small ops, then name aliases — all data-driven so the
# surfaces cannot drift (ops/inplace_aliases.py).
from . import inplace_aliases as _ia  # noqa: E402

PUBLIC_OPS.update(_ia.EXTRA_OPS)
PUBLIC_OPS.update(_ia.derive_inplace(PUBLIC_OPS))
for _alias, _target in _ia.ALIASES.items():
    if _target in PUBLIC_OPS:
        PUBLIC_OPS.setdefault(_alias, PUBLIC_OPS[_target])
PUBLIC_OPS.update({k: v for k, v in _ia.CONSTANTS.items()})


def monkey_patch_tensor():
    from ..core.tensor import Tensor

    # Method surface: generated from the op schema (ops.yaml ->
    # generated/tensor_methods.py), mirroring the reference's build-time
    # generated eager_method.cc binding.
    from .generated import bind_tensor_methods
    bind_tensor_methods(Tensor)

    # Aliases matching paddle Tensor-method names.
    alias = {
        "mod": math.mod, "floor_mod": math.mod, "pow": math.pow,
        "abs": math.abs, "t": manipulation.transpose,
        "add": math.add, "subtract": math.subtract, "multiply": math.multiply,
        "divide": math.divide, "matmul": math.matmul, "dot": math.dot,
        "unflatten": manipulation.unflatten,
    }
    for name, fn in alias.items():
        setattr(Tensor, name, fn)

    # Arithmetic dunders (and reflected). Matches the reference's
    # math-op method binding in eager_math_op_patch.
    def _rbin(fn):
        def op(self, other):
            return fn(other, self)
        return op

    Tensor.__add__ = math.add
    Tensor.__radd__ = math.add
    Tensor.__sub__ = math.subtract
    Tensor.__rsub__ = _rbin(math.subtract)
    Tensor.__mul__ = math.multiply
    Tensor.__rmul__ = math.multiply
    Tensor.__truediv__ = math.divide
    Tensor.__rtruediv__ = _rbin(math.divide)
    Tensor.__floordiv__ = math.floor_divide
    Tensor.__rfloordiv__ = _rbin(math.floor_divide)
    Tensor.__mod__ = math.mod
    Tensor.__rmod__ = _rbin(math.mod)
    Tensor.__pow__ = math.pow
    Tensor.__rpow__ = _rbin(math.pow)
    Tensor.__matmul__ = math.matmul
    Tensor.__rmatmul__ = _rbin(math.matmul)
    Tensor.__neg__ = math.neg
    Tensor.__abs__ = math.abs
    Tensor.__invert__ = logic.bitwise_not
    Tensor.__and__ = logic.bitwise_and
    Tensor.__or__ = logic.bitwise_or
    Tensor.__xor__ = logic.bitwise_xor
    Tensor.__eq__ = logic.equal
    Tensor.__ne__ = logic.not_equal
    Tensor.__lt__ = logic.less_than
    Tensor.__le__ = logic.less_equal
    Tensor.__gt__ = logic.greater_than
    Tensor.__ge__ = logic.greater_equal
    Tensor.__getitem__ = indexing.getitem
    Tensor.__setitem__ = indexing.setitem

    # In-place arithmetic: rebind storage (optimizers use _replace_data instead).
    def _iop(fn):
        def op(self, other):
            out = fn(self, other)
            self._data = out._data
            self._grad_node = out._grad_node
            self._output_index = out._output_index
            return self
        return op

    Tensor.__iadd__ = _iop(math.add)
    Tensor.__isub__ = _iop(math.subtract)
    Tensor.__imul__ = _iop(math.multiply)
    Tensor.__itruediv__ = _iop(math.divide)
    Tensor.add_ = _iop(math.add)
    Tensor.subtract_ = _iop(math.subtract)
    Tensor.multiply_ = _iop(math.multiply)
    Tensor.divide_ = _iop(math.divide)
    Tensor.clip_ = lambda self, min=None, max=None, name=None: _inplace(self, math.clip(self, min, max))
    Tensor.scale_ = lambda self, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None: \
        _inplace(self, math.scale(self, scale, bias, bias_after_scale))
    Tensor.zero_ = lambda self: _inplace(self, creation.zeros_like(self))
    Tensor.fill_ = lambda self, value: _inplace(self, creation.full_like(self, value))
    Tensor.exponential_ = random.exponential_
    Tensor.uniform_ = random.uniform_
    Tensor.normal_ = random.normal_

    # Bind the remaining reference Tensor-method surface from the public
    # ops: every derived inplace twin, plus the free functions the
    # reference also exposes as methods (tensor/__init__.py
    # tensor_method_func rows not covered by the generated binding).
    extra_methods = tuple(n for n in PUBLIC_OPS if n.endswith("_")) + (
        "block_diag", "add_n", "inverse", "isin", "broadcast_shape",
        "is_tensor", "reverse", "scatter_nd", "slice_scatter",
        "top_p_sampling", "broadcast_tensors", "multi_dot", "frexp",
        "trapezoid", "cumulative_trapezoid", "polar", "sigmoid",
        "as_strided", "unfold", "diag_embed", "negative", "less",
        "gammainc", "gammaincc", "cast", "mv", "matrix_transpose",
        "multiplex", "multigammaln", "histogram_bin_edges", "histogramdd",
        "cond", "cholesky_inverse", "ormqr", "svd_lowrank",
    )
    for name in extra_methods:
        fn = PUBLIC_OPS.get(name)
        if fn is not None and callable(fn) and not hasattr(Tensor, name):
            setattr(Tensor, name, fn)

    # signal-domain methods (reference binds stft/istft as Tensor methods)
    def _stft(self, *a, **k):
        from ..signal import stft as _f
        return _f(self, *a, **k)

    def _istft(self, *a, **k):
        from ..signal import istft as _f
        return _f(self, *a, **k)

    Tensor.stft = _stft
    Tensor.istft = _istft

    # storage-management inplace ops (reference eager_method set_/resize_)
    def _set(self, source=None, shape=None, **kw):
        import jax.numpy as _jnp
        if source is not None:
            src = source._data if isinstance(source, Tensor) else \
                _jnp.asarray(source)
            self._data = src
        elif shape is not None:
            self._data = _jnp.zeros(tuple(int(s) for s in shape),
                                    self._data.dtype)
        return self

    def _resize(self, shape, fill_zero=False):
        import jax.numpy as _jnp
        n_new = 1
        for s in shape:
            n_new *= int(s)
        flat = self._data.reshape(-1)
        if n_new <= flat.shape[0]:
            out = flat[:n_new]
        else:
            out = _jnp.concatenate(
                [flat, _jnp.zeros((n_new - flat.shape[0],), flat.dtype)])
        self._data = out.reshape(tuple(int(s) for s in shape))
        return self

    Tensor.set_ = _set
    Tensor.resize_ = _resize

    # legacy factory methods the reference binds on Tensor (create_* ignore
    # self — LayerHelper-era surface)
    import paddle_tpu as _root

    Tensor.create_parameter = staticmethod(
        lambda *a, **k: _root.create_parameter(*a, **k))

    def _create_tensor(self=None, dtype="float32", name=None,
                       persistable=False):
        import jax.numpy as _jnp
        from ..core.dtype import to_jax_dtype
        return Tensor(_jnp.zeros((0,), to_jax_dtype(dtype)))

    Tensor.create_tensor = _create_tensor


def _inplace(t, out):
    t._data = out._data
    t._grad_node = out._grad_node
    t._output_index = out._output_index
    return t
