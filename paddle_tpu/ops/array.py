"""TensorArray ops (reference python/paddle/tensor/array.py: create_array /
array_write / array_read / array_length / array_pop over DENSE_TENSOR_ARRAY).

TPU-native design: in eager mode a tensor array IS a Python list (exactly the
reference's dygraph contract — its dygraph branches assert `isinstance(array,
list)`).  Under `jit.to_static` capture, Python lists trace naturally through
JAX (each write/read is resolved at trace time), so no IR-level array type is
needed — the captured program sees the individual element tensors, which is
strictly more XLA-friendly than a runtime array-of-buffers variable.
"""
from __future__ import annotations

from ..core.tensor import Tensor

__all__ = ["create_array", "array_write", "array_read", "array_length",
           "array_pop"]


def _index(i) -> int:
    """Accept int or 0-D/[1] int Tensor (the reference's index contract)."""
    if isinstance(i, Tensor):
        import numpy as np
        arr = np.asarray(i.numpy()).reshape(-1)
        if arr.size != 1:
            raise ValueError(
                f"array index must have a single element, got shape "
                f"{tuple(i.shape)}")
        return int(arr[0])
    return int(i)


def create_array(dtype="float32", initialized_list=None):
    """New tensor array, optionally seeded with tensors
    (reference array.py:232 create_array)."""
    if initialized_list is None:
        return []
    if not isinstance(initialized_list, (list, tuple)):
        raise TypeError(
            f"initialized_list must be list/tuple, got "
            f"{type(initialized_list).__name__}")
    return list(initialized_list)


def array_write(x, i, array=None):
    """Write x at position i; appends when i == len (reference
    array.py:189)."""
    idx = _index(i)
    if array is None:
        array = []
    if idx > len(array):
        raise IndexError(
            f"array_write index {idx} out of range for array of length "
            f"{len(array)} (writes may extend by at most one)")
    if idx == len(array):
        array.append(x)
    else:
        array[idx] = x
    return array

def array_read(array, i):
    """Read the element at position i (reference array.py:110)."""
    return array[_index(i)]


def array_length(array):
    """Number of elements (reference array.py:43)."""
    return len(array)


def array_pop(array, i=-1):
    """Remove and return element i (reference array.py:248 array_pop)."""
    return array.pop(_index(i))
