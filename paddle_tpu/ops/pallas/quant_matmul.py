"""Pallas fused dequant-matmul (TPU): int8/int4 weight streaming.

Decode is HBM-bandwidth-bound: every step re-streams the full weight
set per token, so weight BYTES — not FLOPs — set the decode ceiling.
These kernels store transformer weights as quantized pools (int8 with
per-output-channel f32 scales; int4 nibble-packed two-per-byte with
per-128-row-group scales) and dequantize INLINE in the matmul: each
grid step streams one quantized [bk, bn] weight block from HBM,
upcasts it in VMEM against its scale rows, and feeds the MXU — the
weight traffic per decode step drops ~4x (int8) / ~8x (int4) vs f32
while activations and accumulation stay full f32.

Layout contract (shared with LLMEngine's weight pools):

* int8: ``q`` is [K, N] int8, ``s`` is [N] f32 — symmetric
  per-output-channel scales, float = int8 * s[n].
* int4: ``q`` is [K//2, N] int8 with two signed nibbles per byte —
  packed row r holds unpacked rows 2r (low nibble) and 2r+1 (high
  nibble) of column n; ``s`` is [ceil(K/128), N] f32 — one scale per
  128 consecutive K rows per output column, float = nibble * s[r//128,
  n].  K must be even.

Column-sliced TP sharding commutes with both layouts: slicing q and s
by the same output-column blocks IS the quantization of the sliced f32
weight, so tp=N engines shard the pools with zero resharding.

``reference_matmul`` is the term-identical XLA fake-quant oracle
(dense dequantize, then one f32 matmul) — the CPU/test path and the
correctness baseline; kernel-vs-oracle parity is allclose, not
bit-identical, because the blocked k-loop sums partial products in a
different order than the dense contraction.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tri-state interpret override, same contract as paged_attention.py:
# None (default) resolves per-backend — interpret everywhere except a
# real TPU — so the kernel entry points work on CPU without mutating
# this global.  NOTE the serving engine does NOT ride the auto-resolved
# mode: interpreted matmul costs a Python step per (M/bm, N/bn, K/bk)
# grid cell, so LLMEngine uses the XLA fake-quant reference off-TPU
# unless INTERPRET is explicitly True.
INTERPRET = None

GROUP = 128             # int4 scale-group length along K


def interpret_mode() -> bool:
    """Resolved interpret flag: the module override wins when set."""
    if INTERPRET is None:
        return jax.default_backend() != "tpu"
    return bool(INTERPRET)


# ---------------------------------------------------------------------------
# quantize / dequantize (build-time host transforms + oracle half)
# ---------------------------------------------------------------------------

def quantize_weight(w, weight_dtype: str):
    """Quantize one [K, N] f32 weight to ``(q, s)`` in the pool layout.

    int8: per-output-channel symmetric, s[n] = amax(w[:, n]) / 127.
    int4: per-128-row-group per-output-channel, s[g, n] =
    amax(group) / 7, nibbles packed two-per-byte along K.  All-zero
    channels/groups quantize against scale 1.0 (q == 0 regardless).
    """
    w = jnp.asarray(w, jnp.float32)
    if w.ndim != 2:
        raise ValueError(f"expected a [K, N] weight, got shape {w.shape}")
    K, N = w.shape
    if weight_dtype == "int8":
        amax = jnp.max(jnp.abs(w), axis=0)                   # [N]
        s = jnp.where(amax > 0.0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.round(w / s[None, :]), -127, 127).astype(jnp.int8)
        return q, s
    if weight_dtype == "int4":
        if K % 2:
            raise ValueError(f"int4 packing needs even K, got K={K}")
        G = -(-K // GROUP)
        pad = G * GROUP - K
        wp = jnp.pad(w, ((0, pad), (0, 0))) if pad else w
        gmax = jnp.max(jnp.abs(wp.reshape(G, GROUP, N)), axis=1)  # [G, N]
        s = jnp.where(gmax > 0.0, gmax / 7.0, 1.0)
        srow = jnp.repeat(s, GROUP, axis=0)[:K]              # [K, N]
        q = jnp.clip(jnp.round(w / srow), -8, 7).astype(jnp.int32)
        lo, hi = q[0::2], q[1::2]                            # [K//2, N]
        packed = ((hi << 4) | (lo & 0xF)) & 0xFF
        return jax.lax.bitcast_convert_type(
            packed.astype(jnp.uint8), jnp.int8), s
    raise ValueError(
        f"weight_dtype must be 'int8' or 'int4', got {weight_dtype!r}")


def unpack_int4(packed):
    """[K//2, N] nibble-packed int8 -> [K, N] int32 in [-8, 7]; packed
    row r expands to rows 2r (low nibble) and 2r+1 (high nibble)."""
    p = packed.astype(jnp.int32)
    lo = (p << 28) >> 28            # sign-extend the low nibble
    hi = p >> 4                     # int8->int32 sign-extended already
    Kh, N = packed.shape
    return jnp.stack([lo, hi], axis=1).reshape(2 * Kh, N)


def dequantize_weight(q, s, weight_dtype: str):
    """Dense f32 [K, N] weight from a quantized pool entry — the XLA
    fake-quant half of the oracle, and the engine's off-TPU path."""
    if weight_dtype == "int8":
        return q.astype(jnp.float32) * s[None, :]
    if weight_dtype == "int4":
        w = unpack_int4(q).astype(jnp.float32)
        K = w.shape[0]
        srow = jnp.repeat(s, GROUP, axis=0)[:K]
        return w * srow
    raise ValueError(
        f"weight_dtype must be 'int8' or 'int4', got {weight_dtype!r}")


def quantize_embedding(embed, weight_dtype: str):
    """Quantize a [V, H] embedding table with per-vocab-row symmetric
    scales — the gather axis, so a token lookup dequantizes exactly the
    rows it reads.  int4 packs column PAIRS two-per-byte along H (byte
    column c holds columns 2c low / 2c+1 high); H must be even."""
    embed = jnp.asarray(embed, jnp.float32)
    V, H = embed.shape
    amax = jnp.max(jnp.abs(embed), axis=1)                   # [V]
    if weight_dtype == "int8":
        s = jnp.where(amax > 0.0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.round(embed / s[:, None]),
                     -127, 127).astype(jnp.int8)
        return q, s
    if weight_dtype == "int4":
        if H % 2:
            raise ValueError(f"int4 packing needs even H, got H={H}")
        s = jnp.where(amax > 0.0, amax / 7.0, 1.0)
        q = jnp.clip(jnp.round(embed / s[:, None]), -8, 7) \
            .astype(jnp.int32)
        lo, hi = q[:, 0::2], q[:, 1::2]                      # [V, H//2]
        packed = ((hi << 4) | (lo & 0xF)) & 0xFF
        return jax.lax.bitcast_convert_type(
            packed.astype(jnp.uint8), jnp.int8), s
    raise ValueError(
        f"weight_dtype must be 'int8' or 'int4', got {weight_dtype!r}")


def dequantize_rows(q_rows, s_rows, weight_dtype: str):
    """Inline gather-dequant: gathered embedding rows ``q_rows``
    [T, H or H//2] with their per-row scales ``s_rows`` [T] -> [T, H]
    f32.  This is the embedding's whole bandwidth win — only the rows a
    launch actually reads are ever upcast."""
    if weight_dtype == "int8":
        return q_rows.astype(jnp.float32) * s_rows[:, None]
    if weight_dtype == "int4":
        p = q_rows.astype(jnp.int32)
        lo = (p << 28) >> 28
        hi = p >> 4
        T, Hh = q_rows.shape
        rows = jnp.stack([lo, hi], axis=2).reshape(T, 2 * Hh)
        return rows.astype(jnp.float32) * s_rows[:, None]
    raise ValueError(
        f"weight_dtype must be 'int8' or 'int4', got {weight_dtype!r}")


def reference_matmul(x, q, s, weight_dtype: str):
    """Term-identical XLA fake-quant oracle: dense dequant then one f32
    contraction.  ``x`` [M, K] (any float dtype), result [M, N] f32."""
    w = dequantize_weight(q, s, weight_dtype)
    return jax.lax.dot_general(
        x.astype(jnp.float32), w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# tuned launch geometry
# ---------------------------------------------------------------------------

def _fit(dim: int, want: int) -> int:
    """Largest block <= want that divides dim (block grids never pad)."""
    b = max(1, min(int(want), dim))
    while dim % b:
        b -= 1
    return b


def _fit_k(K: int, want: int, packed: bool) -> int:
    """k-block fit.  int4 blocks must additionally pack (even) and nest
    with the 128-row scale groups: a block is either a multiple of the
    group (one scale row per 128 rows) or a divisor of it (the whole
    block inside one group)."""
    b = max(1, min(int(want), K))
    while b > 1:
        if K % b == 0 and (
                not packed
                or (b % 2 == 0 and (b % GROUP == 0 or GROUP % b == 0))):
            return b
        b -= 1
    return 1


def _block_geometry(m: int, k: int, n: int, weight_dtype: str):
    """Trace-time tuned (bm, bn, bk) for one quantized matmul launch.

    The tuned values only re-tile the SAME contraction — k-blocks are
    visited in ascending order whatever bk is, so accumulation order
    within a block boundary family is fixed by the config, and the
    result is allclose-stable across configs (blocked f32 partial
    sums)."""
    from ...tune import kernel_config
    cfg = kernel_config("quant_matmul",
                        {"m": m, "k": k, "n": n, "dtype": weight_dtype})
    packed = weight_dtype == "int4"
    bm = _fit(m, cfg["block_m"])
    bn = _fit(n, cfg["block_n"])
    bk = _fit_k(k, cfg["block_k"], packed)
    return bm, bn, bk


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------

def _qmm_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, bk, packed):
    """grid (M/bm, N/bn, K/bk), k innermost.  x block [bm, bk]; w block
    [bk, bn] int8 (int4: [bk//2, bn] nibble-packed); s block [gb, bn]
    f32 scale rows covering the block's K rows; o [bm, bn]; scratch acc
    [bm, bn] f32.  Dequant happens HERE, in VMEM, on the streamed
    block — the f32 weight tile never exists in HBM."""
    kstep = pl.program_id(2)

    @pl.when(kstep == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w = w_ref[...]
    if packed:
        p = w.astype(jnp.int32)
        lo = (p << 28) >> 28
        hi = p >> 4
        w = jnp.stack([lo, hi], axis=1).reshape(bk, w.shape[1])
    s = s_ref[...].astype(jnp.float32)               # [gb, bn]
    s = jnp.repeat(s, bk // s.shape[0], axis=0)      # [bk, bn]
    wf = w.astype(jnp.float32) * s
    acc_ref[...] += jax.lax.dot_general(
        x_ref[...].astype(jnp.float32), wf, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(kstep == pl.num_programs(2) - 1)
    def _out():
        o_ref[...] = acc_ref[...]


@functools.partial(jax.jit, static_argnames=("weight_dtype",))
def matmul(x, q, s, *, weight_dtype: str):
    """Fused gather-dequant matmul: ``x @ dequant(q, s)`` -> [M, N] f32.

    ``x`` [M, K] float; ``q``/``s`` in the pool layout documented in
    the module header.  Geometry flows from the tuning cache via
    ``_block_geometry``; callers off-TPU should prefer
    ``reference_matmul`` unless INTERPRET is forced True (the engine's
    contract — the interpreter pays a Python step per grid cell)."""
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(
            f"weight_dtype must be 'int8' or 'int4', got {weight_dtype!r}")
    packed = weight_dtype == "int4"
    M, K = x.shape
    N = q.shape[1]
    s2 = jnp.atleast_2d(s)                           # [G, N] (int8: G=1)
    bm, bn, bk = _block_geometry(M, K, N, weight_dtype)
    if packed:
        w_spec = pl.BlockSpec((bk // 2, bn), lambda m, n, k: (k, n))
        gb = max(1, bk // GROUP)
        if bk % GROUP == 0:
            s_spec = pl.BlockSpec((gb, bn), lambda m, n, k: (k, n))
        else:
            # whole k-block inside one 128-row group
            s_spec = pl.BlockSpec(
                (1, bn), lambda m, n, k: ((k * bk) // GROUP, n))
    else:
        w_spec = pl.BlockSpec((bk, bn), lambda m, n, k: (k, n))
        s_spec = pl.BlockSpec((1, bn), lambda m, n, k: (0, n))
    kern = functools.partial(_qmm_kernel, bk=bk, packed=packed)
    return pl.pallas_call(
        kern,
        grid=(M // bm, N // bn, K // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda m, n, k: (m, k)),
            w_spec,
            s_spec,
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda m, n, k: (m, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret_mode(),
    )(x, q, s2)


# ---------------------------------------------------------------------------
# eligibility: shape heuristics + cached lowering probe
# ---------------------------------------------------------------------------

_PROBE_CACHE: dict = {}
_PROBE_LOGGED = False


def _probe_lowering(M, K, N, weight_dtype) -> bool:
    """Compile-probe the fused kernel for these shapes (cached; the
    degrade-don't-crash contract of the paged kernels: any failure
    returns False so callers fall back to the XLA fake-quant path)."""
    global _PROBE_LOGGED
    key = (M, K, N, weight_dtype, jax.default_backend())
    hit = _PROBE_CACHE.get(key)
    if hit is not None:
        return hit
    if interpret_mode():  # interpreter enforces no TPU tiling rules
        _PROBE_CACHE[key] = True
        return True
    G = -(-K // GROUP)
    qs = jax.ShapeDtypeStruct((K // 2, N), jnp.int8) \
        if weight_dtype == "int4" \
        else jax.ShapeDtypeStruct((K, N), jnp.int8)
    ss = jax.ShapeDtypeStruct((G, N), jnp.float32) \
        if weight_dtype == "int4" \
        else jax.ShapeDtypeStruct((N,), jnp.float32)
    try:
        jax.jit(functools.partial(matmul, weight_dtype=weight_dtype)) \
            .lower(jax.ShapeDtypeStruct((M, K), jnp.float32), qs, ss) \
            .compile()
        ok = True
    except Exception as e:
        ok = False
        if not _PROBE_LOGGED:
            _PROBE_LOGGED = True
            import logging
            logging.getLogger("paddle_tpu.pallas").warning(
                "fused dequant matmul does not lower for "
                f"M={M} K={K} N={N} {weight_dtype}: "
                f"{type(e).__name__}; falling back to XLA fake-quant")
    _PROBE_CACHE[key] = ok
    return ok


def supports(M, K, N, weight_dtype: str) -> bool:
    """Eligibility for the fused kernel: shape heuristic, then an actual
    lowering probe (cached).  Under tensor parallelism callers pass the
    PER-SHARD N — column-sharded pools launch inside shard_map, so
    Mosaic tiles against the shard-local width."""
    if weight_dtype not in ("int8", "int4"):
        return False
    if M < 1 or K < 2 or N < 1:
        return False
    if weight_dtype == "int4" and K % 2:
        return False
    if N % 128 != 0:    # lane tiling: quantized blocks want full lanes
        return False
    return _probe_lowering(M, K, N, weight_dtype)
