"""Flash attention (TPU Pallas), forward AND backward.

TPU-native analog of the reference's FA2 CUDA kernels
(/root/reference/paddle/phi/kernels/gpu/flash_attn_kernel.cu and
flash_attn_grad_kernel.cu wrapping third_party/flashattn, surfaced at
python/paddle/nn/functional/flash_attention.py:358).

Forward: online-softmax kernel tiled for the MXU, emitting the per-row
logsumexp.  Backward: two Pallas kernels (dk/dv then dq) that RECOMPUTE the
probability tiles from q/k + the saved logsumexp — residuals are O(S·D+S),
never the O(S^2) score matrix.  GQA (num_kv_heads < num_heads) is handled in
the index maps; grouped dk/dv partials are summed over the query-head group.

Layout: q [batch, seq, heads, head_dim]; k/v [batch, seq, kv_heads, head_dim]
(paddle flash_attention layout), output [batch, seq, heads, head_dim].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

# Tests flip this to run the same kernels via the Pallas interpreter on CPU.
INTERPRET = False


def _fa_blocks(sq: int, sk: int, d: int, dtype_name: str,
               kernel: str = "flash_attention"):
    """Trace-time tuned (block_q, block_k) for this launch shape.

    Geometry flows from the tuning cache (env overrides and forced
    configs win inside kernel_config); _pick_block then snaps each
    preference to a power of two dividing the actual extent."""
    from ...tune import kernel_config
    cfg = kernel_config(kernel, {"seq_q": sq, "seq_k": sk, "head_dim": d,
                                 "dtype": dtype_name})
    return (_pick_block(sq, int(cfg["block_q"])),
            _pick_block(sk, int(cfg["block_k"])))


def _pick_block(seq_len: int, pref: int) -> int:
    """Largest power-of-two block <= pref that divides seq_len (>=128).

    Big blocks matter on TPU: grid programs run sequentially on the one
    TensorCore, so 128-wide tiles at head_dim 64 leave the MXU mostly idle
    on per-program overhead — 512-wide tiles amortize it (measured 2.4x
    step-time win at S=2048 on v5e, tmp/fa_block_sweep).
    """
    b = pref
    while b > 128 and seq_len % b:
        b //= 2
    return min(b, seq_len)


def _repeat_kv(x, group):
    if group == 1:
        return x
    b, s, hk, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, hk, group, d)
                            ).reshape(b, s, hk * group, d)


def _ref_attention(q, k, v, causal):
    """O(S^2) reference composition (numerics oracle + XLA fallback)."""
    group = q.shape[2] // k.shape[2]
    k, v = _repeat_kv(k, group), _repeat_kv(v, group)
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * s
    if causal:
        ql, kl = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _dot_f32(a, b, dims):
    """Matmul keeping operands in their storage dtype (bf16 runs the MXU at
    full rate; f32 operands would run at a fraction of it) with float32
    accumulation."""
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                causal, sm_scale, block_k, kv_len):
    # grid: (batch*heads, q_blocks); refs are [block_q, d] / [kv_len, d]
    # sm_scale folded into q ONCE ([block_q, d] pass) instead of into every
    # [block_q, block_k] score tile; causal masking (2 iotas + cmp + select
    # per tile, all VPU) runs ONLY on diagonal-crossing blocks — interior
    # blocks take the mask-free body.  The VPU passes per tile, not the MXU
    # matmuls, bound this kernel at head_dim 64 (measured on v5e).
    q = (q_ref[...].astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
    block_q, d = q.shape
    q_idx = pl.program_id(1)

    acc = jnp.zeros((block_q, d), jnp.float32)
    m_i = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l_i = jnp.zeros((block_q,), jnp.float32)

    num_k_blocks = kv_len // block_k

    def tile(kb, carry, masked):
        acc, m_i, l_i = carry
        k = k_ref[pl.dslice(kb * block_k, block_k), :]
        v = v_ref[pl.dslice(kb * block_k, block_k), :]
        s = _dot_f32(q, k, ((1,), (1,)))             # [block_q, block_k] f32
        if masked:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_i - m_new)
        l_new = alpha * l_i + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + _dot_f32(p.astype(v.dtype), v,
                                              ((1,), (0,)))
        return acc, m_new, l_new

    carry = (acc, m_i, l_i)
    if causal:
        # interior blocks (entirely below the diagonal): mask-free body
        q_lo = q_idx.astype(jnp.int32) * jnp.int32(block_q)
        q_end = q_lo + jnp.int32(block_q)
        full_hi = q_lo // jnp.int32(block_k)
        hi = jnp.minimum(jnp.int32(num_k_blocks),
                         (q_end - 1) // jnp.int32(block_k) + jnp.int32(1))
        carry = jax.lax.fori_loop(
            jnp.int32(0), full_hi,
            lambda kb, c: tile(kb, c, masked=False), carry)
        carry = jax.lax.fori_loop(
            full_hi, hi, lambda kb, c: tile(kb, c, masked=True), carry)
    else:
        carry = jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(num_k_blocks),
            lambda kb, c: tile(kb, c, masked=False), carry)
    acc, m_i, l_i = carry
    o_ref[...] = (acc / l_i[:, None]).astype(o_ref.dtype)
    # lse ref is [1, block_q]: kept 3-D as [BH, 1, Sq] outside so the block's
    # last-two dims (1, block_q) satisfy Mosaic's (8,128)-divisible-or-full
    # rule.  lse is in the SCALED (q*sm_scale) domain, matching what the
    # backward kernels recompute.
    lse_ref[...] = (m_i + jnp.log(l_i))[None, :]


def _gqa_maps(h, group):
    """Index maps over grid (bh, blk) for q-layout [B*H] and kv-layout
    [B*HK] flattened leading dims (HK = H // group)."""
    hk = h // group

    def q_map(bh, blk):
        return (bh, blk, 0)

    def kv_map(bh, blk):
        kvh = (bh // h) * hk + (bh % h) // group
        return (kvh, 0, 0)

    return q_map, kv_map


def _flash_fwd_pallas(q, k, v, causal):
    """Returns (out, lse); lse is [B*H, 1, Sq] float32 in the scaled domain
    (the singleton dim keeps the Pallas vector blocks TPU-tileable)."""
    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    group = h // hk
    sm_scale = 1.0 / math.sqrt(d)
    # flatten batch*heads; layout [BH, S, D]
    qr = jnp.swapaxes(q, 1, 2).reshape(b * h, sq, d)
    kr = jnp.swapaxes(k, 1, 2).reshape(b * hk, sk, d)
    vr = jnp.swapaxes(v, 1, 2).reshape(b * hk, sk, d)

    block_q, block_k = _fa_blocks(sq, sk, d, jnp.dtype(q.dtype).name)

    kernel = functools.partial(_fwd_kernel, causal=causal, sm_scale=sm_scale,
                               block_k=block_k, kv_len=sk)
    q_map, kv_map = _gqa_maps(h, group)

    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), q_map),
            pl.BlockSpec((None, sk, d), kv_map),
            pl.BlockSpec((None, sk, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), q_map),
            pl.BlockSpec((None, 1, block_q), lambda bh, qb: (bh, 0, qb)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, sq), jnp.float32),
        ],
        interpret=INTERPRET,
    )(qr, kr, vr)
    return jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2), lse


def _bwd_dkdv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                     dk_ref, dv_ref, *, causal, sm_scale, block_q, q_len):
    # grid: (batch*heads, k_blocks); k/v refs [block_k, d];
    # q/do refs [q_len, d]; lse/delta refs [1, q_len]
    k = k_ref[...]
    v = v_ref[...]
    block_k, d = k.shape
    k_idx = pl.program_id(1)

    dk = jnp.zeros((block_k, d), jnp.float32)
    dv = jnp.zeros((block_k, d), jnp.float32)
    num_q_blocks = q_len // block_q

    def tile(qb, carry, masked):
        dk, dv = carry
        # sm_scale folded into the [block_q, d] q slice, not the
        # [block_k, block_q] score tile; the dk matmul then needs NO extra
        # dst * sm_scale pass (dk = dst^T (q*sm)).
        q = (q_ref[pl.dslice(qb * block_q, block_q), :]
             .astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
        do = do_ref[pl.dslice(qb * block_q, block_q), :]
        lse = lse_ref[0, pl.dslice(qb * block_q, block_q)]
        delta = delta_ref[0, pl.dslice(qb * block_q, block_q)]
        # transposed score tile: [block_k, block_q] f32
        st = _dot_f32(k, q, ((1,), (1,)))
        if masked:
            k_pos = k_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 0)
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 1)
            st = jnp.where(q_pos >= k_pos, st, -jnp.inf)
        pt = jnp.exp(st - lse[None, :])
        ptc = pt.astype(do.dtype)
        dv = dv + _dot_f32(ptc, do, ((1,), (0,)))
        dpt = _dot_f32(v, do, ((1,), (1,)))  # [block_k, block_q] f32
        dst = pt * (dpt - delta[None, :])
        dk = dk + _dot_f32(dst.astype(q.dtype), q, ((1,), (0,)))
        return dk, dv

    carry = (dk, dv)
    if causal:
        # q blocks [lo, full_lo) cross the diagonal (masked body); q blocks
        # [full_lo, nqb) are entirely below it (mask-free body)
        k_lo = k_idx.astype(jnp.int32) * jnp.int32(block_k)
        lo = k_lo // jnp.int32(block_q)
        full_lo = jnp.minimum(
            jnp.int32(num_q_blocks),
            (k_lo + jnp.int32(block_k - 1)) // jnp.int32(block_q)
            + jnp.int32(1))
        carry = jax.lax.fori_loop(
            lo, full_lo, lambda qb, c: tile(qb, c, masked=True), carry)
        carry = jax.lax.fori_loop(
            full_lo, jnp.int32(num_q_blocks),
            lambda qb, c: tile(qb, c, masked=False), carry)
    else:
        carry = jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(num_q_blocks),
            lambda qb, c: tile(qb, c, masked=False), carry)
    dk, dv = carry
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(k_ref, v_ref, do_ref, lse_ref, delta_ref, q_ref,
                   dq_ref, *, causal, sm_scale, block_k, kv_len):
    # grid: (batch*heads, q_blocks); q/do/dq refs [block_q, d];
    # k/v refs [kv_len, d]; lse/delta refs [1, block_q]
    # sm_scale folded into q once; the dq matmul consumes a scaled k slice
    # (dq = ds (k*sm)), so no per-tile ds * sm_scale pass
    q = (q_ref[...].astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
    do = do_ref[...]
    lse = lse_ref[0, :]
    delta = delta_ref[0, :]
    block_q, d = q.shape
    q_idx = pl.program_id(1)

    dq = jnp.zeros((block_q, d), jnp.float32)
    num_k_blocks = kv_len // block_k

    def tile(kb, dq, masked):
        k = k_ref[pl.dslice(kb * block_k, block_k), :]
        v = v_ref[pl.dslice(kb * block_k, block_k), :]
        ks = (k.astype(jnp.float32) * sm_scale).astype(k.dtype)
        s = _dot_f32(q, k, ((1,), (1,)))
        if masked:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        p = jnp.exp(s - lse[:, None])
        dp = _dot_f32(do, v, ((1,), (1,)))
        ds = p * (dp - delta[:, None])
        return dq + _dot_f32(ds.astype(k.dtype), ks, ((1,), (0,)))

    if causal:
        q_lo = q_idx.astype(jnp.int32) * jnp.int32(block_q)
        q_end = q_lo + jnp.int32(block_q)
        full_hi = q_lo // jnp.int32(block_k)
        hi = jnp.minimum(jnp.int32(num_k_blocks),
                         (q_end - 1) // jnp.int32(block_k) + jnp.int32(1))
        dq = jax.lax.fori_loop(
            jnp.int32(0), full_hi, lambda kb, a: tile(kb, a, masked=False),
            dq)
        dq = jax.lax.fori_loop(
            full_hi, hi, lambda kb, a: tile(kb, a, masked=True), dq)
    else:
        dq = jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(num_k_blocks),
            lambda kb, a: tile(kb, a, masked=False), dq)
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, g, causal):
    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    group = h // hk
    sm_scale = 1.0 / math.sqrt(d)

    qr = jnp.swapaxes(q, 1, 2).reshape(b * h, sq, d)
    kr = jnp.swapaxes(k, 1, 2).reshape(b * hk, sk, d)
    vr = jnp.swapaxes(v, 1, 2).reshape(b * hk, sk, d)
    dor = jnp.swapaxes(g, 1, 2).reshape(b * h, sq, d)
    outr = jnp.swapaxes(out, 1, 2).reshape(b * h, sq, d)

    # delta_i = rowsum(dO_i * O_i) — O(S·D) precompute, standard FA2 trick;
    # carried [BH, 1, Sq] like lse for TPU-legal vector tiling
    delta = jnp.sum(dor.astype(jnp.float32) * outr.astype(jnp.float32),
                    axis=-1)[:, None, :]

    block_q, block_k = _fa_blocks(sq, sk, d, jnp.dtype(q.dtype).name)
    q_map, kv_map = _gqa_maps(h, group)

    def vec_q_map(bh, blk):
        return (bh, 0, 0)

    # ---- dk/dv: grid over (B*H, k blocks); per-query-head partials are
    # summed over the GQA group afterwards (group is small).
    k_blk_map = lambda bh, kb: (bh, kb, 0)  # noqa: E731

    dk_part, dv_part = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, q_len=sq),
        grid=(b * h, sk // block_k),
        in_specs=[
            # q/do are full-seq blocks: the block index along seq must be a
            # literal 0 (kb-kb), NOT the k-block id — relying on Pallas's
            # out-of-range clamp would be wrong-by-construction
            pl.BlockSpec((None, sq, d), lambda bh, kb: (bh, 0, 0)),
            pl.BlockSpec((None, sq, d), lambda bh, kb: (bh, 0, 0)),
            pl.BlockSpec((None, 1, sq), vec_q_map),   # lse
            pl.BlockSpec((None, 1, sq), vec_q_map),   # delta
            pl.BlockSpec((None, block_k, d),
                         lambda bh, kb, _h=h, _g=group, _hk=hk:
                         ((bh // _h) * _hk + (bh % _h) // _g, kb, 0)),
            pl.BlockSpec((None, block_k, d),
                         lambda bh, kb, _h=h, _g=group, _hk=hk:
                         ((bh // _h) * _hk + (bh % _h) // _g, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), k_blk_map),
            pl.BlockSpec((None, block_k, d), k_blk_map),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, sk, d), jnp.float32),
        ],
        interpret=INTERPRET,
    )(qr, dor, lse, delta, kr, vr)

    if group > 1:
        dk_r = dk_part.reshape(b, hk, group, sk, d).sum(axis=2)
        dv_r = dv_part.reshape(b, hk, group, sk, d).sum(axis=2)
    else:
        dk_r = dk_part.reshape(b, hk, sk, d)
        dv_r = dv_part.reshape(b, hk, sk, d)
    dk = jnp.swapaxes(dk_r, 1, 2).astype(k.dtype)
    dv = jnp.swapaxes(dv_r, 1, 2).astype(v.dtype)

    # ---- dq: grid over (B*H, q blocks)
    dq_flat = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, sm_scale=sm_scale,
                          block_k=block_k, kv_len=sk),
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, sk, d), kv_map),      # k
            pl.BlockSpec((None, sk, d), kv_map),      # v
            pl.BlockSpec((None, block_q, d), q_map),  # do
            pl.BlockSpec((None, 1, block_q), lambda bh, qb: (bh, 0, qb)),
            pl.BlockSpec((None, 1, block_q), lambda bh, qb: (bh, 0, qb)),
            pl.BlockSpec((None, block_q, d), q_map),  # q
        ],
        out_specs=pl.BlockSpec((None, block_q, d), q_map),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=INTERPRET,
    )(kr, vr, dor, lse, delta, qr)
    dq = jnp.swapaxes(dq_flat.reshape(b, h, sq, d), 1, 2)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_attention(causal, q, k, v):
    out, _ = _flash_fwd_pallas(q, k, v, causal)
    return out


def _flash_fwd_rule(causal, q, k, v):
    out, lse = _flash_fwd_pallas(q, k, v, causal)
    # residuals are O(S·D) + O(S): inputs, output, logsumexp — never scores
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_pallas(q, k, v, out, lse, g, causal)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _shapes_eligible(shape, dtype_name, kv_shape=None, causal=True) -> bool:
    """Static shape heuristic: do these shapes tile onto the MXU at all?"""
    if not _HAS_PALLAS:
        return False
    if jax.default_backend() not in ("tpu",) and not INTERPRET:
        return False
    if len(shape) != 4:
        return False
    b, s, h, d = shape
    if d % 128 != 0 and d not in (64, 128, 256):
        return False
    if kv_shape is not None:
        if len(kv_shape) != 4 or kv_shape[0] != b or kv_shape[3] != d:
            return False
        hk = kv_shape[2]
        if hk == 0 or h % hk != 0:  # GQA group must divide heads
            return False
        if kv_shape[1] % 128 != 0:
            return False
        # the kernel's causal mask is top-left aligned (q_pos >= k_pos);
        # _ref_attention uses bottom-right alignment for sq != sk, so
        # cross-length causal must NOT take the kernel path
        if causal and kv_shape[1] != s:
            return False
    return s % 128 == 0 and dtype_name in ("float32", "bfloat16")


# (shapes, dtype, causal, backend) -> bool.  The r2 bench died because a
# shape heuristic said yes and Mosaic said no at run time; the authoritative
# check is an actual lowering, done ONCE per shape and cached.
_PROBE_CACHE: dict = {}
_PROBE_LOGGED = False


def _probe_lowering(q_sds, k_sds, causal) -> bool:
    """Compile-probe the fwd+bwd kernels for these abstract shapes.

    Returns False (and logs once) on any lowering/compile failure so callers
    degrade to `_ref_attention` instead of zeroing the whole program — the
    TPU analog of the reference's kernel-selection fallback around FA2
    (flash_attn_kernel.cu dispatch path).
    """
    global _PROBE_LOGGED
    key = (tuple(q_sds.shape), tuple(k_sds.shape), str(q_sds.dtype),
           bool(causal), jax.default_backend())
    hit = _PROBE_CACHE.get(key)
    if hit is not None:
        return hit
    if INTERPRET:  # interpreter enforces no TPU tiling rules; nothing to probe
        _PROBE_CACHE[key] = True
        return True

    def fwd_bwd(q, k, v, g):
        out, vjp = jax.vjp(
            lambda q_, k_, v_: _flash_attention(causal, q_, k_, v_), q, k, v)
        return out, vjp(g)

    try:
        jax.jit(fwd_bwd).lower(q_sds, k_sds, k_sds, q_sds).compile()
        ok = True
    except Exception as e:  # Mosaic/XLA lowering failure -> fallback
        ok = False
        if not _PROBE_LOGGED:
            _PROBE_LOGGED = True
            import logging
            logging.getLogger("paddle_tpu").warning(
                "Pallas flash-attention failed to lower for q=%s k=%s "
                "(causal=%s): %s -- falling back to the XLA composition",
                q_sds.shape, k_sds.shape, causal, str(e)[:500])
    _PROBE_CACHE[key] = ok
    return ok


def use_flash(q, k, causal=True) -> bool:
    """THE eligibility predicate (single source of truth): flag + static
    shape check + one-time lowering probe."""
    from ...core.flags import get_flag
    if not get_flag("use_pallas_kernels"):
        return False
    if not _shapes_eligible(tuple(q.shape), jnp.dtype(q.dtype).name,
                            tuple(k.shape), bool(causal)):
        return False
    return _probe_lowering(jax.ShapeDtypeStruct(q.shape, q.dtype),
                           jax.ShapeDtypeStruct(k.shape, k.dtype), causal)


def attention(q, k, v, causal=True):
    """Fused attention with automatic fallback: Pallas flash kernels when
    they provably lower on this backend, else the XLA composition."""
    if use_flash(q, k, causal):
        return _flash_attention(bool(causal), q, k, v)
    return _ref_attention(q, k, v, causal)


class _FlashFwd:
    """Callable op with the centralized eligibility check."""

    def __call__(self, q, k, v, causal):
        return _flash_attention(bool(causal), q, k, v)

    @staticmethod
    def supports(shape, dtype_name, kv_shape=None, causal=True) -> bool:
        if not _shapes_eligible(shape, dtype_name, kv_shape, bool(causal)):
            return False
        import numpy as _np
        dt = jnp.bfloat16 if dtype_name == "bfloat16" else _np.dtype(dtype_name)
        kv = kv_shape if kv_shape is not None else shape
        return _probe_lowering(jax.ShapeDtypeStruct(tuple(shape), dt),
                               jax.ShapeDtypeStruct(tuple(kv), dt),
                               bool(causal))

    # identity used as the dispatch cache key
    def __hash__(self):
        return hash("pallas_flash_attention")

    def __eq__(self, other):
        return isinstance(other, _FlashFwd)


flash_attention_fwd = _FlashFwd()
