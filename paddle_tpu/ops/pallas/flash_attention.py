"""Flash attention (TPU Pallas).

TPU-native analog of the reference's FA2 CUDA kernel
(/root/reference/paddle/phi/kernels/gpu/flash_attn_kernel.cu wrapping
third_party/flashattn).  Forward is a Pallas online-softmax kernel tiled for
the MXU; backward falls back to XLA's fused attention gradient (jax.vjp over
the reference composition) — a custom_vjp pairs them.

Layout: [batch, seq, heads, head_dim] in, same out (matches paddle
flash_attention API).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    import jax.experimental.pallas.tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_BLOCK_Q = 128
_BLOCK_K = 128


def _ref_attention(q, k, v, causal):
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * s
    if causal:
        ql, kl = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, sm_scale, block_k, kv_len):
    # grid: (batch*heads, q_blocks); refs are [block_q, d] / [kv_len, d]
    q = q_ref[...].astype(jnp.float32) * sm_scale
    block_q, d = q.shape
    q_idx = pl.program_id(1)

    acc = jnp.zeros((block_q, d), jnp.float32)
    m_i = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l_i = jnp.zeros((block_q,), jnp.float32)

    num_k_blocks = kv_len // block_k

    def body(kb, carry):
        acc, m_i, l_i = carry
        k = k_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = q @ k.T  # [block_q, block_k]
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_i - m_new)
        l_new = alpha * l_i + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + p @ v
        return acc, m_new, l_new

    if causal:
        # only iterate over k blocks that intersect the causal band
        q_end = (q_idx.astype(jnp.int32) + jnp.int32(1)) * jnp.int32(block_q)
        hi = jnp.minimum(jnp.int32(num_k_blocks),
                         q_end // jnp.int32(block_k) + jnp.int32(1))
    else:
        hi = jnp.int32(num_k_blocks)
    acc, m_i, l_i = jax.lax.fori_loop(jnp.int32(0), hi, body, (acc, m_i, l_i))
    o_ref[...] = (acc / l_i[:, None]).astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, causal):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    sm_scale = 1.0 / math.sqrt(d)
    # flatten batch*heads; layout [BH, S, D]
    qr = jnp.swapaxes(q, 1, 2).reshape(b * h, sq, d)
    kr = jnp.swapaxes(k, 1, 2).reshape(b * h, sk, d)
    vr = jnp.swapaxes(v, 1, 2).reshape(b * h, sk, d)

    block_q = min(_BLOCK_Q, sq)
    block_k = min(_BLOCK_K, sk)

    kernel = functools.partial(_fwd_kernel, causal=causal, sm_scale=sm_scale,
                               block_k=block_k, kv_len=sk)
    # NB: x64 mode promotes literal 0 to i64, which Mosaic rejects in the
    # index-map return tuple; derive an i32 zero from the grid index instead.
    def _q_map(bh, qb):
        return (bh, qb, qb - qb)

    def _kv_map(bh, qb):
        return (bh, qb - qb, qb - qb)

    out = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), _q_map),
            pl.BlockSpec((None, sk, d), _kv_map),
            pl.BlockSpec((None, sk, d), _kv_map),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), _q_map),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
    )(qr, kr, vr)
    return jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_attention(causal, q, k, v):
    return _flash_fwd_pallas(q, k, v, causal)


def _flash_fwd_rule(causal, q, k, v):
    out = _flash_fwd_pallas(q, k, v, causal)
    return out, (q, k, v)


def _flash_bwd_rule(causal, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _ref_attention(q, k, v, causal), q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


class _FlashFwd:
    """Callable op with a static shape-eligibility check."""

    def __call__(self, q, k, v, causal):
        return _flash_attention(bool(causal), q, k, v)

    @staticmethod
    def supports(shape, dtype_name) -> bool:
        if not _HAS_PALLAS:
            return False
        if jax.default_backend() not in ("tpu",):
            return False
        if len(shape) != 4:
            return False
        b, s, h, d = shape
        if d % 128 != 0 and d not in (64, 128, 256):
            return False
        return s % 128 == 0 and dtype_name in ("float32", "bfloat16")

    # identity used as the dispatch cache key
    def __hash__(self):
        return hash("pallas_flash_attention")

    def __eq__(self, other):
        return isinstance(other, _FlashFwd)


flash_attention_fwd = _FlashFwd()
