"""Varlen (unpadded) flash attention — TPU Pallas, forward and backward.

TPU-native analog of the reference's FA2 varlen path
(/root/reference/paddle/phi/kernels/gpu/flash_attn_kernel.cu FlashAttnUnpadded
+ python/paddle/nn/functional/flash_attention.py:756 flash_attn_unpadded):
concatenated sequences [total_tokens, heads, head_dim] with cu_seqlens
offsets, no O(S^2) score materialization.

Design: segment-ids (the splash-attention idiom) instead of the CUDA
kernel's per-sequence grid — every token carries (segment, position-in-
segment); the online-softmax kernels mask cross-segment pairs, and per-block
[lo, hi) kv-ranges are precomputed with XLA and handed to the kernels via
scalar prefetch (SMEM), so compute stays O(sum s_i^2) like FA2-varlen, not
O(T^2).  Total-token counts are padded to the 128 lane quantum with a
sentinel segment that matches nothing.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from . import flash_attention as _fa
from .flash_attention import _dot_f32, _pick_block

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_NEG_INF = float("-inf")


# ---------------------------------------------------------------------------
# Host-side (XLA) segment metadata
# ---------------------------------------------------------------------------

def _segment_meta(cu, total, pad_to, pad_seg):
    """seg[pad_to] (pad rows get pad_seg), rel[pad_to], both int32."""
    pos = jnp.arange(pad_to, dtype=jnp.int32)
    seg = jnp.searchsorted(cu.astype(jnp.int32), pos, side="right") - 1
    seg = jnp.where(pos < total, seg, pad_seg)
    rel = pos - cu.astype(jnp.int32)[jnp.clip(seg, 0, cu.shape[0] - 2)]
    return seg, rel


def _block_bounds_q(seg_q, rel_q, cu_k, block_q, block_k, nkb, causal):
    """Per-q-block kv row-range -> block range [lo_b, hi_b) (int32 [nqb])."""
    cu_k = cu_k.astype(jnp.int32)
    nseq = cu_k.shape[0] - 1
    valid = seg_q < nseq                          # pad rows contribute nothing
    seg_c = jnp.clip(seg_q, 0, nseq - 1)
    row_lo = jnp.where(valid, cu_k[seg_c], jnp.int32(2 ** 30))
    if causal:
        row_hi = jnp.where(valid, cu_k[seg_c] + rel_q + 1, 0)
    else:
        row_hi = jnp.where(valid, cu_k[seg_c + 1], 0)
    nqb = seg_q.shape[0] // block_q
    lo = jnp.min(row_lo.reshape(nqb, block_q), axis=1) // block_k
    hi = -(-jnp.max(row_hi.reshape(nqb, block_q), axis=1) // block_k)
    lo = jnp.clip(lo, 0, nkb)
    hi = jnp.clip(hi, lo, nkb)
    return lo.astype(jnp.int32), hi.astype(jnp.int32)


def _block_bounds_k(seg_k, rel_k, cu_q, block_q, block_k, nqb, causal):
    """Per-k-block q row-range -> block range [lo_b, hi_b) (int32 [nkb])."""
    cu_q = cu_q.astype(jnp.int32)
    nseq = cu_q.shape[0] - 1
    valid = seg_k < nseq
    seg_c = jnp.clip(seg_k, 0, nseq - 1)
    if causal:
        row_lo = jnp.where(valid, cu_q[seg_c] + rel_k, jnp.int32(2 ** 30))
    else:
        row_lo = jnp.where(valid, cu_q[seg_c], jnp.int32(2 ** 30))
    row_hi = jnp.where(valid, cu_q[seg_c + 1], 0)
    nkb = seg_k.shape[0] // block_k
    lo = jnp.min(row_lo.reshape(nkb, block_k), axis=1) // block_q
    hi = -(-jnp.max(row_hi.reshape(nkb, block_k), axis=1) // block_q)
    lo = jnp.clip(lo, 0, nqb)
    hi = jnp.clip(hi, lo, nqb)
    return lo.astype(jnp.int32), hi.astype(jnp.int32)


def _pad_tokens(x, pad_to):
    t = x.shape[0]
    if t == pad_to:
        return x
    return jnp.pad(x, ((0, pad_to - t),) + ((0, 0),) * (x.ndim - 1))


# ---------------------------------------------------------------------------
# Kernels.  Layout inside: q/k/v [H, T, D]; seg/rel [1, T] int32.
# Scalar-prefetch: lo_b/hi_b per grid block.
# ---------------------------------------------------------------------------

def _vfwd_kernel(lo_ref, hi_ref, q_ref, k_ref, v_ref, sq_ref, rq_ref,
                 sk_ref, rk_ref, o_ref, lse_ref, *, sm_scale, block_k,
                 causal):
    q = q_ref[...]
    block_q, d = q.shape
    qb = pl.program_id(1)

    acc = jnp.zeros((block_q, d), jnp.float32)
    m_i = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l_i = jnp.zeros((block_q,), jnp.float32)
    seg_q = sq_ref[0, :]
    rel_q = rq_ref[0, :]

    def body(kb, carry):
        acc, m_i, l_i = carry
        k = k_ref[pl.dslice(kb * block_k, block_k), :]
        v = v_ref[pl.dslice(kb * block_k, block_k), :]
        seg_k = sk_ref[0, pl.dslice(kb * block_k, block_k)]
        rel_k = rk_ref[0, pl.dslice(kb * block_k, block_k)]
        s = _dot_f32(q, k, ((1,), (1,))) * sm_scale
        ok = seg_q[:, None] == seg_k[None, :]
        if causal:
            ok &= rel_q[:, None] >= rel_k[None, :]
        s = jnp.where(ok, s, _NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=1))
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe[:, None])           # masked entries -> 0
        alpha = jnp.where(jnp.isneginf(m_i), 0.0, jnp.exp(m_i - m_safe))
        l_new = alpha * l_i + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + _dot_f32(p.astype(v.dtype), v,
                                              ((1,), (0,)))
        return acc, m_new, l_new

    acc, m_i, l_i = jax.lax.fori_loop(lo_ref[qb], hi_ref[qb], body,
                                      (acc, m_i, l_i))
    has = l_i > 0.0
    o_ref[...] = jnp.where(has[:, None], acc / jnp.where(has, l_i, 1.0)[:, None],
                           0.0).astype(o_ref.dtype)
    lse_ref[...] = jnp.where(has, m_i + jnp.log(jnp.where(has, l_i, 1.0)),
                             _NEG_INF)[None, :]


def _vbwd_dkdv_kernel(lo_ref, hi_ref, q_ref, do_ref, lse_ref, delta_ref,
                      sq_ref, rq_ref, k_ref, v_ref, sk_ref, rk_ref,
                      dk_ref, dv_ref, *, sm_scale, block_q, causal):
    k = k_ref[...]
    v = v_ref[...]
    block_k, d = k.shape
    kb = pl.program_id(1)
    seg_k = sk_ref[0, :]
    rel_k = rk_ref[0, :]

    dk = jnp.zeros((block_k, d), jnp.float32)
    dv = jnp.zeros((block_k, d), jnp.float32)

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[pl.dslice(qb * block_q, block_q), :]
        do = do_ref[pl.dslice(qb * block_q, block_q), :]
        lse = lse_ref[0, pl.dslice(qb * block_q, block_q)]
        delta = delta_ref[0, pl.dslice(qb * block_q, block_q)]
        seg_q = sq_ref[0, pl.dslice(qb * block_q, block_q)]
        rel_q = rq_ref[0, pl.dslice(qb * block_q, block_q)]
        st = _dot_f32(k, q, ((1,), (1,))) * sm_scale   # [block_k, block_q]
        ok = seg_k[:, None] == seg_q[None, :]
        if causal:
            ok &= rel_q[None, :] >= rel_k[:, None]
        st = jnp.where(ok, st, _NEG_INF)
        lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
        pt = jnp.exp(st - lse_safe[None, :])           # masked -> 0
        ptc = pt.astype(do.dtype)
        dv = dv + _dot_f32(ptc, do, ((1,), (0,)))
        dpt = _dot_f32(v, do, ((1,), (1,)))
        dst = pt * (dpt - delta[None, :]) * sm_scale
        dk = dk + _dot_f32(dst.astype(q.dtype), q, ((1,), (0,)))
        return dk, dv

    dk, dv = jax.lax.fori_loop(lo_ref[kb], hi_ref[kb], body, (dk, dv))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _vbwd_dq_kernel(lo_ref, hi_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    q_ref, sq_ref, rq_ref, sk_ref, rk_ref, dq_ref, *,
                    sm_scale, block_k, causal):
    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[0, :]
    delta = delta_ref[0, :]
    seg_q = sq_ref[0, :]
    rel_q = rq_ref[0, :]
    block_q, d = q.shape
    qb = pl.program_id(1)

    dq = jnp.zeros((block_q, d), jnp.float32)
    lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)

    def body(kb, dq):
        k = k_ref[pl.dslice(kb * block_k, block_k), :]
        v = v_ref[pl.dslice(kb * block_k, block_k), :]
        seg_k = sk_ref[0, pl.dslice(kb * block_k, block_k)]
        rel_k = rk_ref[0, pl.dslice(kb * block_k, block_k)]
        s = _dot_f32(q, k, ((1,), (1,))) * sm_scale
        ok = seg_q[:, None] == seg_k[None, :]
        if causal:
            ok &= rel_q[:, None] >= rel_k[None, :]
        s = jnp.where(ok, s, _NEG_INF)
        p = jnp.exp(s - lse_safe[:, None])             # masked -> 0
        dp = _dot_f32(do, v, ((1,), (1,)))
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + _dot_f32(ds.astype(k.dtype), k, ((1,), (0,)))

    dq = jax.lax.fori_loop(lo_ref[qb], hi_ref[qb], body, dq)
    dq_ref[...] = dq.astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------

# Shared index maps over grid (head, block) + 2 prefetch refs (ignored):
# positioned blocks along the token dim vs whole-array blocks.
def _map_blk(hh, b, lo, hi):      # [H, T, D] block b along tokens
    return (hh, b, 0)


def _map_full(hh, b, lo, hi):     # [H, T, D] whole token dim
    return (hh, 0, 0)


def _map_vec_blk(hh, b, lo, hi):  # [1, T] int vectors, block b
    return (0, b)


def _map_vec_full(hh, b, lo, hi):
    return (0, 0)


def _map_hvec_blk(hh, b, lo, hi):  # [H, 1, T] lse/delta, block b
    return (hh, 0, b)



def _prep(q, k, v, cu_q, cu_k, causal):
    tq, h, d = q.shape
    tk = k.shape[0]
    nseq = cu_q.shape[0] - 1
    from ...tune import kernel_config
    cfg = kernel_config("flash_attention_varlen",
                        {"seq_q": tq, "seq_k": tk, "head_dim": d,
                         "dtype": jnp.dtype(q.dtype).name})
    block_q = _pick_block(max(128, -(-tq // 128) * 128), int(cfg["block_q"]))
    block_k = _pick_block(max(128, -(-tk // 128) * 128), int(cfg["block_k"]))
    pad_q = -(-tq // block_q) * block_q
    pad_k = -(-tk // block_k) * block_k
    # sentinel segments: q pads get nseq, k pads nseq+1 -> never equal
    seg_q, rel_q = _segment_meta(cu_q, tq, pad_q, nseq)
    seg_k, rel_k = _segment_meta(cu_k, tk, pad_k, nseq + 1)
    qr = jnp.swapaxes(_pad_tokens(q, pad_q), 0, 1)       # [H, Tq, D]
    kr = jnp.swapaxes(_pad_tokens(k, pad_k), 0, 1)
    vr = jnp.swapaxes(_pad_tokens(v, pad_k), 0, 1)
    return (qr, kr, vr, seg_q[None], rel_q[None], seg_k[None], rel_k[None],
            block_q, block_k, pad_q, pad_k, tq, h, d)


def _varlen_fwd(q, k, v, cu_q, cu_k, causal, sm_scale):
    (qr, kr, vr, sq, rq, sk, rk, block_q, block_k, pad_q, pad_k,
     tq, h, d) = _prep(q, k, v, cu_q, cu_k, causal)
    nqb, nkb = pad_q // block_q, pad_k // block_k
    lo, hi = _block_bounds_q(sq[0], rq[0], cu_k, block_q, block_k, nkb,
                             causal)

    kernel = functools.partial(_vfwd_kernel, sm_scale=sm_scale,
                               block_k=block_k, causal=causal)
    grid = (h, nqb)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((None, block_q, d), _map_blk),
                pl.BlockSpec((None, pad_k, d), _map_full),
                pl.BlockSpec((None, pad_k, d), _map_full),
                pl.BlockSpec((1, block_q), _map_vec_blk),      # seg_q
                pl.BlockSpec((1, block_q), _map_vec_blk),      # rel_q
                pl.BlockSpec((1, pad_k), _map_vec_full),        # seg_k
                pl.BlockSpec((1, pad_k), _map_vec_full),        # rel_k
            ],
            out_specs=[
                pl.BlockSpec((None, block_q, d), _map_blk),
                pl.BlockSpec((None, 1, block_q),
                             _map_hvec_blk),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((h, pad_q, d), q.dtype),
            jax.ShapeDtypeStruct((h, 1, pad_q), jnp.float32),
        ],
        interpret=_fa.INTERPRET,
    )(lo, hi, qr, kr, vr, sq, rq, sk, rk)
    return jnp.swapaxes(out, 0, 1)[:tq], lse


def _varlen_bwd(q, k, v, out, lse, g, cu_q, cu_k, causal, sm_scale):
    (qr, kr, vr, sq, rq, sk, rk, block_q, block_k, pad_q, pad_k,
     tq, h, d) = _prep(q, k, v, cu_q, cu_k, causal)
    tk = k.shape[0]
    nqb, nkb = pad_q // block_q, pad_k // block_k
    dor = jnp.swapaxes(_pad_tokens(g, pad_q), 0, 1)
    outr = jnp.swapaxes(_pad_tokens(out, pad_q), 0, 1)
    delta = jnp.sum(dor.astype(jnp.float32) * outr.astype(jnp.float32),
                    axis=-1)[:, None, :]             # [H, 1, pad_q]

    # ---- dk/dv over k blocks
    lo_k, hi_k = _block_bounds_k(sk[0], rk[0], cu_q, block_q, block_k, nqb,
                                 causal)
    dk, dv = pl.pallas_call(
        functools.partial(_vbwd_dkdv_kernel, sm_scale=sm_scale,
                          block_q=block_q, causal=causal),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(h, nkb),
            in_specs=[
                pl.BlockSpec((None, pad_q, d), _map_full),   # q
                pl.BlockSpec((None, pad_q, d), _map_full),   # do
                pl.BlockSpec((None, 1, pad_q), _map_full),    # lse
                pl.BlockSpec((None, 1, pad_q), _map_full),    # delta
                pl.BlockSpec((1, pad_q), _map_vec_full),           # seg_q
                pl.BlockSpec((1, pad_q), _map_vec_full),           # rel_q
                pl.BlockSpec((None, block_k, d), _map_blk),  # k
                pl.BlockSpec((None, block_k, d), _map_blk),  # v
                pl.BlockSpec((1, block_k), _map_vec_blk),         # seg_k
                pl.BlockSpec((1, block_k), _map_vec_blk),         # rel_k
            ],
            out_specs=[
                pl.BlockSpec((None, block_k, d), _map_blk),
                pl.BlockSpec((None, block_k, d), _map_blk),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((h, pad_k, d), jnp.float32),
            jax.ShapeDtypeStruct((h, pad_k, d), jnp.float32),
        ],
        interpret=_fa.INTERPRET,
    )(lo_k, hi_k, qr, dor, lse, delta, sq, rq, kr, vr, sk, rk)
    dk = jnp.swapaxes(dk, 0, 1)[:tk].astype(k.dtype)
    dv = jnp.swapaxes(dv, 0, 1)[:tk].astype(v.dtype)

    # ---- dq over q blocks
    lo_q, hi_q = _block_bounds_q(sq[0], rq[0], cu_k, block_q, block_k, nkb,
                                 causal)
    dq = pl.pallas_call(
        functools.partial(_vbwd_dq_kernel, sm_scale=sm_scale,
                          block_k=block_k, causal=causal),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(h, nqb),
            in_specs=[
                pl.BlockSpec((None, pad_k, d), _map_full),   # k
                pl.BlockSpec((None, pad_k, d), _map_full),   # v
                pl.BlockSpec((None, block_q, d), _map_blk),  # do
                pl.BlockSpec((None, 1, block_q),
                             _map_hvec_blk),  # lse
                pl.BlockSpec((None, 1, block_q),
                             _map_hvec_blk),  # delta
                pl.BlockSpec((None, block_q, d), _map_blk),  # q
                pl.BlockSpec((1, block_q), _map_vec_blk),          # seg_q
                pl.BlockSpec((1, block_q), _map_vec_blk),          # rel_q
                pl.BlockSpec((1, pad_k), _map_vec_full),            # seg_k
                pl.BlockSpec((1, pad_k), _map_vec_full),            # rel_k
            ],
            out_specs=pl.BlockSpec((None, block_q, d), _map_blk),
        ),
        out_shape=jax.ShapeDtypeStruct((h, pad_q, d), q.dtype),
        interpret=_fa.INTERPRET,
    )(lo_q, hi_q, kr, vr, dor, lse, delta, qr, sq, rq, sk, rk)
    dq = jnp.swapaxes(dq, 0, 1)[:q.shape[0]]
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp + eligibility
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _varlen_attention(causal, sm_scale, q, k, v, cu_q, cu_k):
    out, _ = _varlen_fwd(q, k, v, cu_q, cu_k, causal, sm_scale)
    return out


def _varlen_fwd_rule(causal, sm_scale, q, k, v, cu_q, cu_k):
    out, lse = _varlen_fwd(q, k, v, cu_q, cu_k, causal, sm_scale)
    return out, (q, k, v, out, lse, cu_q, cu_k)


def _varlen_bwd_rule(causal, sm_scale, res, g):
    q, k, v, out, lse, cu_q, cu_k = res
    dq, dk, dv = _varlen_bwd(q, k, v, out, lse, g, cu_q, cu_k, causal,
                             sm_scale)
    return dq, dk, dv, None, None


_varlen_attention.defvjp(_varlen_fwd_rule, _varlen_bwd_rule)

_PROBE_CACHE: dict = {}


def use_varlen_flash(q, k, causal) -> bool:
    """Eligibility + one-time lowering probe (same policy as the fixed-shape
    kernel, flash_attention.py:use_flash): flag + shape rules + compile
    probe with XLA-composition fallback on failure."""
    from ...core.flags import get_flag
    if not _HAS_PALLAS or not get_flag("use_pallas_kernels"):
        return False
    if jax.default_backend() != "tpu" and not _fa.INTERPRET:
        return False
    if q.ndim != 3 or k.ndim != 3 or q.shape[2] != k.shape[2]:
        return False
    if q.shape[1] != k.shape[1]:      # GQA via composition fallback
        return False
    if q.shape[2] not in (64, 128, 256):
        return False
    if jnp.dtype(q.dtype).name not in ("float32", "bfloat16"):
        return False
    if _fa.INTERPRET:
        return True
    key = (tuple(q.shape), tuple(k.shape), str(q.dtype), bool(causal))
    hit = _PROBE_CACHE.get(key)
    if hit is not None:
        return hit
    try:
        sm = 1.0 / math.sqrt(q.shape[-1])
        nseq = 2
        q_s = jax.ShapeDtypeStruct(q.shape, q.dtype)
        k_s = jax.ShapeDtypeStruct(k.shape, k.dtype)
        cu = jax.ShapeDtypeStruct((nseq + 1,), jnp.int32)

        def fwd_bwd(q, k, v, cq, ck, g):
            out, vjp = jax.vjp(
                lambda q_, k_, v_: _varlen_attention(causal, sm, q_, k_, v_,
                                                     cq, ck), q, k, v)
            return out, vjp(g)

        jax.jit(fwd_bwd).lower(q_s, k_s, k_s, cu, cu, q_s).compile()
        ok = True
    except Exception as e:
        ok = False
        import logging
        logging.getLogger("paddle_tpu").warning(
            "varlen flash attention failed to lower for q=%s (causal=%s): "
            "%s -- falling back to the XLA composition",
            q.shape, causal, str(e)[:300])
    _PROBE_CACHE[key] = ok
    return ok
