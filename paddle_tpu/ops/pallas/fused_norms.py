"""Fused RMSNorm / LayerNorm (TPU Pallas).

TPU-native analog of the reference fused norm CUDA kernels
(/root/reference/paddle/phi/kernels/fusion/gpu/fused_rms_norm*.cu and
fused_layernorm*.cu, exposed via python/paddle/incubate/nn/functional/
fused_rms_norm.py / fused_layer_norm.py).  Forward is a row-tiled Pallas
kernel (single HBM pass, fp32 accumulation in VMEM); backward pairs it with
XLA's fused gradient of the reference composition via custom_vjp — same
structure as ops/pallas/flash_attention.py.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_BLOCK_R = 256  # built-in preference; the tuning cache can widen/narrow it


def _rms_ref(x, w, eps):
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (y * w.astype(jnp.float32)).astype(x.dtype)


def _ln_ref(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=1, keepdims=True)
    o_ref[...] = (x * jax.lax.rsqrt(ms + eps) * w[None, :]).astype(o_ref.dtype)


def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=1, keepdims=True)
    var = jnp.mean((x - mu) ** 2, axis=1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + eps)
    o_ref[...] = (y * w[None, :] + b[None, :]).astype(o_ref.dtype)


def _pick_block_r(R, pref=None):
    """Largest power-of-two block <= pref that exactly divides R.

    The grid is R // block_r with no ragged-tail masking, so block_r MUST
    divide R; _supports guarantees R % 8 == 0, making 8 the floor here.
    """
    pref = _BLOCK_R if pref is None else pref
    for b in (1024, 512, 256, 128, 64, 32, 16, 8):
        if b <= pref and R % b == 0:
            return b
    return None


def _row_call(kernel, out_dtype, x2d, *vecs):
    from ...tune import kernel_config
    R, H = x2d.shape
    cfg = kernel_config("fused_norms",
                        {"rows": R, "hidden": H,
                         "dtype": jnp.dtype(x2d.dtype).name})
    block_r = _pick_block_r(R, int(cfg["block_r"]))
    vec_specs = [pl.BlockSpec((H,), lambda r: (0,)) for _ in vecs]
    return pl.pallas_call(
        kernel,
        grid=(R // block_r,),
        in_specs=[pl.BlockSpec((block_r, H), lambda r: (r, 0))] + vec_specs,
        out_specs=pl.BlockSpec((block_r, H), lambda r: (r, 0)),
        out_shape=jax.ShapeDtypeStruct((R, H), out_dtype),
    )(x2d, *vecs)


def _supports(shape, dtype_name):
    if not _HAS_PALLAS or jax.default_backend() != "tpu":
        return False
    if dtype_name not in ("float32", "bfloat16"):
        return False
    H = shape[-1]
    rows = 1
    for s in shape[:-1]:
        rows *= s
    return H % 128 == 0 and rows % 8 == 0 and _pick_block_r(rows) is not None


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _rms_pallas(eps, x, w):
    shape = x.shape
    y = _row_call(functools.partial(_rms_kernel, eps=eps), x.dtype,
                  x.reshape(-1, shape[-1]), w)
    return y.reshape(shape)


def _rms_fwd(eps, x, w):
    return _rms_pallas(eps, x, w), (x, w)


def _rms_bwd(eps, res, g):
    x, w = res
    _, vjp = jax.vjp(lambda x, w: _rms_ref(x, w, eps), x, w)
    return vjp(g)


_rms_pallas.defvjp(_rms_fwd, _rms_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ln_pallas(eps, x, w, b):
    shape = x.shape
    y = _row_call(functools.partial(_ln_kernel, eps=eps), x.dtype,
                  x.reshape(-1, shape[-1]), w, b)
    return y.reshape(shape)


def _ln_fwd(eps, x, w, b):
    return _ln_pallas(eps, x, w, b), (x, w, b)


def _ln_bwd(eps, res, g):
    x, w, b = res
    _, vjp = jax.vjp(lambda x, w, b: _ln_ref(x, w, b, eps), x, w, b)
    return vjp(g)


_ln_pallas.defvjp(_ln_fwd, _ln_bwd)


# One-time compile probe per (op, shape, dtype): a shape heuristic alone let
# a Mosaic-illegal kernel reach the r2 bench — the authoritative eligibility
# check is an actual lowering (same policy as flash_attention._probe_lowering).
_PROBE_CACHE: dict = {}


def _probe(tag, fn, *sds) -> bool:
    key = (tag,) + tuple((tuple(s.shape), str(s.dtype)) for s in sds) \
        + (jax.default_backend(),)
    hit = _PROBE_CACHE.get(key)
    if hit is not None:
        return hit
    try:
        jax.jit(fn).lower(*sds).compile()
        ok = True
    except Exception as e:
        ok = False
        import logging
        logging.getLogger("paddle_tpu").warning(
            "Pallas %s failed to lower for %s: %s -- using XLA fallback",
            tag, [s.shape for s in sds], str(e)[:300])
    _PROBE_CACHE[key] = ok
    return ok


def _np_dt(name):
    return jnp.bfloat16 if name == "bfloat16" else np.dtype(name)


class _RmsNormOp:
    def __call__(self, x, w, eps):
        return _rms_pallas(float(eps), x, w)

    @staticmethod
    def supports(shape, dtype_name, w_dtype_name=None):
        if not _supports(shape, dtype_name):
            return False
        x = jax.ShapeDtypeStruct(tuple(shape), _np_dt(dtype_name))
        # probe with the ACTUAL weight dtype — master-weight setups keep the
        # norm weight fp32 against bf16 activations, a different lowering
        w = jax.ShapeDtypeStruct((shape[-1],),
                                 _np_dt(w_dtype_name or dtype_name))
        return _probe("rms_norm", lambda x, w: _rms_pallas(1e-6, x, w), x, w)

    def __hash__(self):
        return hash("pallas_rms_norm")

    def __eq__(self, other):
        return isinstance(other, _RmsNormOp)


class _LayerNormOp:
    def __call__(self, x, w, b, eps):
        return _ln_pallas(float(eps), x, w, b)

    @staticmethod
    def supports(shape, dtype_name, w_dtype_name=None):
        if not _supports(shape, dtype_name):
            return False
        x = jax.ShapeDtypeStruct(tuple(shape), _np_dt(dtype_name))
        v = jax.ShapeDtypeStruct((shape[-1],),
                                 _np_dt(w_dtype_name or dtype_name))
        return _probe("layer_norm",
                      lambda x, w, b: _ln_pallas(1e-6, x, w, b), x, v, v)

    def __hash__(self):
        return hash("pallas_layer_norm")

    def __eq__(self, other):
        return isinstance(other, _LayerNormOp)


rms_norm_fused = _RmsNormOp()
layer_norm_fused = _LayerNormOp()
