"""Pallas paged-KV decode attention (TPU).

The serving decode step attends one fresh query token per sequence against
that sequence's KV cache, which lives in non-contiguous fixed-size pages
addressed by a block table (the reference's paged CUDA decode kernel,
/root/reference/paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu
-> block_attn.h).  The XLA composition must first GATHER every sequence's
pages into a dense [B, nblk*bs] buffer — O(B * max_len) HBM traffic twice
(gather + read).  This kernel instead walks the block table with Pallas
scalar prefetch: the grid's page dimension indexes `block_tables[b, i]`
directly in each page's BlockSpec index map, so pages stream from HBM to
VMEM exactly once, with no dense intermediate.

Layout: caches are [num_blocks, H_kv, bs, D] (blha cache layout), the
query is [B, H, D], block table [B, nblk] int32, lengths [B] int32 (count
of valid positions per sequence AFTER the current token's k/v insert).
GQA is native: grid runs over kv heads, each kernel instance carries the
q-head group [G, D] so the [G, bs] score tile keeps the MXU busy.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tri-state interpret override.  None (default) resolves per-backend:
# interpret everywhere except a real TPU, so kernel entry points work on
# CPU without mutating this global.  Tests that need a forced mode (the
# fixture in tests/test_paged_attention.py) may still assign True/False
# here and restore the old value after.  NOTE the serving engine does
# NOT ride the auto-resolved interpret mode: interpreted decode costs a
# Python step per (B, H_kv, nblk) grid cell, so LLMEngine uses the XLA
# reference path off-TPU unless INTERPRET is explicitly True.
INTERPRET = None


def interpret_mode() -> bool:
    """Resolved interpret flag: the module override wins when set."""
    if INTERPRET is None:
        return jax.default_backend() != "tpu"
    return bool(INTERPRET)


def _decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, bs, sm_scale):
    """grid (B, H_kv, nblk); refs: q [G, D], k/v [bs, D] (one page of one
    kv head), o [G, D]; scratch m/l [G, 1] f32, acc [G, D] f32."""
    b = pl.program_id(0)
    i = pl.program_id(2)
    nblk = pl.num_programs(2)
    seq_len = len_ref[b]                      # valid positions this seq

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    base = i * bs

    @pl.when(base < seq_len)
    def _tile():
        q = (q_ref[...].astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
        k = k_ref[...]                         # [bs, D]
        v = v_ref[...]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [G, bs]
        pos = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < seq_len, s, -jnp.inf)
        m_prev = m_ref[...]                    # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                 # [G, bs]
        alpha = jnp.exp(m_prev - m_new)        # [G, 1]
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(i == nblk - 1)
    def _finalize():
        o_ref[...] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def paged_decode_attention(q, key_cache, value_cache, block_tables,
                           lengths):
    """One-token-per-sequence decode over paged KV.

    q [B, H, D]; caches [num_blocks, H_kv, bs, D]; block_tables [B, nblk]
    int32; lengths [B] int32 (valid positions incl. the fresh token).
    Returns [B, H, D].
    """
    B, H, D = q.shape
    _, Hkv, bs, _ = key_cache.shape
    G = H // Hkv
    nblk = block_tables.shape[1]
    sm_scale = 1.0 / (D ** 0.5)

    kernel = functools.partial(_decode_kernel, bs=bs, sm_scale=sm_scale)
    # q rows for kv head h are h*G..(h+1)*G: block (1, G, D) at index (b, h)
    qr = q.reshape(B, Hkv, G, D)
    # the grid DMAs a page per table entry even past each sequence's
    # length (compute is skipped, the copy is not): clamp the reference
    # blha convention's -1 padding entries to a valid block index
    block_tables = jnp.clip(block_tables, 0, key_cache.shape[0] - 1)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,             # block_tables, lengths
            grid=(B, Hkv, nblk),
            in_specs=[
                pl.BlockSpec((None, None, G, D),
                             lambda b, h, i, bt, ln: (b, h, 0, 0)),
                pl.BlockSpec((None, None, bs, D),
                             lambda b, h, i, bt, ln: (bt[b, i], h, 0, 0)),
                pl.BlockSpec((None, None, bs, D),
                             lambda b, h, i, bt, ln: (bt[b, i], h, 0, 0)),
            ],
            out_specs=pl.BlockSpec((None, None, G, D),
                                   lambda b, h, i, bt, ln: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, D), q.dtype),
        interpret=interpret_mode(),
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      qr, key_cache, value_cache)
    return out.reshape(B, H, D)


def paged_decode_reference(q, key_cache, value_cache, block_tables,
                           lengths):
    """Dense-gather XLA oracle (the pre-r5 decode path's math)."""
    B, H, D = q.shape
    _, Hkv, bs, _ = key_cache.shape
    kpages = key_cache[block_tables]           # [B, nblk, Hkv, bs, D]
    vpages = value_cache[block_tables]
    ks = jnp.moveaxis(kpages, 2, 1).reshape(B, Hkv, -1, D)
    vs = jnp.moveaxis(vpages, 2, 1).reshape(B, Hkv, -1, D)
    if Hkv != H:
        g = H // Hkv
        ks = jnp.repeat(ks, g, axis=1)
        vs = jnp.repeat(vs, g, axis=1)
    scores = jnp.einsum("bhd,bhmd->bhm", q.astype(jnp.float32),
                        ks.astype(jnp.float32)) / jnp.sqrt(jnp.float32(D))
    pos = jnp.arange(ks.shape[2])[None, None, :]
    scores = jnp.where(pos < lengths[:, None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhm,bhmd->bhd", probs, vs.astype(jnp.float32))
    return out.astype(q.dtype)


_PROBE_CACHE: dict = {}
_PROBE_LOGGED = False


def _probe_lowering(B, H, Hkv, D, bs, nblk, dtype) -> bool:
    """Compile-probe the decode kernel for these shapes.

    The authoritative eligibility check is an actual lowering (the r2
    bench died on a heuristic yes / Mosaic no — flash_attention.py:453);
    returns False on any failure so callers degrade to the dense-gather
    XLA path instead of crashing every serving decode step.
    """
    global _PROBE_LOGGED
    key = (B, H, Hkv, D, bs, nblk, str(dtype), jax.default_backend())
    hit = _PROBE_CACHE.get(key)
    if hit is not None:
        return hit
    if interpret_mode():  # interpreter enforces no TPU tiling rules
        _PROBE_CACHE[key] = True
        return True
    num_blocks = max(nblk * B, 1)
    try:
        jax.jit(paged_decode_attention).lower(
            jax.ShapeDtypeStruct((B, H, D), dtype),
            jax.ShapeDtypeStruct((num_blocks, Hkv, bs, D), dtype),
            jax.ShapeDtypeStruct((num_blocks, Hkv, bs, D), dtype),
            jax.ShapeDtypeStruct((B, nblk), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
        ).compile()
        ok = True
    except Exception as e:
        ok = False
        if not _PROBE_LOGGED:
            _PROBE_LOGGED = True
            import logging
            logging.getLogger("paddle_tpu.pallas").warning(
                "paged decode kernel does not lower for "
                f"B={B} H={H} Hkv={Hkv} D={D} bs={bs}: "
                f"{type(e).__name__}; falling back to dense gather")
    _PROBE_CACHE[key] = ok
    return ok


def supports(B, H, Hkv, D, bs, nblk=None, dtype=jnp.float32) -> bool:
    """Eligibility for the pallas decode kernel: shape heuristic, then an
    actual lowering probe (cached)."""
    if H % Hkv != 0:
        return False
    if D % 128 != 0 and D not in (64,):
        return False
    if bs % 8 != 0:
        return False
    if nblk is None:
        return True     # shape-only query (no probe possible yet)
    return _probe_lowering(B, H, Hkv, D, bs, nblk, dtype)
