"""Pallas paged-KV attention (TPU): ragged serving kernel + decode kernel.

The serving step attends query tokens against KV caches that live in
non-contiguous fixed-size pages addressed by block tables (the reference's
paged CUDA decode kernel,
/root/reference/paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu
-> block_attn.h).  The XLA composition must first GATHER every sequence's
pages into a dense [B, nblk*bs] buffer — O(B * max_len) HBM traffic twice
(gather + read).  These kernels instead walk the block table with Pallas
scalar prefetch: the grid's page dimension indexes the block table
directly in each page's BlockSpec index map, so pages stream from HBM to
VMEM exactly once, with no dense intermediate.

`ragged_paged_attention` is the serving workhorse (arxiv 2604.15464): the
grid runs over FLAT query tokens, each token resolves its owning row via
`cu_seqlens` and masks keys at its absolute position — so a prefill
chunk, a resumed chunk, a single decode token, and a k-draft verify row
are all just rows with different query lengths, served by ONE program.
`paged_decode_attention` is the original one-token-per-row special case,
kept for the incubating blha path and as a second oracle.

Layout: caches are [num_blocks, H_kv, bs, D] (blha cache layout), block
tables int32, per-row lengths int32.  GQA is native: grid runs over kv
heads, each kernel instance carries the q-head group [G, D] so the
[G, bs] score tile keeps the MXU busy.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tri-state interpret override.  None (default) resolves per-backend:
# interpret everywhere except a real TPU, so kernel entry points work on
# CPU without mutating this global.  Tests that need a forced mode (the
# fixture in tests/test_paged_attention.py) may still assign True/False
# here and restore the old value after.  NOTE the serving engine does
# NOT ride the auto-resolved interpret mode: interpreted decode costs a
# Python step per (B, H_kv, nblk) grid cell, so LLMEngine uses the XLA
# reference path off-TPU unless INTERPRET is explicitly True.
INTERPRET = None


def interpret_mode() -> bool:
    """Resolved interpret flag: the module override wins when set."""
    if INTERPRET is None:
        return jax.default_backend() != "tpu"
    return bool(INTERPRET)


def _pages_per_step(tq, kv_heads, head_dim, page, nblk, dtype):
    """Trace-time tuned page-walk width for the paged kernels.

    The tuned value only widens the innermost grid step — pages are
    still visited in the same ascending order, so the online-softmax
    accumulation (and therefore every output byte) is invariant; only
    the launch-overhead amortization changes."""
    from ...tune import kernel_config
    cfg = kernel_config("paged_attention",
                        {"tq": tq, "kv_heads": kv_heads,
                         "head_dim": head_dim, "page": page, "nblk": nblk,
                         "dtype": jnp.dtype(dtype).name})
    return max(1, min(int(cfg["pages_per_step"]), nblk))


def _page_index(i, pages, j, nblk):
    """Block-table column for page-slot j of grid step i.  The final
    step may overhang nblk; the clamp keeps the DMA on a real page and
    the kernels' `base <= rel` / `base < seq_len` guards (base >=
    nblk*bs for overhang slots) skip its compute."""
    return jnp.minimum(i * pages + j, nblk - 1)


def _decode_kernel(bt_ref, len_ref, q_ref, *refs, bs, sm_scale, pages,
                   nblk):
    """grid (B, H_kv, ceil(nblk/pages)); refs: q [G, D], then `pages` k
    pages and `pages` v pages [bs, D] (one kv head each), o [G, D];
    scratch m/l [G, 1] f32, acc [G, D] f32.  Pages are walked j=0..pages
    in ascending order — identical accumulation order for any width."""
    k_refs = refs[:pages]
    v_refs = refs[pages:2 * pages]
    o_ref, m_ref, l_ref, acc_ref = refs[2 * pages:]
    b = pl.program_id(0)
    i = pl.program_id(2)
    steps = pl.num_programs(2)
    seq_len = len_ref[b]                      # valid positions this seq

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    for j in range(pages):
        base = (i * pages + j) * bs

        @pl.when(base < seq_len)
        def _tile(base=base, k_ref=k_refs[j], v_ref=v_refs[j]):
            q = (q_ref[...].astype(jnp.float32) * sm_scale).astype(
                q_ref.dtype)
            k = k_ref[...]                     # [bs, D]
            v = v_ref[...]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)      # [G, bs]
            pos = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(pos < seq_len, s, -jnp.inf)
            m_prev = m_ref[...]                # [G, 1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)             # [G, bs]
            alpha = jnp.exp(m_prev - m_new)    # [G, 1]
            l_ref[...] = alpha * l_ref[...] + \
                jnp.sum(p, axis=1, keepdims=True)
            acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[...] = m_new

    @pl.when(i == steps - 1)
    def _finalize():
        o_ref[...] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def _paged_decode_launch(q, key_cache, value_cache, block_tables,
                         lengths):
    """The raw decode launch.  Callers must satisfy the packed-operand
    invariant: block_tables/lengths already int32 with every table entry
    in [0, num_blocks) — the grid DMAs a page per table entry even past
    each sequence's length (compute is skipped, the copy is not), so an
    out-of-range entry is an out-of-bounds DMA."""
    B, H, D = q.shape
    _, Hkv, bs, _ = key_cache.shape
    G = H // Hkv
    nblk = block_tables.shape[1]
    sm_scale = 1.0 / (D ** 0.5)
    pages = _pages_per_step(B, Hkv, D, bs, nblk, q.dtype)

    kernel = functools.partial(_decode_kernel, bs=bs, sm_scale=sm_scale,
                               pages=pages, nblk=nblk)
    # q rows for kv head h are h*G..(h+1)*G: block (1, G, D) at index (b, h)
    qr = q.reshape(B, Hkv, G, D)

    def _kv_spec(j):
        return pl.BlockSpec(
            (None, None, bs, D),
            lambda b, h, i, bt, ln, _j=j:
            (bt[b, _page_index(i, pages, _j, nblk)], h, 0, 0))

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,             # block_tables, lengths
            grid=(B, Hkv, -(-nblk // pages)),
            in_specs=[
                pl.BlockSpec((None, None, G, D),
                             lambda b, h, i, bt, ln: (b, h, 0, 0)),
            ] + [_kv_spec(j) for j in range(pages)] * 2,
            out_specs=pl.BlockSpec((None, None, G, D),
                                   lambda b, h, i, bt, ln: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, D), q.dtype),
        interpret=interpret_mode(),
    )(block_tables, lengths, qr,
      *([key_cache] * pages), *([value_cache] * pages))
    return out.reshape(B, H, D)


def paged_decode_attention(q, key_cache, value_cache, block_tables,
                           lengths):
    """One-token-per-sequence decode over paged KV.

    q [B, H, D]; caches [num_blocks, H_kv, bs, D]; block_tables [B, nblk]
    int32; lengths [B] int32 (valid positions incl. the fresh token).
    Returns [B, H, D].  Clamps the reference blha convention's -1 table
    padding to a valid block index before launching; callers that pack
    valid tables on the host should use
    :func:`paged_decode_attention_packed` instead.
    """
    block_tables = jnp.clip(block_tables, 0,
                            key_cache.shape[0] - 1).astype(jnp.int32)
    return _paged_decode_launch(q, key_cache, value_cache, block_tables,
                                lengths.astype(jnp.int32))


def paged_decode_attention_packed(q, key_cache, value_cache, block_tables,
                                  lengths):
    """Decode launch without the defensive table clip/casts, for callers
    owning the host packing path (serving.py keeps its table pool int32
    and NULL_BLOCK-padded with valid indices, so re-normalizing every
    launch is pure waste)."""
    return _paged_decode_launch(q, key_cache, value_cache, block_tables,
                                lengths)


def paged_decode_reference(q, key_cache, value_cache, block_tables,
                           lengths):
    """Dense-gather XLA oracle (the pre-r5 decode path's math)."""
    B, H, D = q.shape
    _, Hkv, bs, _ = key_cache.shape
    kpages = key_cache[block_tables]           # [B, nblk, Hkv, bs, D]
    vpages = value_cache[block_tables]
    ks = jnp.moveaxis(kpages, 2, 1).reshape(B, Hkv, -1, D)
    vs = jnp.moveaxis(vpages, 2, 1).reshape(B, Hkv, -1, D)
    if Hkv != H:
        g = H // Hkv
        ks = jnp.repeat(ks, g, axis=1)
        vs = jnp.repeat(vs, g, axis=1)
    scores = jnp.einsum("bhd,bhmd->bhm", q.astype(jnp.float32),
                        ks.astype(jnp.float32)) / jnp.sqrt(jnp.float32(D))
    pos = jnp.arange(ks.shape[2])[None, None, :]
    scores = jnp.where(pos < lengths[:, None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhm,bhmd->bhd", probs, vs.astype(jnp.float32))
    return out.astype(q.dtype)


def _ragged_kernel(seg_ref, rel_ref, bt_ref, q_ref, *refs, bs, sm_scale,
                   pages, nblk):
    """grid (Tq, H_kv, ceil(nblk/pages)); refs: q [G, D] (one flat
    token's group for one kv head), then `pages` k pages and `pages` v
    pages [bs, D] of that token's owning row, o [G, D]; scratch m/l
    [G, 1] f32, acc [G, D] f32.

    seg[t] names the block-table row owning flat token t; rel[t] is the
    token's position within that row's KV (0-based), so causality is just
    `keypos <= rel[t]` — uniform across prefill/resume/decode/verify rows.
    Pages are walked j=0..pages in ascending order: the accumulation
    order — and therefore every output byte — is identical for any
    `pages` width; only launch-overhead amortization changes.
    """
    k_refs = refs[:pages]
    v_refs = refs[pages:2 * pages]
    o_ref, m_ref, l_ref, acc_ref = refs[2 * pages:]
    t = pl.program_id(0)
    i = pl.program_id(2)
    steps = pl.num_programs(2)
    rel = rel_ref[t]                          # absolute key budget, 0-based

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    for j in range(pages):
        base = (i * pages + j) * bs

        @pl.when(base <= rel)
        def _tile(base=base, k_ref=k_refs[j], v_ref=v_refs[j]):
            q = (q_ref[...].astype(jnp.float32) * sm_scale).astype(
                q_ref.dtype)
            k = k_ref[...]                     # [bs, D]
            v = v_ref[...]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)      # [G, bs]
            pos = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(pos <= rel, s, -jnp.inf)
            m_prev = m_ref[...]                # [G, 1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)             # [G, bs]
            alpha = jnp.exp(m_prev - m_new)    # [G, 1]
            l_ref[...] = alpha * l_ref[...] + \
                jnp.sum(p, axis=1, keepdims=True)
            acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[...] = m_new

    @pl.when(i == steps - 1)
    def _finalize():
        o_ref[...] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def _ragged_quant_kernel(seg_ref, rel_ref, bt_ref, ksc_ref, vsc_ref,
                         q_ref, *refs, bs, sm_scale, pages, nblk):
    """Int8-page variant of `_ragged_kernel`: k/v refs are int8 pages and
    the per-page-per-head float32 scales ride the scalar-prefetch path
    (SMEM) next to the block table, so dequantization happens inline as
    each page streams into VMEM — no dense float intermediate ever
    exists.  ksc/vsc are [num_blocks, H_kv] f32; each page-slot's scale
    is looked up through the same clamped `bt[seg[t], i*pages+j]`
    indirection its BlockSpec index map uses.
    """
    k_refs = refs[:pages]
    v_refs = refs[pages:2 * pages]
    o_ref, m_ref, l_ref, acc_ref = refs[2 * pages:]
    t = pl.program_id(0)
    h = pl.program_id(1)
    i = pl.program_id(2)
    steps = pl.num_programs(2)
    rel = rel_ref[t]                          # absolute key budget, 0-based

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    for j in range(pages):
        base = (i * pages + j) * bs
        blk = bt_ref[seg_ref[t], _page_index(i, pages, j, nblk)]

        @pl.when(base <= rel)
        def _tile(base=base, blk=blk, k_ref=k_refs[j], v_ref=v_refs[j]):
            q = q_ref[...].astype(jnp.float32) * sm_scale
            k = k_ref[...].astype(jnp.float32) * ksc_ref[blk, h]  # [bs, D]
            v = v_ref[...].astype(jnp.float32) * vsc_ref[blk, h]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)      # [G, bs]
            pos = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(pos <= rel, s, -jnp.inf)
            m_prev = m_ref[...]                # [G, 1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)             # [G, bs]
            alpha = jnp.exp(m_prev - m_new)    # [G, 1]
            l_ref[...] = alpha * l_ref[...] + \
                jnp.sum(p, axis=1, keepdims=True)
            acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[...] = m_new

    @pl.when(i == steps - 1)
    def _finalize():
        o_ref[...] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def ragged_segments(cu_seqlens, kv_lens, n_tokens):
    """Derive per-flat-token (seg, rel) from the ragged row layout.

    cu_seqlens [R+1] int32 (row r owns flat tokens cu[r]..cu[r+1]);
    kv_lens [R] int32 (valid KV positions per row AFTER this launch's
    inserts).  Padding tokens past cu[R] get seg == R and rel == 0 so the
    kernel computes a finite garbage row the caller discards.
    """
    cu = cu_seqlens.astype(jnp.int32)
    kvl = kv_lens.astype(jnp.int32)
    R = kvl.shape[0]
    tpos = jnp.arange(n_tokens, dtype=jnp.int32)
    seg = jnp.searchsorted(cu[1:], tpos, side="right").astype(jnp.int32)
    segc = jnp.minimum(seg, R - 1)
    qlen = cu[1:] - cu[:-1]
    rel = jnp.where(seg < R, kvl[segc] - qlen[segc] + tpos - cu[segc], 0)
    return seg, rel


def decode_window_segments(active, kv_lens):
    """Per-iteration (seg, rel) for the device-resident decode window.

    One window iteration carries exactly one flat token per batch row
    (token s belongs to row s), so the ragged searchsorted collapses to
    an identity map.  Rows frozen by the active-mask (eos/length hit
    mid-window) are redirected to the sentinel row B — the [B+1]-row
    block table's null row — so their K/V append and attention reads
    land in the reserved garbage page, exactly like ragged padding
    tokens, and never touch a live sequence's pages.

    active [B] bool (row still decoding), kv_lens [B] int32 (valid KV
    positions AFTER this iteration's insert).  Returns (seg [B], rel [B])
    int32 for the packed/reference segrel attention entry points.
    """
    B = active.shape[0]
    seg = jnp.where(active, jnp.arange(B, dtype=jnp.int32), jnp.int32(B))
    rel = jnp.where(active, kv_lens.astype(jnp.int32) - 1, 0)
    return seg, rel


def _ragged_launch(q, key_cache, value_cache, block_tables, seg, rel):
    """The raw ragged launch.  Callers must satisfy the packed-operand
    invariant: int32 scalar operands, table entries in [0, num_blocks),
    seg values naming real table rows (serving's [B+1]-row table makes
    the pad sentinel B a valid null row)."""
    Tq, H, D = q.shape
    _, Hkv, bs, _ = key_cache.shape
    G = H // Hkv
    R, nblk = block_tables.shape
    sm_scale = 1.0 / (D ** 0.5)
    pages = _pages_per_step(Tq, Hkv, D, bs, nblk, key_cache.dtype)

    kernel = functools.partial(_ragged_kernel, bs=bs, sm_scale=sm_scale,
                               pages=pages, nblk=nblk)
    qr = q.reshape(Tq, Hkv, G, D)

    def _kv_spec(j):
        return pl.BlockSpec(
            (None, None, bs, D),
            lambda t, h, i, sg, rl, bt, _j=j:
            (bt[sg[t], _page_index(i, pages, _j, nblk)], h, 0, 0))

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,             # seg, rel, block_tables
            grid=(Tq, Hkv, -(-nblk // pages)),
            in_specs=[
                pl.BlockSpec((None, None, G, D),
                             lambda t, h, i, sg, rl, bt: (t, h, 0, 0)),
            ] + [_kv_spec(j) for j in range(pages)] * 2,
            out_specs=pl.BlockSpec((None, None, G, D),
                                   lambda t, h, i, sg, rl, bt: (t, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((Tq, Hkv, G, D), q.dtype),
        interpret=interpret_mode(),
    )(seg, rel, block_tables, qr,
      *([key_cache] * pages), *([value_cache] * pages))
    return out.reshape(Tq, H, D)


def ragged_paged_attention_segrel(q, key_cache, value_cache, block_tables,
                                  seg, rel):
    """Ragged attention with precomputed (seg, rel) per flat token.

    q [Tq, H, D]; caches [num_blocks, H_kv, bs, D]; block_tables [R, nblk]
    int32; seg [Tq] int32 in [0, R] (R == padding sentinel); rel [Tq]
    int32.  Returns [Tq, H, D].

    Clamps table entries (blha -1 padding) AND seg (R == pad sentinel) so
    every index map resolves to a real page; padded/overhung tiles are
    DMA'd but masked or skipped in compute.  Callers that already pack
    valid int32 operands on the host should use
    :func:`ragged_paged_attention_segrel_packed`.
    """
    R = block_tables.shape[0]
    block_tables = jnp.clip(block_tables.astype(jnp.int32), 0,
                            key_cache.shape[0] - 1)
    seg = jnp.clip(seg.astype(jnp.int32), 0, R - 1)
    return _ragged_launch(q, key_cache, value_cache, block_tables, seg,
                          rel.astype(jnp.int32))


def ragged_paged_attention_segrel_packed(q, key_cache, value_cache,
                                         block_tables, seg, rel):
    """Ragged launch without the defensive clips/casts, for callers that
    guarantee the host-packing invariant (serving.py owns these buffers:
    its table pool is int32 and NULL_BLOCK-padded with valid indices,
    and its [B+1]-row table makes the seg pad sentinel a real null row,
    so re-normalizing every launch is pure waste)."""
    return _ragged_launch(q, key_cache, value_cache, block_tables, seg,
                          rel)


def ragged_paged_attention(q, key_cache, value_cache, block_tables,
                           cu_seqlens, kv_lens):
    """One ragged launch over flat query tokens from mixed-phase rows.

    q [Tq, H, D] (rows packed back-to-back, tail padding allowed);
    caches [num_blocks, H_kv, bs, D]; block_tables [R, nblk] int32;
    cu_seqlens [R+1] int32; kv_lens [R] int32 (valid KV per row AFTER
    this launch's inserts — a row's queries sit at its LAST kv_lens
    positions).  Returns [Tq, H, D]; padding rows are finite garbage.
    """
    seg, rel = ragged_segments(cu_seqlens, kv_lens, q.shape[0])
    return ragged_paged_attention_segrel(
        q, key_cache, value_cache, block_tables, seg, rel)


def _ragged_quant_launch(q, key_cache, value_cache, key_scales,
                         value_scales, block_tables, seg, rel):
    """The raw int8-page ragged launch; same packed-operand invariant as
    `_ragged_launch`, plus f32 scales."""
    Tq, H, D = q.shape
    _, Hkv, bs, _ = key_cache.shape
    G = H // Hkv
    R, nblk = block_tables.shape
    sm_scale = 1.0 / (D ** 0.5)
    pages = _pages_per_step(Tq, Hkv, D, bs, nblk, key_cache.dtype)

    kernel = functools.partial(_ragged_quant_kernel, bs=bs,
                               sm_scale=sm_scale, pages=pages, nblk=nblk)
    qr = q.reshape(Tq, Hkv, G, D)

    def _kv_spec(j):
        return pl.BlockSpec(
            (None, None, bs, D),
            lambda t, h, i, sg, rl, bt, ks, vs, _j=j:
            (bt[sg[t], _page_index(i, pages, _j, nblk)], h, 0, 0))

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,     # seg, rel, block_tables, ksc, vsc
            grid=(Tq, Hkv, -(-nblk // pages)),
            in_specs=[
                pl.BlockSpec((None, None, G, D),
                             lambda t, h, i, sg, rl, bt, ks, vs:
                             (t, h, 0, 0)),
            ] + [_kv_spec(j) for j in range(pages)] * 2,
            out_specs=pl.BlockSpec((None, None, G, D),
                                   lambda t, h, i, sg, rl, bt, ks, vs:
                                   (t, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((Tq, Hkv, G, D), q.dtype),
        interpret=interpret_mode(),
    )(seg, rel, block_tables, key_scales, value_scales, qr,
      *([key_cache] * pages), *([value_cache] * pages))
    return out.reshape(Tq, H, D)


def ragged_paged_attention_quant_segrel(q, key_cache, value_cache,
                                        key_scales, value_scales,
                                        block_tables, seg, rel):
    """Ragged attention over int8 KV pages with per-page-per-head scales.

    q [Tq, H, D] float; caches [num_blocks, H_kv, bs, D] int8;
    key_scales/value_scales [num_blocks, H_kv] f32 (symmetric:
    float = int8 * scale); block_tables [R, nblk] int32; seg/rel as in
    `ragged_paged_attention_segrel`.  Returns [Tq, H, D] in q.dtype.
    """
    R = block_tables.shape[0]
    block_tables = jnp.clip(block_tables.astype(jnp.int32), 0,
                            key_cache.shape[0] - 1)
    seg = jnp.clip(seg.astype(jnp.int32), 0, R - 1)
    return _ragged_quant_launch(
        q, key_cache, value_cache, key_scales.astype(jnp.float32),
        value_scales.astype(jnp.float32), block_tables, seg,
        rel.astype(jnp.int32))


def ragged_paged_attention_quant_segrel_packed(q, key_cache, value_cache,
                                               key_scales, value_scales,
                                               block_tables, seg, rel):
    """Int8-page ragged launch without the defensive clips/casts, for
    callers that guarantee the host-packing invariant (serving.py packs
    int32 tables/seg/rel and f32 scale pools)."""
    return _ragged_quant_launch(q, key_cache, value_cache, key_scales,
                                value_scales, block_tables, seg, rel)


def ragged_paged_reference_quant_segrel(q, key_cache, value_cache,
                                        key_scales, value_scales,
                                        block_tables, seg, rel):
    """Fake-quant XLA oracle for the int8-page kernel: dequantize the
    whole pool densely (float = int8 * scale, the exact math the kernel
    applies per page) and delegate to the float reference, so CPU tests
    stay exact-vs-oracle in int8 mode."""
    kd = key_cache.astype(jnp.float32) * \
        key_scales.astype(jnp.float32)[:, :, None, None]
    vd = value_cache.astype(jnp.float32) * \
        value_scales.astype(jnp.float32)[:, :, None, None]
    return ragged_paged_reference_segrel(q, kd, vd, block_tables, seg, rel)


def ragged_paged_reference_segrel(q, key_cache, value_cache, block_tables,
                                  seg, rel):
    """Dense-gather XLA oracle for the ragged kernel (the engine's former
    chunked-resume math, term for term)."""
    Tq, H, D = q.shape
    _, Hkv, bs, _ = key_cache.shape
    R, nblk = block_tables.shape
    bt = jnp.clip(block_tables.astype(jnp.int32), 0,
                  key_cache.shape[0] - 1)
    seg = jnp.clip(seg.astype(jnp.int32), 0, R - 1)
    kg = key_cache[bt].transpose(0, 1, 3, 2, 4).reshape(
        R, nblk * bs, Hkv, D)                  # [R, S, Hkv, D]
    vg = value_cache[bt].transpose(0, 1, 3, 2, 4).reshape(
        R, nblk * bs, Hkv, D)
    kq = kg[seg]                               # [Tq, S, Hkv, D]
    vq = vg[seg]
    if Hkv != H:
        g = H // Hkv
        kq = jnp.repeat(kq, g, axis=2)
        vq = jnp.repeat(vq, g, axis=2)
    sm_scale = 1.0 / (D ** 0.5)
    scores = jnp.einsum("qhd,qshd->qhs", q.astype(jnp.float32),
                        kq.astype(jnp.float32)) * sm_scale
    keypos = jnp.arange(nblk * bs, dtype=jnp.int32)
    mask = keypos[None, None, :] <= rel[:, None, None]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("qhs,qshd->qhd", probs, vq.astype(jnp.float32))
    return out.astype(q.dtype)


def ragged_paged_reference(q, key_cache, value_cache, block_tables,
                           cu_seqlens, kv_lens):
    """Dense-gather XLA oracle with the public (cu, kv_lens) interface."""
    seg, rel = ragged_segments(cu_seqlens, kv_lens, q.shape[0])
    return ragged_paged_reference_segrel(
        q, key_cache, value_cache, block_tables, seg, rel)


_PROBE_CACHE: dict = {}
_PROBE_LOGGED = False


def _probe_lowering(B, H, Hkv, D, bs, nblk, dtype) -> bool:
    """Compile-probe the decode kernel for these shapes.

    The authoritative eligibility check is an actual lowering (the r2
    bench died on a heuristic yes / Mosaic no — flash_attention.py:453);
    returns False on any failure so callers degrade to the dense-gather
    XLA path instead of crashing every serving decode step.
    """
    global _PROBE_LOGGED
    key = (B, H, Hkv, D, bs, nblk, str(dtype), jax.default_backend())
    hit = _PROBE_CACHE.get(key)
    if hit is not None:
        return hit
    if interpret_mode():  # interpreter enforces no TPU tiling rules
        _PROBE_CACHE[key] = True
        return True
    num_blocks = max(nblk * B, 1)
    try:
        jax.jit(paged_decode_attention).lower(
            jax.ShapeDtypeStruct((B, H, D), dtype),
            jax.ShapeDtypeStruct((num_blocks, Hkv, bs, D), dtype),
            jax.ShapeDtypeStruct((num_blocks, Hkv, bs, D), dtype),
            jax.ShapeDtypeStruct((B, nblk), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
        ).compile()
        ok = True
    except Exception as e:
        ok = False
        if not _PROBE_LOGGED:
            _PROBE_LOGGED = True
            import logging
            logging.getLogger("paddle_tpu.pallas").warning(
                "paged decode kernel does not lower for "
                f"B={B} H={H} Hkv={Hkv} D={D} bs={bs}: "
                f"{type(e).__name__}; falling back to dense gather")
    _PROBE_CACHE[key] = ok
    return ok


def supports(B, H, Hkv, D, bs, nblk=None, dtype=jnp.float32) -> bool:
    """Eligibility for the pallas decode kernel: shape heuristic, then an
    actual lowering probe (cached)."""
    if H % Hkv != 0:
        return False
    if D % 128 != 0 and D not in (64,):
        return False
    if bs % 8 != 0:
        return False
    if nblk is None:
        return True     # shape-only query (no probe possible yet)
    return _probe_lowering(B, H, Hkv, D, bs, nblk, dtype)


def _probe_ragged_lowering(Tq, H, Hkv, D, bs, R, nblk, dtype) -> bool:
    """Compile-probe the ragged kernel for these shapes (cached; same
    degrade-don't-crash contract as `_probe_lowering`)."""
    global _PROBE_LOGGED
    key = ("ragged", Tq, H, Hkv, D, bs, R, nblk, str(dtype),
           jax.default_backend())
    hit = _PROBE_CACHE.get(key)
    if hit is not None:
        return hit
    if interpret_mode():  # interpreter enforces no TPU tiling rules
        _PROBE_CACHE[key] = True
        return True
    num_blocks = max(nblk * R, 1)
    try:
        jax.jit(ragged_paged_attention_segrel).lower(
            jax.ShapeDtypeStruct((Tq, H, D), dtype),
            jax.ShapeDtypeStruct((num_blocks, Hkv, bs, D), dtype),
            jax.ShapeDtypeStruct((num_blocks, Hkv, bs, D), dtype),
            jax.ShapeDtypeStruct((R, nblk), jnp.int32),
            jax.ShapeDtypeStruct((Tq,), jnp.int32),
            jax.ShapeDtypeStruct((Tq,), jnp.int32),
        ).compile()
        ok = True
    except Exception as e:
        ok = False
        if not _PROBE_LOGGED:
            _PROBE_LOGGED = True
            import logging
            logging.getLogger("paddle_tpu.pallas").warning(
                "ragged paged kernel does not lower for "
                f"Tq={Tq} H={H} Hkv={Hkv} D={D} bs={bs}: "
                f"{type(e).__name__}; falling back to dense gather")
    _PROBE_CACHE[key] = ok
    return ok


def ragged_supports(Tq, H, Hkv, D, bs, R=None, nblk=None,
                    dtype=jnp.float32) -> bool:
    """Eligibility for the ragged pallas kernel: shape heuristic, then an
    actual lowering probe (cached).

    Under tensor parallelism callers pass PER-SHARD head counts (H/tp,
    Hkv/tp): the kernel launches inside shard_map, so Mosaic lowers and
    tiles against the shard-local q/kv shapes, never the mesh-global
    ones.  The engine guarantees tp divides both counts, so the GQA
    ratio H % Hkv == 0 is shard-invariant."""
    if H < 1 or Hkv < 1:
        return False
    if H % Hkv != 0:
        return False
    if D % 128 != 0 and D not in (64,):
        return False
    if bs % 8 != 0:
        return False
    if R is None or nblk is None:
        return True     # shape-only query (no probe possible yet)
    return _probe_ragged_lowering(Tq, H, Hkv, D, bs, R, nblk, dtype)


def _probe_ragged_quant_lowering(Tq, H, Hkv, D, bs, R, nblk, dtype) -> bool:
    """Compile-probe the int8-page ragged kernel (cached; same
    degrade-don't-crash contract as `_probe_lowering`)."""
    global _PROBE_LOGGED
    key = ("ragged-q8", Tq, H, Hkv, D, bs, R, nblk, str(dtype),
           jax.default_backend())
    hit = _PROBE_CACHE.get(key)
    if hit is not None:
        return hit
    if interpret_mode():  # interpreter enforces no TPU tiling rules
        _PROBE_CACHE[key] = True
        return True
    num_blocks = max(nblk * R, 1)
    try:
        jax.jit(ragged_paged_attention_quant_segrel).lower(
            jax.ShapeDtypeStruct((Tq, H, D), dtype),
            jax.ShapeDtypeStruct((num_blocks, Hkv, bs, D), jnp.int8),
            jax.ShapeDtypeStruct((num_blocks, Hkv, bs, D), jnp.int8),
            jax.ShapeDtypeStruct((num_blocks, Hkv), jnp.float32),
            jax.ShapeDtypeStruct((num_blocks, Hkv), jnp.float32),
            jax.ShapeDtypeStruct((R, nblk), jnp.int32),
            jax.ShapeDtypeStruct((Tq,), jnp.int32),
            jax.ShapeDtypeStruct((Tq,), jnp.int32),
        ).compile()
        ok = True
    except Exception as e:
        ok = False
        if not _PROBE_LOGGED:
            _PROBE_LOGGED = True
            import logging
            logging.getLogger("paddle_tpu.pallas").warning(
                "int8 ragged paged kernel does not lower for "
                f"Tq={Tq} H={H} Hkv={Hkv} D={D} bs={bs}: "
                f"{type(e).__name__}; falling back to dense fake-quant")
    _PROBE_CACHE[key] = ok
    return ok


def ragged_quant_supports(Tq, H, Hkv, D, bs, R=None, nblk=None,
                          dtype=jnp.float32) -> bool:
    """Eligibility for the int8-page ragged kernel.  Int8 pages carry a
    (32, 128) minimum tile (vs (8, 128) for f32), so the page-size
    heuristic is stricter than the float path's before the authoritative
    lowering probe runs.  As with ``ragged_supports``, tensor-parallel
    callers pass per-shard head counts."""
    if H < 1 or Hkv < 1:
        return False
    if H % Hkv != 0:
        return False
    if D % 128 != 0 and D not in (64,):
        return False
    if bs % 32 != 0:
        return False
    if R is None or nblk is None:
        return True     # shape-only query (no probe possible yet)
    return _probe_ragged_quant_lowering(Tq, H, Hkv, D, bs, R, nblk, dtype)
