"""Root-surface completion: inplace `op_` variants, aliases, constants.

The reference exports an inplace twin for most elementwise ops
(python/paddle/tensor/*.py `*_` wrappers over inplace kernels) plus a set
of aliases and module constants.  Under XLA there is no in-place kernel —
buffers are immutable — so `x_` computes out-of-place and rebinds the
Tensor's buffer (exactly what the reference's inplace ops guarantee
observably: x aliases the result).  The derivation is data-driven from the
base ops so the two surfaces cannot drift.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["derive_inplace", "ALIASES", "CONSTANTS", "extra_ops"]

# every `name_` the reference exports whose base op we implement
_INPLACE_BASES = [
    "abs", "acos", "addmm", "asin", "atan", "bernoulli", "bitwise_and",
    "bitwise_not", "bitwise_or", "bitwise_xor", "cast", "ceil", "clip",
    "copysign", "cos", "cosh", "cumprod", "cumsum", "digamma", "divide",
    "equal", "erf", "exp", "expm1", "floor", "floor_divide", "frac",
    "gammainc", "gammaincc", "gcd", "greater_equal", "greater_than",
    "not_equal", "atanh", "lerp", "erfinv", "put_along_axis", "sigmoid",
    "acosh", "asinh",
    "hypot", "i0", "index_add",
    "index_fill", "index_put", "lcm", "ldexp", "less_equal", "less_than",
    "lgamma", "log", "log10", "log1p", "log2", "logical_and",
    "logical_not", "logical_or", "logical_xor", "logit", "masked_fill",
    "masked_scatter", "mod", "multiply", "nan_to_num", "neg", "pow",
    "reciprocal", "remainder", "renorm", "round", "rsqrt", "scale",
    "sigmoid", "sin", "sinc", "sinh", "sqrt", "square", "subtract",
    "tan", "tanh", "transpose", "tril", "triu", "trunc", "where",
    "bitwise_left_shift", "bitwise_right_shift", "polygamma",
    "multigammaln", "gammaln", "log_normal", "slice_scatter",
]


def _make_inplace(name, base):
    def fn_(x, *args, **kwargs):
        out = base(x, *args, **kwargs)
        x._data = out._data if isinstance(out, Tensor) else out
        return x
    fn_.__name__ = name + "_"
    fn_.__doc__ = (f"In-place variant of `{name}` (reference {name}_): "
                   "computes out-of-place under XLA and rebinds x's buffer.")
    return fn_


def derive_inplace(public_ops: dict) -> dict:
    out = {}
    for name in _INPLACE_BASES:
        base = public_ops.get(name)
        if base is not None and name + "_" not in public_ops:
            out[name + "_"] = _make_inplace(name, base)
    return out


# ---------------------------------------------------------------------------
# aliases: reference name -> existing op name
# ---------------------------------------------------------------------------

ALIASES = {
    "negative": "neg",
    "less": "less_than",
    "less_": "less_than_",
    "floor_mod": "mod",
    "floor_mod_": "mod_",
    "remainder": "mod",
    "row_stack": "vstack",
    "column_stack": "hstack",
    "bitwise_invert": "bitwise_not",
    "bitwise_invert_": "bitwise_not_",
    "positive": "abs" if False else None,   # resolved in extra_ops
}
ALIASES = {k: v for k, v in ALIASES.items() if v}

CONSTANTS = {
    "inf": float("inf"),
    "nan": float("nan"),
    "pi": float(np.pi),
    "e": float(np.e),
    "newaxis": None,
}


# ---------------------------------------------------------------------------
# remaining small ops the reference exports at root
# ---------------------------------------------------------------------------

def _block_diag_impl(*arrs):
    import jax.numpy as jnp
    import jax.scipy.linalg as jsl
    return jsl.block_diag(*[jnp.atleast_2d(a) for a in arrs])


def extra_ops():
    import jax.numpy as jnp

    from ..core import dispatch as D

    def _t(x):
        return x._data if isinstance(x, Tensor) else jnp.asarray(x)

    def sigmoid(x, name=None):
        """(reference tensor/ops sigmoid — also a Tensor method)"""
        from ..nn.functional.activation import sigmoid as _f
        return _f(x)

    def positive(x, name=None):
        """Identity on numeric tensors (reference tensor/math.py positive)."""
        return D.apply("positive", lambda a: +a, (x,))

    def t(input, name=None):
        """Transpose <=2-D (reference tensor/linalg.py t)."""
        a = _t(input)
        if a.ndim > 2:
            raise ValueError(f"paddle.t expects ndim<=2, got {a.ndim}")
        return D.apply("t", lambda a: a.T, (input,))

    def t_(input, name=None):
        out = t(input)
        input._data = out._data
        return input

    def matrix_transpose(x, name=None):
        """Swap the last two dims (reference linalg matrix_transpose)."""
        return D.apply("matrix_transpose",
                       lambda a: jnp.swapaxes(a, -1, -2), (x,))

    def rank(input, name=None):
        """0-D int tensor holding ndim (reference tensor/attribute rank)."""
        return Tensor(jnp.asarray(_t(input).ndim, jnp.int32))

    def block_diag(inputs, name=None):
        """Block-diagonal assembly (reference tensor/creation block_diag).
        Routed through the dispatcher so gradients flow to every block."""
        return D.apply("block_diag", _block_diag_impl, tuple(inputs))

    def cartesian_prod(x, name=None):
        """Cartesian product of 1-D tensors (reference cartesian_prod)."""
        arrs = [_t(v) for v in x]
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return Tensor(jnp.stack([g.reshape(-1) for g in grids], axis=-1))

    def isin(x, test_x, assume_unique=False, invert=False, name=None):
        def impl(a, b, invert):
            out = jnp.isin(a, b)
            return out != invert if invert else out
        return D.apply("isin", impl, (x, test_x), {"invert": bool(invert)})

    def vecdot(x, y, axis=-1, name=None):
        def impl(a, b, axis):
            return jnp.sum(a * b, axis=axis)
        return D.apply("vecdot", impl, (x, y), {"axis": int(axis)})

    def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
        a = np.asarray(_t(input))
        lo, hi = (float(min), float(max)) if (min != 0 or max != 0) \
            else (float(a.min()), float(a.max()))
        return Tensor(jnp.asarray(
            np.histogram_bin_edges(a, bins=bins, range=(lo, hi))
            .astype(np.float32)))

    def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                    name=None):
        a = np.asarray(_t(x))
        w = None if weights is None else np.asarray(_t(weights))
        hist, edges = np.histogramdd(a, bins=bins, range=ranges,
                                     density=density, weights=w)
        return (Tensor(jnp.asarray(hist.astype(np.float32))),
                [Tensor(jnp.asarray(e.astype(np.float32))) for e in edges])

    def frexp(x, name=None):
        def impl(a):
            m, e = jnp.frexp(a)
            return m, e.astype(jnp.int32)
        return D.apply("frexp", impl, (x,), num_outputs=2)

    def unfold(x, axis, size, step, name=None):
        """Sliding windows along axis (reference Tensor.unfold)."""
        def impl(a, axis, size, step):
            n = (a.shape[axis] - size) // step + 1
            idx = jnp.arange(n)[:, None] * step + jnp.arange(size)[None, :]
            out = jnp.take(a, idx.reshape(-1), axis=axis)
            shp = list(a.shape)
            shp[axis:axis + 1] = [n, size]
            out = out.reshape(shp)
            # reference puts the window dim LAST
            return jnp.moveaxis(out, axis + 1, -1)

        return D.apply("unfold_windows", impl, (x,),
                       {"axis": int(axis), "size": int(size),
                        "step": int(step)})

    def check_shape(x, expected_shape):
        """Shape assertion helper (reference check_shape)."""
        got = tuple(_t(x).shape)
        want = tuple(expected_shape)
        ok = len(got) == len(want) and all(
            w in (-1, None) or g == w for g, w in zip(got, want))
        if not ok:
            raise ValueError(f"shape mismatch: got {got}, expected {want}")
        return True

    return {k: v for k, v in locals().items()
            if callable(v) and not k.startswith("_")}


# materialize the extra ops as module attributes (the schema conformance
# test resolves `module:name` to live callables)
EXTRA_OPS = extra_ops()
globals().update(EXTRA_OPS)


def derived_names(public_ops: dict) -> set:
    """Names derived programmatically from schema'd bases (inplace twins,
    aliases, constants) — transitively covered by the schema."""
    names = set(CONSTANTS)
    names.update(a for a in ALIASES)
    names.update(n + "_" for n in _INPLACE_BASES)
    return names
