"""Linear-algebra ops.

Parity with /root/reference/python/paddle/tensor/linalg.py (dispatching to
phi lapack/cusolver kernels); here backed by jnp.linalg / lax.linalg which
XLA lowers natively.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dispatch as D
from ..core.tensor import Tensor

__all__ = [
    "norm", "vector_norm", "matrix_norm", "p_norm", "cholesky", "cholesky_solve",
    "qr", "svd", "svdvals", "inv", "solve", "lstsq", "lu", "lu_unpack", "eig",
    "eigh", "eigvals", "eigvalsh", "matrix_power", "matrix_rank", "pinv", "det",
    "slogdet", "triangular_solve", "cross", "cov", "corrcoef", "householder_product",
    "matrix_exp", "cdist", "dist", "multi_dot", "tensordot", "pca_lowrank",
    "cond", "cholesky_inverse", "ormqr", "svd_lowrank",
]


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def _norm(a, p, axis, keepdim):
        if p is None:
            p = "fro" if (axis is None or isinstance(axis, tuple)) and a.ndim >= 2 else 2
        if axis is None:
            if p == "fro":
                return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(a))))
            return jnp.sum(jnp.abs(a) ** p) ** (1.0 / p) if p not in (np.inf, -np.inf) else (
                jnp.max(jnp.abs(a)) if p == np.inf else jnp.min(jnp.abs(a)))
        if isinstance(axis, tuple):
            return jnp.linalg.norm(a, ord=p, axis=axis, keepdims=keepdim)
        if p == "fro":
            p = 2
        if p == np.inf:
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == -np.inf:
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)
    if isinstance(p, str) and p not in ("fro", "nuc"):
        raise ValueError(f"unsupported norm order {p}")
    ax = tuple(int(a) for a in axis) if isinstance(axis, (list, tuple)) else (
        None if axis is None else int(axis))
    pv = p if (p is None or isinstance(p, str)) else float(p)
    return D.apply("p_norm", _norm, (x,), {"p": pv, "axis": ax, "keepdim": bool(keepdim)})


p_norm = norm


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p, axis, keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return D.apply("matrix_norm",
                   lambda a, p, axis, keepdim: jnp.linalg.norm(a, ord=p, axis=axis, keepdims=keepdim),
                   (x,), {"p": p, "axis": tuple(axis), "keepdim": bool(keepdim)})


def _simple(name, jfn, n_out=1):
    def op(x, *args, **kwargs):
        ts = (x,) + tuple(a for a in args if isinstance(a, Tensor))
        return D.apply(name, jfn, ts)
    op.__name__ = name
    return op


def lstsq(x, y, rcond=None, driver=None, name=None):
    def _impl(a, b, rcond):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return D.apply("lstsq", _impl, (x, y), {"rcond": rcond})


def lu(x, pivot=True, get_infos=False, name=None):
    def _impl(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        return lu_mat, (piv + 1).astype(jnp.int32)
    out = D.apply("lu", _impl, (x,))
    if get_infos:
        return out[0], out[1], Tensor(jnp.zeros((), jnp.int32))
    return out


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    def _impl(lu_mat, piv):
        n = lu_mat.shape[-2]
        L = jnp.tril(lu_mat, -1) + jnp.eye(n, lu_mat.shape[-1], dtype=lu_mat.dtype)
        L = L[..., :, :builtins_min(lu_mat.shape[-2], lu_mat.shape[-1])]
        U = jnp.triu(lu_mat)[..., :builtins_min(lu_mat.shape[-2], lu_mat.shape[-1]), :]
        perm = jnp.arange(n)
        def body(i, p):
            j = piv[i] - 1
            pi, pj = p[i], p[j]
            p = p.at[i].set(pj).at[j].set(pi)
            return p
        perm = jax.lax.fori_loop(0, piv.shape[-1], body, perm)
        P = jnp.eye(n, dtype=lu_mat.dtype)[perm].T
        return P, L, U
    return D.apply("lu_unpack", _impl, (x, y))


builtins_min = min


def eig(x, name=None):
    # TPU/XLA has no nonsymmetric eig; host fallback (same as reference CPU lapack).
    a = np.asarray(x._data)
    w, v = np.linalg.eig(a)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    a = np.asarray(x._data)
    return Tensor(jnp.asarray(np.linalg.eigvals(a)))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    def _impl(a, tol, hermitian):
        sv = jnp.abs(jnp.linalg.eigvalsh(a)) if hermitian else jnp.linalg.svd(a, compute_uv=False)
        t = tol if tol is not None else (
            jnp.max(sv, axis=-1, keepdims=True) * builtins_max(a.shape[-2], a.shape[-1])
            * jnp.finfo(a.dtype).eps)
        return jnp.sum((sv > t).astype(jnp.int64), axis=-1)
    tv = tol.item() if isinstance(tol, Tensor) else tol
    return D.apply("matrix_rank", _impl, (x,), {"tol": tv, "hermitian": bool(hermitian)})


builtins_max = max


def householder_product(x, tau, name=None):
    def _impl(a, tau):
        m, n = a.shape[-2], a.shape[-1]
        out = jnp.eye(m, dtype=a.dtype)
        for i in range(n):
            v = jnp.concatenate([jnp.zeros((i,), a.dtype), jnp.ones((1,), a.dtype), a[i + 1:, i]])
            H = jnp.eye(m, dtype=a.dtype) - tau[i] * jnp.outer(v, v)
            out = out @ H
        return out[:, :n]
    return D.apply("householder_product", _impl, (x, tau))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def _impl(a, q, center):
        if center:
            a = a - jnp.mean(a, axis=-2, keepdims=True)
        u, s, vt = jnp.linalg.svd(a, full_matrices=False)
        return u[..., :q], s[..., :q], jnp.swapaxes(vt, -1, -2)[..., :q]
    qv = q if q is not None else min(x.shape[-2:])
    return D.apply("pca_lowrank", _impl, (x,), {"q": int(qv), "center": bool(center)})


def cond(x, p=None, name=None):
    """Condition number (reference tensor/linalg.py cond): sigma_max /
    sigma_min for p=None/2/-2, else norm(x, p) * norm(inv(x), p)."""
    def impl(a, p):
        af = a.astype(jnp.float32)
        if p is None or p in (2, -2):
            s = jnp.linalg.svd(af, compute_uv=False)
            ratio = s[..., 0] / s[..., -1]
            return 1.0 / ratio if p == -2 else ratio
        return jnp.linalg.norm(af, ord=p, axis=(-2, -1)) * \
            jnp.linalg.norm(jnp.linalg.inv(af), ord=p, axis=(-2, -1))

    pk = p if p is None or isinstance(p, str) else float(p)
    if isinstance(pk, float) and pk in (2.0, -2.0):
        pk = int(pk)
    return D.apply("cond", impl, (x,), {"p": pk})


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Multiply `other` by the Q of a Householder QR given (x, tau)
    (reference tensor/linalg.py ormqr).  Q is materialized via
    householder_product — O(m^2 k) like the reference's LAPACK path."""
    def impl(a, tau, y, left, transpose):
        if a.ndim != 2:
            raise ValueError(
                f"ormqr: batched inputs are not supported (got x rank "
                f"{a.ndim}); vmap over the batch dim")
        af = a.astype(jnp.float32)
        tf = tau.astype(jnp.float32)
        yf = y.astype(jnp.float32)
        m, k = af.shape[-2], af.shape[-1]
        Q = jnp.eye(m, dtype=jnp.float32)
        for i in range(k):
            v = jnp.where(jnp.arange(m) < i, 0.0, af[..., :, i])
            v = v.at[i].set(1.0)
            H = jnp.eye(m) - tf[..., i] * jnp.outer(v, v)
            Q = Q @ H
        if transpose:
            Q = Q.T
        return (Q @ yf) if left else (yf @ Q)

    return D.apply("ormqr", impl, (x, tau, other),
                   {"left": bool(left), "transpose": bool(transpose)})


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (reference tensor/linalg.py svd_lowrank,
    Halko et al. subspace iteration)."""
    def impl(a, m=None, q=6, niter=2, seed=0):
        af = a.astype(jnp.float32)
        if m is not None:
            af = af - m.astype(jnp.float32)   # centering (PCA use)
        m, n = af.shape[-2], af.shape[-1]
        key = jax.random.PRNGKey(seed)
        omega = jax.random.normal(key, (n, q), jnp.float32)
        y = af @ omega
        Q, _ = jnp.linalg.qr(y)
        for _ in range(niter):
            Q, _ = jnp.linalg.qr(af.T @ Q)
            Q, _ = jnp.linalg.qr(af @ Q)
        B = Q.T @ af
        u_b, s, vT = jnp.linalg.svd(B, full_matrices=False)
        return Q @ u_b, s, vT.T

    import random as _r
    args = (x,) if M is None else (x, M)
    return D.apply("svd_lowrank", impl, args,
                   {"q": int(q), "niter": int(niter),
                    "seed": _r.randint(0, 2 ** 31 - 1)}, num_outputs=3)


# kernel-driven (generated from ops.yaml `kernel:` over ops/kernels.py)
from .generated.op_wrappers import (  # noqa: E402,F401
    cdist, cholesky, cholesky_inverse, cholesky_solve, corrcoef, cov, cross,
    det, dist, eigh, eigvalsh, inv, matrix_exp, matrix_power, multi_dot,
    pinv, qr, slogdet, solve, svd, svdvals, tensordot, triangular_solve,
)
