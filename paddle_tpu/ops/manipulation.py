"""Shape / indexing / rearrangement ops.

Capability parity with /root/reference/python/paddle/tensor/manipulation.py
and search.py; pure-jnp kernels through the eager dispatcher.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dispatch as D
from ..core.dtype import convert_dtype, to_jax_dtype
from ..core.tensor import Tensor

__all__ = [
    "reshape", "reshape_", "transpose", "moveaxis", "swapaxes", "concat",
    "stack", "vstack", "hstack", "dstack", "split", "vsplit", "hsplit",
    "dsplit", "tensor_split", "chunk", "squeeze", "squeeze_", "unsqueeze",
    "unsqueeze_", "flatten", "flip", "fliplr", "flipud", "roll", "tile",
    "expand", "expand_as", "broadcast_to", "broadcast_tensors", "gather",
    "gather_nd", "scatter", "scatter_", "scatter_nd", "scatter_nd_add",
    "index_select", "index_sample", "index_add", "index_put", "index_fill",
    "masked_select", "masked_fill", "masked_scatter", "take_along_axis",
    "put_along_axis", "unbind", "repeat_interleave", "unique",
    "unique_consecutive", "topk", "sort", "argsort", "searchsorted", "where",
    "nonzero", "one_hot", "unstack", "strided_slice", "slice", "crop",
    "pad", "shard_index", "rotate90", "as_complex", "as_real", "view",
    "view_as", "atleast_1d", "atleast_2d", "atleast_3d", "select_scatter",
    "diagonal_scatter", "flatten_", "tolist", "unflatten", "bucketize",
]


def _t(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _shape_static(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.tolist())
    out = []
    for s in shape:
        out.append(int(s.item()) if isinstance(s, Tensor) else int(s))
    return tuple(out)


def reshape(x, shape, name=None):
    return D.apply("reshape", lambda a, shape: jnp.reshape(a, shape),
                   (x,), {"shape": _shape_static(shape)})


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def transpose(x, perm=None, name=None):
    if perm is None:
        perm = tuple(reversed(range(x.ndim)))
    return D.apply("transpose", lambda a, perm: jnp.transpose(a, perm),
                   (x,), {"perm": tuple(int(p) for p in perm)})


def moveaxis(x, source, destination, name=None):
    s = tuple(source) if isinstance(source, (list, tuple)) else (source,)
    d = tuple(destination) if isinstance(destination, (list, tuple)) else (destination,)
    return D.apply("moveaxis", lambda a, s, d: jnp.moveaxis(a, s, d),
                   (x,), {"s": s, "d": d})


def swapaxes(x, axis1, axis2, name=None):
    return D.apply("swapaxes", lambda a, i, j: jnp.swapaxes(a, i, j),
                   (x,), {"i": int(axis1), "j": int(axis2)})


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return D.apply("concat", lambda *arrs, axis: jnp.concatenate(arrs, axis=axis),
                   tuple(x), {"axis": int(axis)})


def stack(x, axis=0, name=None):
    return D.apply("stack", lambda *arrs, axis: jnp.stack(arrs, axis=axis),
                   tuple(x), {"axis": int(axis)})


def vstack(x, name=None):
    return D.apply("vstack", lambda *arrs: jnp.vstack(arrs), tuple(x))


def hstack(x, name=None):
    return D.apply("hstack", lambda *arrs: jnp.hstack(arrs), tuple(x))


def dstack(x, name=None):
    return D.apply("dstack", lambda *arrs: jnp.dstack(arrs), tuple(x))


def _split_sections(x_shape, num_or_sections, axis):
    axis = axis % len(x_shape)
    n = x_shape[axis]
    if isinstance(num_or_sections, int):
        assert n % num_or_sections == 0, (
            f"dim {n} not divisible into {num_or_sections} sections")
        sizes = [n // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        neg = [i for i, s in enumerate(sizes) if s < 0]
        if neg:
            known = builtins_sum(s for s in sizes if s >= 0)
            sizes[neg[0]] = n - known
    offsets = np.cumsum([0] + sizes[:-1]).tolist()
    return sizes, offsets, axis


builtins_sum = sum


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    sizes, offsets, axis = _split_sections(tuple(x.shape), num_or_sections, axis)

    def _split(a, sizes, offsets, axis):
        return tuple(jax.lax.slice_in_dim(a, o, o + s, axis=axis)
                     for s, o in zip(sizes, offsets))
    out = D.apply("split", _split, (x,),
                  {"sizes": tuple(sizes), "offsets": tuple(offsets), "axis": axis})
    return list(out) if isinstance(out, tuple) else [out]


def tensor_split(x, num_or_indices, axis=0, name=None):
    n = x.shape[axis % x.ndim]
    if isinstance(num_or_indices, int):
        base, extra = divmod(n, num_or_indices)
        sizes = [base + (1 if i < extra else 0) for i in range(num_or_indices)]
    else:
        idx = [0] + [int(i) for i in num_or_indices] + [n]
        sizes = [idx[i + 1] - idx[i] for i in range(len(idx) - 1)]
    return split(x, sizes, axis)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def squeeze(x, axis=None, name=None):
    if axis is None:
        ax = None
    elif isinstance(axis, (list, tuple)):
        ax = tuple(int(a) for a in axis if x.shape[int(a)] == 1)
    else:
        ax = (int(axis),) if x.shape[int(axis)] == 1 else ()
        if ax == ():
            return D.apply("identity", lambda a: a * 1 if jnp.issubdtype(a.dtype, jnp.number) else a, (x,))
    return D.apply("squeeze", lambda a, axis: jnp.squeeze(a, axis=axis),
                   (x,), {"axis": ax})


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def unsqueeze(x, axis, name=None):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    ax = tuple(int(a) for a in axis) if isinstance(axis, (list, tuple)) else (int(axis),)
    return D.apply("unsqueeze", lambda a, axis: jnp.expand_dims(a, axis=axis),
                   (x,), {"axis": ax})


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = x.ndim
    if nd == 0:
        return reshape(x, [1])
    start = start_axis % nd
    stop = stop_axis % nd
    shape = tuple(x.shape)
    new_shape = shape[:start] + (-1,) + shape[stop + 1:]
    return reshape(x, new_shape)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    out = flatten(x, start_axis, stop_axis)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def unflatten(x, axis, shape, name=None):
    axis = axis % x.ndim
    cur = tuple(x.shape)
    return reshape(x, cur[:axis] + tuple(shape) + cur[axis + 1:])


def flip(x, axis, name=None):
    ax = tuple(int(a) for a in axis) if isinstance(axis, (list, tuple)) else (int(axis),)
    return D.apply("flip", lambda a, axis: jnp.flip(a, axis=axis), (x,), {"axis": ax})


def fliplr(x, name=None):
    return flip(x, 1)


def flipud(x, name=None):
    return flip(x, 0)


rotate90 = None  # placeholder; rot90 lives in math


def roll(x, shifts, axis=None, name=None):
    sh = tuple(int(s) for s in shifts) if isinstance(shifts, (list, tuple)) else int(shifts)
    ax = (tuple(int(a) for a in axis) if isinstance(axis, (list, tuple))
          else (None if axis is None else int(axis)))
    return D.apply("roll", lambda a, shifts, axis: jnp.roll(a, shifts, axis=axis),
                   (x,), {"shifts": sh, "axis": ax})


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = repeat_times.tolist()
    return D.apply("tile", lambda a, reps: jnp.tile(a, reps),
                   (x,), {"reps": tuple(int(r) for r in repeat_times)})


def expand(x, shape, name=None):
    tgt = _shape_static(shape)
    cur = tuple(x.shape)
    full = []
    pad = len(tgt) - len(cur)
    for i, s in enumerate(tgt):
        if s == -1:
            full.append(cur[i - pad] if i >= pad else 1)
        else:
            full.append(s)
    return D.apply("expand", lambda a, shape: jnp.broadcast_to(a, shape),
                   (x,), {"shape": tuple(full)})


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    shape = np.broadcast_shapes(*[tuple(t.shape) for t in inputs])
    return [broadcast_to(t, shape) for t in inputs]


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())

    def _gather(a, idx, axis):
        if idx.ndim == 0:
            idx = idx[None]
        return jnp.take(a, idx, axis=axis)
    return D.apply("gather", _gather, (x, index), {"axis": int(axis)})


def gather_nd(x, index, name=None):
    def _gather_nd(a, idx):
        nd = idx.shape[-1]
        out = a[tuple(jnp.moveaxis(idx, -1, 0))]
        return out
    return D.apply("gather_nd", _gather_nd, (x, index))


def scatter(x, index, updates, overwrite=True, name=None):
    def _scatter(a, idx, upd, overwrite):
        if idx.ndim == 2 and idx.shape[1] == 1:
            idx = idx[:, 0]
        if overwrite:
            return a.at[idx].set(upd)
        zeroed = a.at[idx].set(jnp.zeros_like(upd))
        return zeroed.at[idx].add(upd)
    return D.apply("scatter", _scatter, (x, index, updates),
                   {"overwrite": bool(overwrite)})


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def scatter_nd(index, updates, shape, name=None):
    def _scatter_nd(idx, upd, shape):
        zeros = jnp.zeros(shape, upd.dtype)
        return zeros.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return D.apply("scatter_nd", _scatter_nd, (index, updates),
                   {"shape": _shape_static(shape)})


def scatter_nd_add(x, index, updates, name=None):
    def _scatter_nd_add(a, idx, upd):
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return D.apply("scatter_nd_add", _scatter_nd_add, (x, index, updates))


def index_select(x, index, axis=0, name=None):
    return D.apply("index_select", lambda a, idx, axis: jnp.take(a, idx, axis=axis),
                   (x, index), {"axis": int(axis)})


def index_sample(x, index, name=None):
    return D.apply("index_sample",
                   lambda a, idx: jnp.take_along_axis(a, idx, axis=1),
                   (x, index))


def index_add(x, index, axis, value, name=None):
    def _index_add(a, idx, v, axis):
        return jnp.apply_along_axis  # placeholder, replaced below
    def _impl(a, idx, v, axis):
        a_m = jnp.moveaxis(a, axis, 0)
        v_m = jnp.moveaxis(v, axis, 0)
        out = a_m.at[idx].add(v_m)
        return jnp.moveaxis(out, 0, axis)
    return D.apply("index_add", _impl, (x, index, value), {"axis": int(axis)})


def index_put(x, indices, value, accumulate=False, name=None):
    idxs = tuple(indices)

    def _index_put(a, v, *idx, accumulate):
        if accumulate:
            return a.at[idx].add(v)
        return a.at[idx].set(v)
    return D.apply("index_put", _index_put, (x, value) + idxs,
                   {"accumulate": bool(accumulate)})


def index_fill(x, index, axis, value, name=None):
    def _impl(a, idx, axis, value):
        a_m = jnp.moveaxis(a, axis, 0)
        out = a_m.at[idx].set(jnp.asarray(value, a.dtype))
        return jnp.moveaxis(out, 0, axis)
    if isinstance(value, Tensor):
        value = value.item()
    return D.apply("index_fill", _impl, (x, index), {"axis": int(axis), "value": value})


def masked_select(x, mask, name=None):
    # Dynamic output size: host-sync path (same as reference GPU sync).
    a, m = np.asarray(_t(x)), np.asarray(_t(mask))
    return Tensor(jnp.asarray(a[m]))


def masked_fill(x, mask, value, name=None):
    if isinstance(value, Tensor):
        return D.apply("masked_fill_t",
                       lambda a, m, v: jnp.where(m, v.astype(a.dtype), a),
                       (x, mask, value))
    return D.apply("masked_fill",
                   lambda a, m, value: jnp.where(m, jnp.asarray(value, a.dtype), a),
                   (x, mask), {"value": value})


def masked_scatter(x, mask, value, name=None):
    def _ms(a, m, v):
        flat_m = m.ravel()
        pos = jnp.cumsum(flat_m.astype(jnp.int32)) - 1
        gathered = v.ravel()[jnp.clip(pos, 0, v.size - 1)]
        return jnp.where(flat_m, gathered, a.ravel()).reshape(a.shape)
    return D.apply("masked_scatter", _ms, (x, mask, value))


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    def _tala(a, idx, axis):
        return jnp.take_along_axis(a, idx, axis=axis)
    return D.apply("take_along_axis", _tala, (arr, indices), {"axis": int(axis)})


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True,
                   broadcast=True, name=None):
    def _pala(a, idx, v, axis, reduce):
        if jnp.ndim(v) == 0:
            v = jnp.broadcast_to(v, idx.shape)
        v = v.astype(a.dtype)
        dims = [1] * a.ndim
        moved = jnp.moveaxis(a, axis, 0)
        idx_m = jnp.moveaxis(idx, axis, 0)
        v_m = jnp.moveaxis(jnp.broadcast_to(v, idx.shape), axis, 0)
        # build full index grids
        grids = jnp.meshgrid(*[jnp.arange(s) for s in idx_m.shape], indexing="ij")
        grids[0] = idx_m
        if reduce == "assign":
            out = moved.at[tuple(grids)].set(v_m)
        elif reduce in ("add", "sum"):
            out = moved.at[tuple(grids)].add(v_m)
        elif reduce in ("mul", "multiply"):
            out = moved.at[tuple(grids)].multiply(v_m)
        elif reduce == "amax":
            out = moved.at[tuple(grids)].max(v_m)
        elif reduce == "amin":
            out = moved.at[tuple(grids)].min(v_m)
        else:
            raise ValueError(f"unknown reduce {reduce}")
        return jnp.moveaxis(out, 0, axis)
    return D.apply("put_along_axis", _pala, (arr, indices, values),
                   {"axis": int(axis), "reduce": reduce})


def unbind(input, axis=0, name=None):
    n = input.shape[axis % input.ndim]

    def _unbind(a, axis, n):
        return tuple(jnp.squeeze(jax.lax.slice_in_dim(a, i, i + 1, axis=axis), axis)
                     for i in range(n))
    out = D.apply("unbind", _unbind, (input,), {"axis": int(axis), "n": n})
    return list(out)


unstack = unbind


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        return D.apply("repeat_interleave_t",
                       lambda a, r, axis, total: jnp.repeat(a, r, axis=axis,
                                                            total_repeat_length=total),
                       (x, repeats),
                       {"axis": None if axis is None else int(axis),
                        "total": int(np.asarray(repeats._data).sum())})
    return D.apply("repeat_interleave",
                   lambda a, repeats, axis: jnp.repeat(a, repeats, axis=axis),
                   (x,), {"repeats": int(repeats), "axis": None if axis is None else int(axis)})


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # Dynamic output shape: host path.
    a = np.asarray(_t(x))
    res = np.unique(a, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    a = np.asarray(_t(x))
    if axis is None:
        a = a.ravel()
        axis = 0
    mask = np.ones(a.shape[axis], dtype=bool)
    # builtins.slice: this module's `slice` op shadows the builtin
    import builtins
    sl = [builtins.slice(None)] * a.ndim
    if a.shape[axis] > 1:
        d = np.diff(a, axis=axis)
        other = tuple(i for i in range(a.ndim) if i != axis)
        mask[1:] = np.any(d != 0, axis=other) if a.ndim > 1 else (d != 0)
    sl[axis] = mask
    out = a[tuple(sl)]
    outs = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(mask) - 1
        outs.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.nonzero(mask)[0]
        counts = np.diff(np.concatenate([idx, [a.shape[axis]]]))
        outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())

    def _topk(a, k, axis, largest):
        if largest:
            vals, idx = jax.lax.top_k(jnp.moveaxis(a, axis, -1), k)
        else:
            vals, idx = jax.lax.top_k(-jnp.moveaxis(a, axis, -1), k)
            vals = -vals
        return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis).astype(jnp.int64)
    return D.apply("topk", _topk, (x,),
                   {"k": int(k), "axis": int(axis), "largest": bool(largest)})


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def _sort(a, axis, descending):
        out = jnp.sort(a, axis=axis, stable=True)
        return jnp.flip(out, axis=axis) if descending else out
    return D.apply("sort", _sort, (x,), {"axis": int(axis), "descending": bool(descending)})


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def _argsort(a, axis, descending):
        out = jnp.argsort(a, axis=axis, stable=True)
        return (jnp.flip(out, axis=axis) if descending else out).astype(jnp.int64)
    return D.apply("argsort", _argsort, (x,), {"axis": int(axis), "descending": bool(descending)})


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def _ss(seq, v, right):
        side = "right" if right else "left"
        if seq.ndim == 1:
            return jnp.searchsorted(seq, v, side=side).astype(jnp.int64)
        return jax.vmap(lambda s, vv: jnp.searchsorted(s, vv, side=side))(
            seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1])
        ).reshape(v.shape).astype(jnp.int64)
    return D.apply("searchsorted", _ss, (sorted_sequence, values), {"right": bool(right)})


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return D.apply("where", lambda c, a, b: jnp.where(c, a, b), (condition, x, y))


def nonzero(x, as_tuple=False, name=None):
    a = np.asarray(_t(x))
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i[:, None].astype(np.int64))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def one_hot(x, num_classes, name=None):
    return D.apply("one_hot",
                   lambda a, n: jax.nn.one_hot(a, n, dtype=jnp.float32),
                   (x,), {"n": int(num_classes)})


def slice(input, axes, starts, ends, name=None):
    def norm(v):
        if isinstance(v, Tensor):
            return [int(i) for i in v.tolist()]
        return [int(i.item()) if isinstance(i, Tensor) else int(i) for i in v]
    axes_l, starts_l, ends_l = [int(a) for a in axes], norm(starts), norm(ends)

    def _slice(a, axes, starts, ends):
        idx = [builtins_slice(None)] * a.ndim
        for ax, st, en in zip(axes, starts, ends):
            idx[ax] = builtins_slice(st, en)
        return a[tuple(idx)]
    return D.apply("slice", _slice, (input,),
                   {"axes": tuple(axes_l), "starts": tuple(starts_l), "ends": tuple(ends_l)})


import builtins as _builtins
builtins_slice = _builtins.slice


def strided_slice(x, axes, starts, ends, strides, name=None):
    def norm(v):
        return tuple(int(i.item()) if isinstance(i, Tensor) else int(i) for i in v)

    def _ss(a, axes, starts, ends, strides):
        idx = [builtins_slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = builtins_slice(st, en, sd)
        return a[tuple(idx)]
    return D.apply("strided_slice", _ss, (x,),
                   {"axes": tuple(int(a) for a in axes), "starts": norm(starts),
                    "ends": norm(ends), "strides": norm(strides)})


def crop(x, shape=None, offsets=None, name=None):
    shape = _shape_static(shape)
    if offsets is None:
        offsets = [0] * x.ndim
    offsets = tuple(int(o.item()) if isinstance(o, Tensor) else int(o) for o in offsets)
    full_shape = tuple(x.shape[i] if s == -1 else s for i, s in enumerate(shape))

    def _crop(a, shape, offsets):
        return jax.lax.dynamic_slice(a, offsets, shape)
    return D.apply("crop", _crop, (x,), {"shape": full_shape, "offsets": offsets})


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle conv-style: pad pairs are LAST-dim-first — (left, right,
        # top, bottom, front, back): pair 0 pads W, pair 1 pads H, pair 2
        # pads D (reference nn/functional/common.py pad contract)
        k = len(pad) // 2
        width = [(0, 0)] * nd
        if data_format.endswith("C"):  # NHWC / NLC / NDHWC: spatial dims start at 1
            spatial = list(range(1, 1 + k))
        else:  # NCHW / NCL / NCDHW: spatial dims after channel
            spatial = list(range(nd - k, nd))
        for i, dim in enumerate(reversed(spatial)):
            width[dim] = (pad[2 * i], pad[2 * i + 1])

    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]

    def _pad(a, width, jmode, value):
        if jmode == "constant":
            return jnp.pad(a, width, mode=jmode, constant_values=value)
        return jnp.pad(a, width, mode=jmode)
    return D.apply("pad", _pad, (x,),
                   {"width": tuple(width), "jmode": jmode, "value": value})


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1, name=None):
    def _shard(a, index_num, nshards, shard_id, ignore_value):
        size = (index_num + nshards - 1) // nshards
        lo, hi = shard_id * size, (shard_id + 1) * size
        in_range = (a >= lo) & (a < hi)
        return jnp.where(in_range, a - lo, ignore_value)
    return D.apply("shard_index", _shard, (input,),
                   {"index_num": int(index_num), "nshards": int(nshards),
                    "shard_id": int(shard_id), "ignore_value": int(ignore_value)})


def as_complex(x, name=None):
    return D.apply("as_complex", lambda a: jax.lax.complex(a[..., 0], a[..., 1]), (x,))


def as_real(x, name=None):
    return D.apply("as_real", lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), (x,))


def atleast_1d(*inputs, name=None):
    outs = [reshape(t, [1]) if t.ndim == 0 else t for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = []
    for t in inputs:
        if t.ndim == 0:
            outs.append(reshape(t, [1, 1]))
        elif t.ndim == 1:
            outs.append(reshape(t, [1, -1]))
        else:
            outs.append(t)
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = []
    for t in inputs:
        t2 = atleast_2d(t)
        outs.append(unsqueeze(t2, -1) if t2.ndim == 2 else t2)
    return outs[0] if len(outs) == 1 else outs


def select_scatter(x, values, axis, index, name=None):
    def _impl(a, v, axis, index):
        moved = jnp.moveaxis(a, axis, 0)
        out = moved.at[index].set(v.astype(a.dtype))
        return jnp.moveaxis(out, 0, axis)
    return D.apply("select_scatter", _impl, (x, values), {"axis": int(axis), "index": int(index)})


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def _impl(a, b, offset, axis1, axis2):
        n = builtins_min(a.shape[axis1], a.shape[axis2])
        i = jnp.arange(b.shape[-1])
        rows = i - builtins_min(offset, 0) * 0 + (0 if offset >= 0 else -offset)
        cols = i + (offset if offset >= 0 else 0)
        a_m = jnp.moveaxis(jnp.moveaxis(a, axis1, 0), axis2 if axis2 > axis1 else axis2 + 1, 1)
        out = a_m.at[rows, cols].set(jnp.moveaxis(b, -1, 0))
        out = jnp.moveaxis(jnp.moveaxis(out, 1, axis2 if axis2 > axis1 else axis2 + 1), 0, axis1)
        return out
    return D.apply("diagonal_scatter", _impl, (x, y),
                   {"offset": int(offset), "axis1": int(axis1), "axis2": int(axis2)})


builtins_min = min


def tolist(x):
    return x.tolist()
