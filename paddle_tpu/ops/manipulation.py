"""Shape / indexing / rearrangement ops.

Capability parity with /root/reference/python/paddle/tensor/manipulation.py
and search.py; pure-jnp kernels through the eager dispatcher.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dispatch as D
from ..core.dtype import convert_dtype, to_jax_dtype
from ..core.tensor import Tensor

__all__ = [
    "reshape", "reshape_", "transpose", "moveaxis", "swapaxes", "concat",
    "stack", "vstack", "hstack", "dstack", "split", "vsplit", "hsplit",
    "dsplit", "tensor_split", "chunk", "squeeze", "squeeze_", "unsqueeze",
    "unsqueeze_", "flatten", "flip", "fliplr", "flipud", "roll", "tile",
    "expand", "expand_as", "broadcast_to", "broadcast_tensors", "gather",
    "gather_nd", "scatter", "scatter_", "scatter_nd", "scatter_nd_add",
    "index_select", "index_sample", "index_add", "index_put", "index_fill",
    "masked_select", "masked_fill", "masked_scatter", "take_along_axis",
    "put_along_axis", "unbind", "repeat_interleave", "unique",
    "unique_consecutive", "topk", "sort", "argsort", "searchsorted", "where",
    "nonzero", "one_hot", "unstack", "strided_slice", "slice", "crop",
    "pad", "shard_index", "rotate90", "as_complex", "as_real", "view",
    "view_as", "atleast_1d", "atleast_2d", "atleast_3d", "select_scatter",
    "diagonal_scatter", "flatten_", "tolist", "unflatten", "bucketize",
]


def _t(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _shape_static(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.tolist())
    out = []
    for s in shape:
        out.append(int(s.item()) if isinstance(s, Tensor) else int(s))
    return tuple(out)


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def _split_sections(x_shape, num_or_sections, axis):
    axis = axis % len(x_shape)
    n = x_shape[axis]
    if isinstance(num_or_sections, int):
        assert n % num_or_sections == 0, (
            f"dim {n} not divisible into {num_or_sections} sections")
        sizes = [n // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        neg = [i for i, s in enumerate(sizes) if s < 0]
        if neg:
            known = builtins_sum(s for s in sizes if s >= 0)
            sizes[neg[0]] = n - known
    offsets = np.cumsum([0] + sizes[:-1]).tolist()
    return sizes, offsets, axis


builtins_sum = sum


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    sizes, offsets, axis = _split_sections(tuple(x.shape), num_or_sections, axis)

    def _split(a, sizes, offsets, axis):
        return tuple(jax.lax.slice_in_dim(a, o, o + s, axis=axis)
                     for s, o in zip(sizes, offsets))
    out = D.apply("split", _split, (x,),
                  {"sizes": tuple(sizes), "offsets": tuple(offsets), "axis": axis})
    return list(out) if isinstance(out, tuple) else [out]


def tensor_split(x, num_or_indices, axis=0, name=None):
    n = x.shape[axis % x.ndim]
    if isinstance(num_or_indices, int):
        base, extra = divmod(n, num_or_indices)
        sizes = [base + (1 if i < extra else 0) for i in range(num_or_indices)]
    else:
        idx = [0] + [int(i) for i in num_or_indices] + [n]
        sizes = [idx[i + 1] - idx[i] for i in range(len(idx) - 1)]
    return split(x, sizes, axis)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    out = flatten(x, start_axis, stop_axis)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


rotate90 = None  # placeholder; rot90 lives in math


def broadcast_tensors(inputs, name=None):
    shape = np.broadcast_shapes(*[tuple(t.shape) for t in inputs])
    return [broadcast_to(t, shape) for t in inputs]


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def index_put(x, indices, value, accumulate=False, name=None):
    idxs = tuple(indices)

    def _index_put(a, v, *idx, accumulate):
        if accumulate:
            return a.at[idx].add(v)
        return a.at[idx].set(v)
    return D.apply("index_put", _index_put, (x, value) + idxs,
                   {"accumulate": bool(accumulate)})


def masked_select(x, mask, name=None):
    # Dynamic output size: host-sync path (same as reference GPU sync).
    a, m = np.asarray(_t(x)), np.asarray(_t(mask))
    return Tensor(jnp.asarray(a[m]))


def unbind(input, axis=0, name=None):
    n = input.shape[axis % input.ndim]

    def _unbind(a, axis, n):
        return tuple(jnp.squeeze(jax.lax.slice_in_dim(a, i, i + 1, axis=axis), axis)
                     for i in range(n))
    out = D.apply("unbind", _unbind, (input,), {"axis": int(axis), "n": n})
    return list(out)


unstack = unbind


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # Dynamic output shape: host path.
    a = np.asarray(_t(x))
    res = np.unique(a, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    a = np.asarray(_t(x))
    if axis is None:
        a = a.ravel()
        axis = 0
    mask = np.ones(a.shape[axis], dtype=bool)
    # builtins.slice: this module's `slice` op shadows the builtin
    import builtins
    sl = [builtins.slice(None)] * a.ndim
    if a.shape[axis] > 1:
        d = np.diff(a, axis=axis)
        other = tuple(i for i in range(a.ndim) if i != axis)
        mask[1:] = np.any(d != 0, axis=other) if a.ndim > 1 else (d != 0)
    sl[axis] = mask
    out = a[tuple(sl)]
    outs = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(mask) - 1
        outs.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.nonzero(mask)[0]
        counts = np.diff(np.concatenate([idx, [a.shape[axis]]]))
        outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return outs[0] if len(outs) == 1 else tuple(outs)






def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return D.apply("where", lambda c, a, b: jnp.where(c, a, b), (condition, x, y))


def nonzero(x, as_tuple=False, name=None):
    a = np.asarray(_t(x))
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i[:, None].astype(np.int64))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))




def slice(input, axes, starts, ends, name=None):
    def norm(v):
        if isinstance(v, Tensor):
            return [int(i) for i in v.tolist()]
        return [int(i.item()) if isinstance(i, Tensor) else int(i) for i in v]
    axes_l, starts_l, ends_l = [int(a) for a in axes], norm(starts), norm(ends)

    def _slice(a, axes, starts, ends):
        idx = [builtins_slice(None)] * a.ndim
        for ax, st, en in zip(axes, starts, ends):
            idx[ax] = builtins_slice(st, en)
        return a[tuple(idx)]
    return D.apply("slice", _slice, (input,),
                   {"axes": tuple(axes_l), "starts": tuple(starts_l), "ends": tuple(ends_l)})


import builtins as _builtins
builtins_slice = _builtins.slice














def atleast_1d(*inputs, name=None):
    outs = [reshape(t, [1]) if t.ndim == 0 else t for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = []
    for t in inputs:
        if t.ndim == 0:
            outs.append(reshape(t, [1, 1]))
        elif t.ndim == 1:
            outs.append(reshape(t, [1, -1]))
        else:
            outs.append(t)
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = []
    for t in inputs:
        t2 = atleast_2d(t)
        outs.append(unsqueeze(t2, -1) if t2.ndim == 2 else t2)
    return outs[0] if len(outs) == 1 else outs






builtins_min = min


def tolist(x):
    return x.tolist()


# ---------------------------------------------------------------------------
# Kernel-driven ops (third tranche): the yaml schema is the source of truth;
# wrappers are generated (ops/generated/op_wrappers.py) from `kernel:` fields
# over ops/kernels.py.  Re-exported here so `from paddle_tpu.ops.manipulation
# import reshape` and in-module callers (view, *_ inplace variants,
# broadcast_tensors) keep resolving.
# ---------------------------------------------------------------------------
from .generated.op_wrappers import (  # noqa: E402,F401
    argsort, broadcast_to, expand, expand_as, flatten, flip, fliplr, flipud,
    gather, gather_nd, index_add, index_fill, index_sample, index_select,
    masked_fill, masked_scatter, moveaxis, put_along_axis, repeat_interleave,
    reshape, roll, scatter, scatter_nd, scatter_nd_add, sort, squeeze,
    swapaxes, take_along_axis, tile, topk, transpose, unflatten, unsqueeze,
)

from .generated.op_wrappers import (  # noqa: E402,F401
    concat, dstack, hstack, stack, vstack,
)


# kernel-driven since r5 (generated from ops.yaml `kernel:` over
# ops/kernels.py); re-exported here so intra-repo imports keep working
from .generated.op_wrappers import (  # noqa: E402,F401
    as_complex,
    as_real,
    bucketize,
    crop,
    diagonal_scatter,
    one_hot,
    pad,
    searchsorted,
    select_scatter,
    shard_index,
    strided_slice,
)
