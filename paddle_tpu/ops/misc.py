"""Remaining reference op-surface coverage: casting, structural fills,
sequence/beam utilities, sampling, and norm reductions.

Reference counterparts are cited per op (python/paddle/tensor/*.py wrappers
over phi kernels, paddle/phi/ops/yaml/ops.yaml entries).  All device ops are
pure-jnp kernels through the eager dispatcher; `edit_distance` is host-side
(data-dependent DP, like the reference's CPU kernel).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dispatch as D
from ..core.dtype import to_jax_dtype
from ..core.tensor import Tensor

__all__ = [
    "cast", "shape", "mv", "inverse", "multiplex", "reverse", "fill_",
    "fill_diagonal", "fill_diagonal_tensor", "diag_embed", "clip_by_norm",
    "mean_all", "frobenius_norm", "squared_l2_norm", "sequence_mask",
    "gather_tree", "top_p_sampling", "temporal_shift", "edit_distance",
    "viterbi_decode", "as_strided", "slice_scatter", "gammainc",
    "gammaincc", "multigammaln",
]


def _t(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def cast(x, dtype):
    """paddle.cast (reference tensor/manipulation.py cast -> _C_ops.cast)."""
    return D.apply("cast", lambda a, dt: a.astype(dt), (x,),
                   {"dt": to_jax_dtype(dtype)})


def shape(x, name=None):
    """Shape as an int32 tensor (reference ops.yaml `shape`/`shape64`)."""
    return Tensor(jnp.asarray(tuple(_t(x).shape), jnp.int32))


def mv(x, vec, name=None):
    """Matrix-vector product (reference tensor/linalg.py mv)."""
    return D.apply("mv", lambda a, b: a @ b, (x, vec))


def inverse(x, name=None):
    """Matrix inverse (reference tensor/math.py inverse)."""
    return D.apply("inverse", jnp.linalg.inv, (x,))


def multiplex(inputs, index, name=None):
    """Row-wise select across candidate tensors: out[i] = inputs[index[i]][i]
    (reference tensor/math.py multiplex)."""
    def impl(idx, *cands):
        stacked = jnp.stack(cands, axis=0)             # [C, B, ...]
        sel = idx.reshape(-1).astype(jnp.int32)        # [B]
        rows = jnp.arange(stacked.shape[1])
        return stacked[sel, rows]

    return D.apply("multiplex", impl, (index, *inputs))


def reverse(x, axis, name=None):
    """Alias of flip (reference legacy `reverse` op)."""
    from .manipulation import flip
    return flip(x, axis)


def fill_(x, value):
    """In-place fill (reference Tensor.fill_, ops.yaml `fill`)."""
    arr = _t(x)
    x._data = jnp.full_like(arr, value)
    return x


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    """Fill the main diagonal (reference Tensor.fill_diagonal_;
    wrap continues the diagonal in tall matrices like the reference)."""
    def impl(a, value, offset, wrap):
        n, m = a.shape[-2], a.shape[-1]
        i = jnp.arange(n)[:, None]
        j = jnp.arange(m)[None, :]
        diag = (j - i) == offset
        if wrap and n > m:
            period = m + 1
            diag = ((i * m + j) % period == offset % period) if offset == 0 \
                else diag
        return jnp.where(diag, jnp.asarray(value, a.dtype), a)

    return D.apply("fill_diagonal", impl, (x,),
                   {"value": float(value), "offset": int(offset),
                    "wrap": bool(wrap)})


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """Write tensor y along the (dim1, dim2) diagonal of x
    (reference Tensor.fill_diagonal_tensor)."""
    def impl(a, b, offset, dim1, dim2):
        nd = a.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        perm = [d for d in range(nd) if d not in (d1, d2)] + [d1, d2]
        ap = jnp.transpose(a, perm)
        n, m = ap.shape[-2], ap.shape[-1]
        i = jnp.arange(n)[:, None]
        j = jnp.arange(m)[None, :]
        mask = (j - i) == offset
        # scatter b (last dim runs along the diagonal) into a carrier
        dlen = min(n, m - offset) if offset >= 0 else min(n + offset, m)
        di = jnp.arange(dlen)
        rows = di if offset >= 0 else di - offset
        cols = di + max(0, offset)
        carrier = jnp.zeros_like(ap).at[..., rows, cols].set(
            b.astype(a.dtype))
        out = jnp.where(mask, carrier, ap)
        inv = np.argsort(perm)
        return jnp.transpose(out, inv)

    return D.apply("fill_diagonal_tensor", impl, (x, y),
                   {"offset": int(offset), "dim1": int(dim1),
                    "dim2": int(dim2)})


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    """Batched diagonal embedding (reference tensor/creation.py
    diag_embed)."""
    def impl(a, offset, dim1, dim2):
        n = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        di = jnp.arange(a.shape[-1])
        rows = di + max(0, -offset)
        cols = di + max(0, offset)
        out = base.at[..., rows, cols].set(a)
        nd = out.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        # currently the two new dims are the last two; move them
        perm = list(range(nd - 2))
        order = sorted([d1, d2])
        for pos, d in zip(order, (nd - 2, nd - 1)):
            perm.insert(pos, d)
        return jnp.transpose(out, perm)

    return D.apply("diag_embed", impl, (x,),
                   {"offset": int(offset), "dim1": int(dim1),
                    "dim2": int(dim2)})


def clip_by_norm(x, max_norm, name=None):
    """Scale down to L2 norm <= max_norm (reference ops.yaml
    clip_by_norm; nn/clip.py ClipGradByNorm semantics)."""
    def impl(a, max_norm):
        norm = jnp.sqrt(jnp.sum(a.astype(jnp.float32) ** 2))
        scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12),
                          1.0)
        return (a.astype(jnp.float32) * scale).astype(a.dtype)

    return D.apply("clip_by_norm", impl, (x,), {"max_norm": float(max_norm)})


def mean_all(x, name=None):
    """Scalar mean over every element (reference ops.yaml mean_all)."""
    return D.apply("mean_all", lambda a: jnp.mean(a), (x,))


def frobenius_norm(x, axis=None, keepdim=False, name=None):
    """(reference tensor/linalg.py frobenius_norm branch of norm)."""
    def impl(a, axis, keepdim):
        af = a.astype(jnp.float32)
        out = jnp.sqrt(jnp.sum(af * af, axis=axis, keepdims=keepdim))
        return out.astype(a.dtype)

    ax = tuple(int(a) for a in axis) if isinstance(axis, (tuple, list)) \
        else (None if axis is None else int(axis))
    return D.apply("frobenius_norm", impl, (x,),
                   {"axis": ax, "keepdim": bool(keepdim)})


def squared_l2_norm(x, name=None):
    """sum(x^2) as a scalar (reference ops.yaml squared_l2_norm — the grad
    -clip helper kernel)."""
    return D.apply("squared_l2_norm",
                   lambda a: jnp.sum(a.astype(jnp.float32) ** 2), (x,))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Length vector -> boolean-ish mask [..., maxlen] (reference
    tensor/creation.py sequence_mask / ops.yaml sequence_mask)."""
    lens = _t(x)
    if maxlen is None:
        maxlen = int(jnp.max(lens)) if lens.size else 0

    def impl(lens, maxlen, dt):
        pos = jnp.arange(maxlen, dtype=lens.dtype)
        return (pos[None, :] < lens[..., None].reshape(-1, 1)).reshape(
            lens.shape + (maxlen,)).astype(dt)

    return D.apply("sequence_mask", impl, (x,),
                   {"maxlen": int(maxlen), "dt": to_jax_dtype(dtype)})


def gather_tree(ids, parents, name=None):
    """Reconstruct full beam-search sequences from per-step ids + parent
    beam indices (reference tensor/manipulation.py gather_tree, kernel
    phi/kernels/gather_tree_kernel).  ids/parents: [T, B, beam]."""
    def impl(ids, parents):
        T = ids.shape[0]
        beams = jnp.broadcast_to(
            jnp.arange(ids.shape[2], dtype=parents.dtype)[None, :],
            (ids.shape[1], ids.shape[2]))

        def step(carry, t):
            beam = carry                      # [B, beam] beam index at t+1
            tt = T - 1 - t
            out = jnp.take_along_axis(ids[tt], beam, axis=1)
            parent = jnp.take_along_axis(parents[tt], beam,
                                         axis=1).astype(beam.dtype)
            return parent, out

        _, rev = jax.lax.scan(step, beams, jnp.arange(T))
        return jnp.flip(rev, axis=0)

    return D.apply("gather_tree", impl, (ids, parents))


def top_p_sampling(x, ps, threshold=None, seed=-1, name=None):
    """Nucleus sampling over probabilities x [B, V] with per-row p
    (reference ops.yaml top_p_sampling).  Returns (sampled values,
    sampled ids)."""
    def impl(probs, ps, seed):
        sort_idx = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        keep = cum - sorted_p <= ps[:, None]     # always keep the top token
        trimmed = jnp.where(keep, sorted_p, 0.0)
        trimmed = trimmed / jnp.sum(trimmed, axis=-1, keepdims=True)
        key = jax.random.PRNGKey(seed if seed >= 0 else 0)
        pick = jax.random.categorical(
            key, jnp.log(jnp.maximum(trimmed, 1e-38)), axis=-1)
        ids = jnp.take_along_axis(sort_idx, pick[:, None], axis=-1)
        vals = jnp.take_along_axis(probs, ids, axis=-1)
        return vals, ids.astype(jnp.int64)

    if seed < 0:
        import random as _r
        seed = _r.randint(0, 2 ** 31 - 1)
    return D.apply("top_p_sampling", impl, (x, ps), {"seed": int(seed)},
                   num_outputs=2)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """Shift a fraction of channels one step along the segment (time) dim
    (reference nn/functional/extension.py temporal_shift)."""
    def impl(a, seg_num, shift_ratio, data_format):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        back = jnp.pad(v[:, 1:, :c1], ((0, 0), (0, 1), (0, 0), (0, 0),
                                       (0, 0)))
        fwd = jnp.pad(v[:, :-1, c1:c2], ((0, 0), (1, 0), (0, 0), (0, 0),
                                         (0, 0)))
        out = jnp.concatenate([back, fwd, v[:, :, c2:]], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return D.apply("temporal_shift", impl, (x,),
                   {"seg_num": int(seg_num),
                    "shift_ratio": float(shift_ratio),
                    "data_format": str(data_format)})


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Batched Levenshtein distance (reference nn/functional/loss
    edit_distance; CPU kernel phi/kernels/cpu/edit_distance_kernel.cc).
    Host-side: the DP is data-dependent, the reference also runs it on CPU.
    Returns (distance [B,1] float32, sequence_num [1] int64)."""
    hyp = np.asarray(input.numpy() if isinstance(input, Tensor) else input)
    ref = np.asarray(label.numpy() if isinstance(label, Tensor) else label)
    hl = (np.asarray(input_length.numpy() if isinstance(input_length, Tensor)
                     else input_length).reshape(-1)
          if input_length is not None else
          np.full((hyp.shape[0],), hyp.shape[1], np.int64))
    ll = (np.asarray(label_length.numpy() if isinstance(label_length, Tensor)
                     else label_length).reshape(-1)
          if label_length is not None else
          np.full((ref.shape[0],), ref.shape[1], np.int64))
    ignored = set(ignored_tokens or ())
    out = np.zeros((hyp.shape[0], 1), np.float32)
    for b in range(hyp.shape[0]):
        h = [t for t in hyp[b][:hl[b]].tolist() if t not in ignored]
        r = [t for t in ref[b][:ll[b]].tolist() if t not in ignored]
        dp = np.arange(len(r) + 1, dtype=np.float32)
        for i, th in enumerate(h, 1):
            prev = dp.copy()
            dp[0] = i
            for j, tr in enumerate(r, 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (th != tr))
        d = dp[len(r)]
        if normalized:
            d = d / max(len(r), 1)
        out[b, 0] = d
    return (Tensor(jnp.asarray(out)),
            Tensor(jnp.asarray([hyp.shape[0]], jnp.int64)))


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decoding (reference text/viterbi_decode.py /
    ops.yaml viterbi_decode): potentials [B, T, N], transition [N(+2), ...].
    Returns (scores [B], paths [B, T])."""
    def impl(emis, trans, lens, with_tag):
        B, T, N = emis.shape
        emis = emis.astype(jnp.float32)
        trans = trans.astype(jnp.float32)
        if with_tag:
            # rows/cols N and N+1 are BOS/EOS (reference convention)
            start = trans[N, :N]
            stop = trans[:N, N + 1]
            tr = trans[:N, :N]
        else:
            start = jnp.zeros((N,), jnp.float32)
            stop = jnp.zeros((N,), jnp.float32)
            tr = trans

        alpha0 = emis[:, 0] + start[None, :]

        def step(carry, t):
            alpha = carry                       # [B, N]
            scores = alpha[:, :, None] + tr[None, :, :] + emis[:, t][:, None, :]
            best = jnp.max(scores, axis=1)
            back = jnp.argmax(scores, axis=1)
            # positions past the sequence keep their alpha (masked)
            live = (t < lens)[:, None]
            return jnp.where(live, best, alpha), back

        alpha, backs = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        final = alpha + stop[None, :] if with_tag else alpha
        score = jnp.max(final, axis=-1)
        last = jnp.argmax(final, axis=-1)

        def walk(carry, t):
            tag = carry                        # [B]
            tt = T - 2 - t
            prev = jnp.take_along_axis(backs[tt], tag[:, None], axis=1)[:, 0]
            live = (tt + 1) < lens
            newtag = jnp.where(live, prev, tag)
            return newtag, tag

        # rev emits tags at positions T-1 .. 1; the final carry is position 0
        tag0, rev = jax.lax.scan(walk, last, jnp.arange(T - 1))
        path = jnp.concatenate([tag0[:, None], jnp.flip(rev.T, axis=1)],
                               axis=1)
        return score, path.astype(jnp.int64)

    return D.apply("viterbi_decode", impl,
                   (potentials, transition_params, lengths),
                   {"with_tag": bool(include_bos_eos_tag)}, num_outputs=2)


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view materialized via gather (reference
    tensor/manipulation.py as_strided over strided TensorImpl — XLA has no
    aliasing views, so this produces the same VALUES as a copy)."""
    def impl(a, shape, stride, offset):
        flat = a.reshape(-1)
        idx = jnp.asarray(offset, jnp.int32)
        for n, s in zip(shape, stride):
            idx = idx[..., None] + jnp.arange(n, dtype=jnp.int32) * s
        return jnp.take(flat, idx.reshape(shape), mode="clip")

    return D.apply("as_strided", impl, (x,),
                   {"shape": tuple(int(s) for s in shape),
                    "stride": tuple(int(s) for s in stride),
                    "offset": int(offset)})


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    """Write `value` into strided slices of x (reference
    tensor/manipulation.py slice_scatter)."""
    def impl(a, v, axes, starts, ends, strides):
        idx = [slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = slice(s, e, st)
        return a.at[tuple(idx)].set(v.astype(a.dtype))

    return D.apply("slice_scatter", impl, (x, value),
                   {"axes": tuple(int(a) for a in axes),
                    "starts": tuple(int(s) for s in starts),
                    "ends": tuple(int(e) for e in ends),
                    "strides": tuple(int(s) for s in strides)})


def gammainc(x, y, name=None):
    """Regularized lower incomplete gamma P(x, y) (reference gammainc)."""
    return D.apply("gammainc",
                   lambda a, b: jax.scipy.special.gammainc(
                       a.astype(jnp.float32), b.astype(jnp.float32)), (x, y))


def gammaincc(x, y, name=None):
    """Regularized upper incomplete gamma Q(x, y) (reference gammaincc)."""
    return D.apply("gammaincc",
                   lambda a, b: jax.scipy.special.gammaincc(
                       a.astype(jnp.float32), b.astype(jnp.float32)), (x, y))


def multigammaln(x, p, name=None):
    """Log multivariate gamma (reference tensor/math.py multigammaln)."""
    def impl(a, p):
        af = a.astype(jnp.float32)
        const = p * (p - 1) / 4.0 * jnp.log(jnp.pi).astype(jnp.float32)
        terms = sum(jax.scipy.special.gammaln(af - i / 2.0)
                    for i in range(p))
        return const + terms

    return D.apply("multigammaln", impl, (x,), {"p": int(p)})
