"""Remaining reference op-surface coverage: casting, structural fills,
sequence/beam utilities, sampling, and norm reductions.

Reference counterparts are cited per op (python/paddle/tensor/*.py wrappers
over phi kernels, paddle/phi/ops/yaml/ops.yaml entries).  All device ops are
pure-jnp kernels through the eager dispatcher; `edit_distance` is host-side
(data-dependent DP, like the reference's CPU kernel).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dispatch as D
from ..core.dtype import to_jax_dtype
from ..core.tensor import Tensor

__all__ = [
    "cast", "shape", "mv", "inverse", "multiplex", "reverse", "fill_",
    "fill_diagonal", "fill_diagonal_tensor", "diag_embed", "clip_by_norm",
    "mean_all", "frobenius_norm", "squared_l2_norm", "sequence_mask",
    "gather_tree", "top_p_sampling", "temporal_shift", "edit_distance",
    "viterbi_decode", "as_strided", "slice_scatter", "gammainc",
    "gammaincc", "multigammaln",
]


def _t(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)




def shape(x, name=None):
    """Shape as an int32 tensor (reference ops.yaml `shape`/`shape64`)."""
    return Tensor(jnp.asarray(tuple(_t(x).shape), jnp.int32))






def multiplex(inputs, index, name=None):
    """Row-wise select across candidate tensors: out[i] = inputs[index[i]][i]
    (reference tensor/math.py multiplex)."""
    def impl(idx, *cands):
        stacked = jnp.stack(cands, axis=0)             # [C, B, ...]
        sel = idx.reshape(-1).astype(jnp.int32)        # [B]
        rows = jnp.arange(stacked.shape[1])
        return stacked[sel, rows]

    return D.apply("multiplex", impl, (index, *inputs))




def fill_(x, value):
    """In-place fill (reference Tensor.fill_, ops.yaml `fill`)."""
    arr = _t(x)
    x._data = jnp.full_like(arr, value)
    return x
















def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Length vector -> boolean-ish mask [..., maxlen] (reference
    tensor/creation.py sequence_mask / ops.yaml sequence_mask)."""
    lens = _t(x)
    if maxlen is None:
        maxlen = int(jnp.max(lens)) if lens.size else 0

    def impl(lens, maxlen, dt):
        pos = jnp.arange(maxlen, dtype=lens.dtype)
        return (pos[None, :] < lens[..., None].reshape(-1, 1)).reshape(
            lens.shape + (maxlen,)).astype(dt)

    return D.apply("sequence_mask", impl, (x,),
                   {"maxlen": int(maxlen), "dt": to_jax_dtype(dtype)})


def gather_tree(ids, parents, name=None):
    """Reconstruct full beam-search sequences from per-step ids + parent
    beam indices (reference tensor/manipulation.py gather_tree, kernel
    phi/kernels/gather_tree_kernel).  ids/parents: [T, B, beam]."""
    def impl(ids, parents):
        T = ids.shape[0]
        beams = jnp.broadcast_to(
            jnp.arange(ids.shape[2], dtype=parents.dtype)[None, :],
            (ids.shape[1], ids.shape[2]))

        def step(carry, t):
            beam = carry                      # [B, beam] beam index at t+1
            tt = T - 1 - t
            out = jnp.take_along_axis(ids[tt], beam, axis=1)
            parent = jnp.take_along_axis(parents[tt], beam,
                                         axis=1).astype(beam.dtype)
            return parent, out

        _, rev = jax.lax.scan(step, beams, jnp.arange(T))
        return jnp.flip(rev, axis=0)

    return D.apply("gather_tree", impl, (ids, parents))


def top_p_sampling(x, ps, threshold=None, seed=-1, name=None):
    """Nucleus sampling over probabilities x [B, V] with per-row p
    (reference ops.yaml top_p_sampling).  Returns (sampled values,
    sampled ids)."""
    def impl(probs, ps, seed):
        sort_idx = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        keep = cum - sorted_p <= ps[:, None]     # always keep the top token
        trimmed = jnp.where(keep, sorted_p, 0.0)
        trimmed = trimmed / jnp.sum(trimmed, axis=-1, keepdims=True)
        key = jax.random.PRNGKey(seed if seed >= 0 else 0)
        pick = jax.random.categorical(
            key, jnp.log(jnp.maximum(trimmed, 1e-38)), axis=-1)
        ids = jnp.take_along_axis(sort_idx, pick[:, None], axis=-1)
        vals = jnp.take_along_axis(probs, ids, axis=-1)
        return vals, ids.astype(jnp.int64)

    if seed < 0:
        import random as _r
        seed = _r.randint(0, 2 ** 31 - 1)
    return D.apply("top_p_sampling", impl, (x, ps), {"seed": int(seed)},
                   num_outputs=2)




def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Batched Levenshtein distance (reference nn/functional/loss
    edit_distance; CPU kernel phi/kernels/cpu/edit_distance_kernel.cc).
    Host-side: the DP is data-dependent, the reference also runs it on CPU.
    Returns (distance [B,1] float32, sequence_num [1] int64)."""
    hyp = np.asarray(input.numpy() if isinstance(input, Tensor) else input)
    ref = np.asarray(label.numpy() if isinstance(label, Tensor) else label)
    hl = (np.asarray(input_length.numpy() if isinstance(input_length, Tensor)
                     else input_length).reshape(-1)
          if input_length is not None else
          np.full((hyp.shape[0],), hyp.shape[1], np.int64))
    ll = (np.asarray(label_length.numpy() if isinstance(label_length, Tensor)
                     else label_length).reshape(-1)
          if label_length is not None else
          np.full((ref.shape[0],), ref.shape[1], np.int64))
    ignored = set(ignored_tokens or ())
    out = np.zeros((hyp.shape[0], 1), np.float32)
    for b in range(hyp.shape[0]):
        h = [t for t in hyp[b][:hl[b]].tolist() if t not in ignored]
        r = [t for t in ref[b][:ll[b]].tolist() if t not in ignored]
        dp = np.arange(len(r) + 1, dtype=np.float32)
        for i, th in enumerate(h, 1):
            prev = dp.copy()
            dp[0] = i
            for j, tr in enumerate(r, 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (th != tr))
        d = dp[len(r)]
        if normalized:
            d = d / max(len(r), 1)
        out[b, 0] = d
    return (Tensor(jnp.asarray(out)),
            Tensor(jnp.asarray([hyp.shape[0]], jnp.int64)))


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decoding (reference text/viterbi_decode.py /
    ops.yaml viterbi_decode): potentials [B, T, N], transition [N(+2), ...].
    Returns (scores [B], paths [B, T])."""
    def impl(emis, trans, lens, with_tag):
        B, T, N = emis.shape
        emis = emis.astype(jnp.float32)
        trans = trans.astype(jnp.float32)
        if with_tag:
            # rows/cols N and N+1 are BOS/EOS (reference convention)
            start = trans[N, :N]
            stop = trans[:N, N + 1]
            tr = trans[:N, :N]
        else:
            start = jnp.zeros((N,), jnp.float32)
            stop = jnp.zeros((N,), jnp.float32)
            tr = trans

        alpha0 = emis[:, 0] + start[None, :]

        def step(carry, t):
            alpha = carry                       # [B, N]
            scores = alpha[:, :, None] + tr[None, :, :] + emis[:, t][:, None, :]
            best = jnp.max(scores, axis=1)
            back = jnp.argmax(scores, axis=1)
            # positions past the sequence keep their alpha (masked)
            live = (t < lens)[:, None]
            return jnp.where(live, best, alpha), back

        alpha, backs = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        final = alpha + stop[None, :] if with_tag else alpha
        score = jnp.max(final, axis=-1)
        last = jnp.argmax(final, axis=-1)

        def walk(carry, t):
            tag = carry                        # [B]
            tt = T - 2 - t
            prev = jnp.take_along_axis(backs[tt], tag[:, None], axis=1)[:, 0]
            live = (tt + 1) < lens
            newtag = jnp.where(live, prev, tag)
            return newtag, tag

        # rev emits tags at positions T-1 .. 1; the final carry is position 0
        tag0, rev = jax.lax.scan(walk, last, jnp.arange(T - 1))
        path = jnp.concatenate([tag0[:, None], jnp.flip(rev.T, axis=1)],
                               axis=1)
        return score, path.astype(jnp.int64)

    return D.apply("viterbi_decode", impl,
                   (potentials, transition_params, lengths),
                   {"with_tag": bool(include_bos_eos_tag)}, num_outputs=2)


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view materialized via gather (reference
    tensor/manipulation.py as_strided over strided TensorImpl — XLA has no
    aliasing views, so this produces the same VALUES as a copy)."""
    def impl(a, shape, stride, offset):
        flat = a.reshape(-1)
        idx = jnp.asarray(offset, jnp.int32)
        for n, s in zip(shape, stride):
            idx = idx[..., None] + jnp.arange(n, dtype=jnp.int32) * s
        return jnp.take(flat, idx.reshape(shape), mode="clip")

    return D.apply("as_strided", impl, (x,),
                   {"shape": tuple(int(s) for s in shape),
                    "stride": tuple(int(s) for s in stride),
                    "offset": int(offset)})


# kernel-driven since r5 (generated from ops.yaml `kernel:` over
# ops/kernels.py); re-exported here so intra-repo imports keep working
from .generated.op_wrappers import (  # noqa: E402,F401
    cast,
    clip_by_norm,
    diag_embed,
    fill_diagonal,
    fill_diagonal_tensor,
    frobenius_norm,
    gammainc,
    gammaincc,
    inverse,
    mean_all,
    multigammaln,
    mv,
    reverse,
    slice_scatter,
    squared_l2_norm,
    temporal_shift,
)
