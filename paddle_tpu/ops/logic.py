"""Comparison / logical / bitwise ops.

Parity with /root/reference/python/paddle/tensor/logic.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core import dispatch as D
from ..core.tensor import Tensor

__all__ = [
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_not", "bitwise_xor", "bitwise_invert",
    "isclose", "allclose", "equal_all", "is_tensor", "is_empty", "is_complex",
    "is_floating_point", "is_integer",
]




def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return D.apply("isclose",
                   lambda a, b, rtol, atol, equal_nan: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                   (x, y), {"rtol": float(rtol), "atol": float(atol), "equal_nan": bool(equal_nan)})


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return D.apply("allclose",
                   lambda a, b, rtol, atol, equal_nan: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                   (x, y), {"rtol": float(rtol), "atol": float(atol), "equal_nan": bool(equal_nan)})


def equal_all(x, y, name=None):
    return D.apply("equal_all",
                   lambda a, b: jnp.asarray(a.shape == b.shape and bool(jnp.all(a == b))
                                            if a.shape == b.shape else False)
                   if a.shape != b.shape else jnp.all(a == b),
                   (x, y))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_complex(x):
    return x.dtype.is_complex


def is_floating_point(x):
    return x.dtype.is_floating_point


def is_integer(x):
    return x.dtype.is_integer


# kernel-driven (yaml source of truth) — see ops/kernels.py
from .generated.op_wrappers import (  # noqa: E402,F401
    equal, not_equal, less_than, less_equal, greater_than, greater_equal,
    logical_and, logical_or, logical_xor, logical_not, bitwise_and,
    bitwise_or, bitwise_xor, bitwise_not,
)

bitwise_invert = bitwise_not
