"""Comparison / logical / bitwise ops.

Parity with /root/reference/python/paddle/tensor/logic.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core import dispatch as D
from ..core.tensor import Tensor

__all__ = [
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_not", "bitwise_xor", "bitwise_invert",
    "isclose", "allclose", "equal_all", "is_tensor", "is_empty", "is_complex",
    "is_floating_point", "is_integer",
]


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_complex(x):
    return x.dtype.is_complex


def is_floating_point(x):
    return x.dtype.is_floating_point


def is_integer(x):
    return x.dtype.is_integer


# kernel-driven (yaml source of truth) — see ops/kernels.py
from .generated.op_wrappers import (  # noqa: E402,F401
    equal, not_equal, less_than, less_equal, greater_than, greater_equal,
    logical_and, logical_or, logical_xor, logical_not, bitwise_and,
    bitwise_or, bitwise_xor, bitwise_not,
)

bitwise_invert = bitwise_not


# kernel-driven (generated from ops.yaml `kernel:` over ops/kernels.py)
from .generated.op_wrappers import (  # noqa: E402,F401
    allclose, equal_all, isclose,
)
