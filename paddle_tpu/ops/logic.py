"""Comparison / logical / bitwise ops.

Parity with /root/reference/python/paddle/tensor/logic.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core import dispatch as D
from ..core.tensor import Tensor

__all__ = [
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_not", "bitwise_xor", "bitwise_invert",
    "isclose", "allclose", "equal_all", "is_tensor", "is_empty", "is_complex",
    "is_floating_point", "is_integer",
]


def _binop(name, jfn):
    def op(x, y, name=None):
        return D.apply(op_name, jfn, (x, y))
    op_name = name
    op.__name__ = name
    return op


equal = _binop("equal", jnp.equal)
not_equal = _binop("not_equal", jnp.not_equal)
less_than = _binop("less_than", jnp.less)
less_equal = _binop("less_equal", jnp.less_equal)
greater_than = _binop("greater_than", jnp.greater)
greater_equal = _binop("greater_equal", jnp.greater_equal)
logical_and = _binop("logical_and", jnp.logical_and)
logical_or = _binop("logical_or", jnp.logical_or)
logical_xor = _binop("logical_xor", jnp.logical_xor)
bitwise_and = _binop("bitwise_and", jnp.bitwise_and)
bitwise_or = _binop("bitwise_or", jnp.bitwise_or)
bitwise_xor = _binop("bitwise_xor", jnp.bitwise_xor)


def logical_not(x, name=None):
    return D.apply("logical_not", jnp.logical_not, (x,))


def bitwise_not(x, name=None):
    return D.apply("bitwise_not", jnp.bitwise_not, (x,))


bitwise_invert = bitwise_not


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return D.apply("isclose",
                   lambda a, b, rtol, atol, equal_nan: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                   (x, y), {"rtol": float(rtol), "atol": float(atol), "equal_nan": bool(equal_nan)})


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return D.apply("allclose",
                   lambda a, b, rtol, atol, equal_nan: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                   (x, y), {"rtol": float(rtol), "atol": float(atol), "equal_nan": bool(equal_nan)})


def equal_all(x, y, name=None):
    return D.apply("equal_all",
                   lambda a, b: jnp.asarray(a.shape == b.shape and bool(jnp.all(a == b))
                                            if a.shape == b.shape else False)
                   if a.shape != b.shape else jnp.all(a == b),
                   (x, y))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_complex(x):
    return x.dtype.is_complex


def is_floating_point(x):
    return x.dtype.is_floating_point


def is_integer(x):
    return x.dtype.is_integer
