"""Math / reduction / elementwise ops.

Capability parity with /root/reference/python/paddle/tensor/math.py (and the
phi kernels those dispatch to); every op is a pure jnp function executed as a
cached XLA executable via the eager dispatcher.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dispatch as D
from ..core.dtype import convert_dtype, to_jax_dtype
from ..core.tensor import Tensor

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
    "pow", "float_power", "matmul", "dot", "mm", "bmm", "inner", "outer", "kron",
    "scale", "abs", "neg", "exp", "expm1", "log", "log2", "log10", "log1p",
    "sqrt", "rsqrt", "square", "sin", "cos", "tan", "asin", "acos", "atan",
    "sinh", "cosh", "asinh", "acosh", "atanh", "atan2", "tanh", "floor", "ceil",
    "round", "trunc", "frac", "sign", "sgn", "reciprocal", "clip", "maximum",
    "minimum", "fmax", "fmin", "sum", "nansum", "mean", "nanmean", "prod",
    "max", "min", "amax", "amin", "argmax", "argmin", "cumsum", "cumprod",
    "cummax", "cummin", "logsumexp", "logcumsumexp", "all", "any", "erf",
    "erfinv", "isnan", "isinf", "isfinite", "nan_to_num", "add_n", "addmm",
    "lerp", "deg2rad", "rad2deg", "gcd", "lcm", "diff", "angle", "conj",
    "real", "imag", "trace", "diagonal", "heaviside", "rot90", "histogram",
    "bincount", "multiply_", "stanh", "logaddexp", "logit", "i0", "i1",
    "digamma", "lgamma", "gammaln", "hypot", "copysign", "ldexp", "frexp",
    "count_nonzero", "broadcast_shape", "increment", "einsum", "renorm",
    "log_normalize", "reduce_as", "isposinf", "isneginf", "isreal", "signbit",
    "nextafter", "take", "vander", "combinations", "bitwise_left_shift",
    "bitwise_right_shift", "std", "var", "median", "nanmedian", "quantile",
    "nanquantile", "mode", "kthvalue", "numel",
]


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# ---------------- matmul family ----------------

def einsum(equation, *operands):
    ops = operands[0] if len(operands) == 1 and isinstance(operands[0], (list, tuple)) else operands
    return D.apply("einsum", lambda *arrs, equation: jnp.einsum(equation, *arrs),
                   tuple(ops), {"equation": equation})


def increment(x, value=1.0, name=None):
    out = D.apply("increment", lambda a, v: a + jnp.asarray(v, a.dtype), (x,), {"v": value})
    x._data = out._data
    x._grad_node = out._grad_node
    x._output_index = out._output_index
    return x


def multiply_(x, y, name=None):
    out = multiply(x, y)
    x._data = out._data
    x._grad_node = out._grad_node
    x._output_index = out._output_index
    return x


# ---------------- reductions ----------------








# ---------------- scans ----------------

def _cum_extreme(fn):
    def impl(a, axis):
        vals = fn.accumulate(a, axis)
        return vals
    return impl


# ---------------- misc ----------------

def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    def _add_n(*arrs):
        out = arrs[0]
        for a in arrs[1:]:
            out = out + a
        return out
    return D.apply("add_n", _add_n, tuple(inputs))






def bincount(x, weights=None, minlength=0, name=None):
    # Output length is data-dependent (reference bincount kernel sizes the
    # result from max(x)); resolve it host-side so the compiled op has a
    # static shape — jnp.bincount cannot trace a dynamic length.
    import builtins
    from ..core.tensor import Tensor
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    # builtins.max: this module's `max` op shadows the builtin
    length = builtins.max(int(xa.max()) + 1 if xa.size else 0,
                          int(minlength))
    if weights is None:
        return D.apply("bincount",
                       lambda a, length: jnp.bincount(
                           a, length=length).astype(jnp.int64),
                       (x,), {"length": length})
    return D.apply("bincount_w",
                   lambda a, w, length: jnp.bincount(a, weights=w,
                                                     length=length),
                   (x, weights), {"length": length})


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


# ---------------------------------------------------------------------------
# Kernel-driven ops: the yaml schema is the source of truth; the wrappers are
# generated (ops/generated/op_wrappers.py) from `kernel:` fields over
# ops/kernels.py.  Re-exported here so `from paddle_tpu.ops.math import add`
# keeps working for callers and the Tensor dunder bindings.
# ---------------------------------------------------------------------------
from .generated.op_wrappers import (  # noqa: E402,F401
    sum, nansum, mean, nanmean, prod, max, min, amax, amin, argmax,
    argmin, all, any, logsumexp, cumsum, cumprod, count_nonzero,
    abs, neg, exp, expm1, log, log2, log10, log1p, sqrt, rsqrt, square,
    sin, cos, tan, asin, acos, atan, sinh, cosh, asinh, acosh, atanh, tanh,
    floor, ceil, round, trunc, frac, sign, sgn, reciprocal, erf, erfinv,
    isnan, isinf, isfinite, isposinf, isneginf, isreal, signbit, deg2rad,
    rad2deg, angle, conj, real, imag, i0, i1, digamma, lgamma, gammaln,
    add, subtract, multiply, divide, floor_divide, remainder, mod, pow,
    maximum, minimum, fmax, fmin, atan2, logaddexp, hypot, copysign,
    nextafter, heaviside, gcd, lcm, ldexp, bitwise_left_shift,
    bitwise_right_shift, matmul, mm, bmm, dot, inner, outer, kron, addmm,
    stanh, logit, nan_to_num, trace, diagonal, rot90, log_normalize,
    reduce_as,
)


# kernel-driven (generated from ops.yaml `kernel:` over ops/kernels.py)
from .generated.op_wrappers import (  # noqa: E402,F401
    clip, combinations, cummax, cummin, float_power, kthvalue, lerp, logcumsumexp, nanquantile, numel, quantile, renorm, scale, std, take, vander, var,
)


# kernel-driven since r5 (generated from ops.yaml `kernel:` over
# ops/kernels.py); re-exported here so intra-repo imports keep working
from .generated.op_wrappers import (  # noqa: E402,F401
    diff,
    histogram,
    median,
    mode,
    nanmedian,
)
