"""Math / reduction / elementwise ops.

Capability parity with /root/reference/python/paddle/tensor/math.py (and the
phi kernels those dispatch to); every op is a pure jnp function executed as a
cached XLA executable via the eager dispatcher.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dispatch as D
from ..core.dtype import convert_dtype, to_jax_dtype
from ..core.tensor import Tensor

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
    "pow", "float_power", "matmul", "dot", "mm", "bmm", "inner", "outer", "kron",
    "scale", "abs", "neg", "exp", "expm1", "log", "log2", "log10", "log1p",
    "sqrt", "rsqrt", "square", "sin", "cos", "tan", "asin", "acos", "atan",
    "sinh", "cosh", "asinh", "acosh", "atanh", "atan2", "tanh", "floor", "ceil",
    "round", "trunc", "frac", "sign", "sgn", "reciprocal", "clip", "maximum",
    "minimum", "fmax", "fmin", "sum", "nansum", "mean", "nanmean", "prod",
    "max", "min", "amax", "amin", "argmax", "argmin", "cumsum", "cumprod",
    "cummax", "cummin", "logsumexp", "logcumsumexp", "all", "any", "erf",
    "erfinv", "isnan", "isinf", "isfinite", "nan_to_num", "add_n", "addmm",
    "lerp", "deg2rad", "rad2deg", "gcd", "lcm", "diff", "angle", "conj",
    "real", "imag", "trace", "diagonal", "heaviside", "rot90", "histogram",
    "bincount", "multiply_", "stanh", "logaddexp", "logit", "i0", "i1",
    "digamma", "lgamma", "gammaln", "hypot", "copysign", "ldexp", "frexp",
    "count_nonzero", "broadcast_shape", "increment", "einsum", "renorm",
    "log_normalize", "reduce_as", "isposinf", "isneginf", "isreal", "signbit",
    "nextafter", "take", "vander", "combinations", "bitwise_left_shift",
    "bitwise_right_shift", "std", "var", "median", "nanmedian", "quantile",
    "nanquantile", "mode", "kthvalue", "numel",
]


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# ---------------- matmul family ----------------

def einsum(equation, *operands):
    ops = operands[0] if len(operands) == 1 and isinstance(operands[0], (list, tuple)) else operands
    return D.apply("einsum", lambda *arrs, equation: jnp.einsum(equation, *arrs),
                   tuple(ops), {"equation": equation})


def increment(x, value=1.0, name=None):
    out = D.apply("increment", lambda a, v: a + jnp.asarray(v, a.dtype), (x,), {"v": value})
    x._data = out._data
    x._grad_node = out._grad_node
    x._output_index = out._output_index
    return x


def multiply_(x, y, name=None):
    out = multiply(x, y)
    x._data = out._data
    x._grad_node = out._grad_node
    x._output_index = out._output_index
    return x


# ---------------- reductions ----------------


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def _median(a, axis, keepdim, mode):
        if mode == "avg":
            return jnp.median(a, axis=axis, keepdims=keepdim)
        n = a.shape[axis] if axis is not None else a.size
        k = (n - 1) // 2
        sorted_a = jnp.sort(a, axis=axis) if axis is not None else jnp.sort(a.ravel())
        out = jnp.take(sorted_a, jnp.asarray([k]),
                       axis=axis if axis is not None else 0)
        if not keepdim or axis is None:
            out = jnp.squeeze(out, axis=axis if axis is not None else 0)
        return out
    return D.apply("median", _median, (x,),
                   {"axis": None if axis is None else int(axis), "keepdim": bool(keepdim),
                    "mode": mode})


def nanmedian(x, axis=None, keepdim=False, name=None):
    return D.apply("nanmedian",
                   lambda a, axis, keepdim: jnp.nanmedian(a, axis=axis, keepdims=keepdim),
                   (x,), {"axis": _axis(axis), "keepdim": bool(keepdim)})


def mode(x, axis=-1, keepdim=False, name=None):
    def _mode(a, axis, keepdim):
        sorted_a = jnp.sort(a, axis=axis)
        idx_a = jnp.argsort(a, axis=axis)
        n = a.shape[axis]
        ax = axis % a.ndim
        shape = [n if i == ax else 1 for i in range(a.ndim)]
        pos = jnp.arange(n).reshape(shape)
        # run-start positions: first element of each run of equal values
        first = jnp.take(sorted_a, jnp.asarray([0]), axis=ax)
        is_start = jnp.concatenate(
            [jnp.ones_like(first, dtype=bool),
             jnp.diff(sorted_a, axis=ax) != 0], axis=ax)
        # segmented run length: position - position of containing run's start + 1
        last_start = jax.lax.associative_scan(
            jnp.maximum, jnp.where(is_start, pos, -1), axis=ax)
        run_len = pos - last_start + 1
        best = jnp.argmax(run_len, axis=ax, keepdims=True)
        vals = jnp.take_along_axis(sorted_a, best, axis=ax)
        idxs = jnp.take_along_axis(idx_a, best, axis=ax)
        if not keepdim:
            vals, idxs = vals.squeeze(ax), idxs.squeeze(ax)
        return vals, idxs.astype(jnp.int64)
    return D.apply("mode", _mode, (x,), {"axis": int(axis), "keepdim": bool(keepdim)})


# ---------------- scans ----------------

def _cum_extreme(fn):
    def impl(a, axis):
        vals = fn.accumulate(a, axis)
        return vals
    return impl


# ---------------- misc ----------------

def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    def _add_n(*arrs):
        out = arrs[0]
        for a in arrs[1:]:
            out = out + a
        return out
    return D.apply("add_n", _add_n, tuple(inputs))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [x]
    has_prepend = prepend is not None
    has_append = append is not None
    if has_prepend:
        args.append(prepend)
    if has_append:
        args.append(append)

    def _diff(*arrs, n, axis, has_prepend, has_append):
        a = arrs[0]
        i = 1
        pre = app = None
        if has_prepend:
            pre = arrs[i]; i += 1
        if has_append:
            app = arrs[i]
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)
    return D.apply("diff", _diff, tuple(args),
                   {"n": int(n), "axis": int(axis), "has_prepend": has_prepend,
                    "has_append": has_append})


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    def _hist(a, bins, mn, mx, density):
        if mn == 0 and mx == 0:
            mn, mx = jnp.min(a), jnp.max(a)
        h, _ = jnp.histogram(a, bins=bins, range=(mn, mx), density=density)
        return h if density else h.astype(jnp.int64)
    return D.apply("histogram", _hist, (input,),
                   {"bins": int(bins), "mn": min, "mx": max, "density": bool(density)})


def bincount(x, weights=None, minlength=0, name=None):
    # Output length is data-dependent (reference bincount kernel sizes the
    # result from max(x)); resolve it host-side so the compiled op has a
    # static shape — jnp.bincount cannot trace a dynamic length.
    import builtins
    from ..core.tensor import Tensor
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    # builtins.max: this module's `max` op shadows the builtin
    length = builtins.max(int(xa.max()) + 1 if xa.size else 0,
                          int(minlength))
    if weights is None:
        return D.apply("bincount",
                       lambda a, length: jnp.bincount(
                           a, length=length).astype(jnp.int64),
                       (x,), {"length": length})
    return D.apply("bincount_w",
                   lambda a, w, length: jnp.bincount(a, weights=w,
                                                     length=length),
                   (x, weights), {"length": length})


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


# ---------------------------------------------------------------------------
# Kernel-driven ops: the yaml schema is the source of truth; the wrappers are
# generated (ops/generated/op_wrappers.py) from `kernel:` fields over
# ops/kernels.py.  Re-exported here so `from paddle_tpu.ops.math import add`
# keeps working for callers and the Tensor dunder bindings.
# ---------------------------------------------------------------------------
from .generated.op_wrappers import (  # noqa: E402,F401
    sum, nansum, mean, nanmean, prod, max, min, amax, amin, argmax,
    argmin, all, any, logsumexp, cumsum, cumprod, count_nonzero,
    abs, neg, exp, expm1, log, log2, log10, log1p, sqrt, rsqrt, square,
    sin, cos, tan, asin, acos, atan, sinh, cosh, asinh, acosh, atanh, tanh,
    floor, ceil, round, trunc, frac, sign, sgn, reciprocal, erf, erfinv,
    isnan, isinf, isfinite, isposinf, isneginf, isreal, signbit, deg2rad,
    rad2deg, angle, conj, real, imag, i0, i1, digamma, lgamma, gammaln,
    add, subtract, multiply, divide, floor_divide, remainder, mod, pow,
    maximum, minimum, fmax, fmin, atan2, logaddexp, hypot, copysign,
    nextafter, heaviside, gcd, lcm, ldexp, bitwise_left_shift,
    bitwise_right_shift, matmul, mm, bmm, dot, inner, outer, kron, addmm,
    stanh, logit, nan_to_num, trace, diagonal, rot90, log_normalize,
    reduce_as,
)


# kernel-driven (generated from ops.yaml `kernel:` over ops/kernels.py)
from .generated.op_wrappers import (  # noqa: E402,F401
    clip, combinations, cummax, cummin, float_power, kthvalue, lerp, logcumsumexp, nanquantile, numel, quantile, renorm, scale, std, take, vander, var,
)
