"""Tensor creation ops.

Capability parity with /root/reference/python/paddle/tensor/creation.py,
built directly on jnp; factories are cheap XLA constants so they bypass the
autograd dispatcher (they never require grad at creation).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dispatch as D
from ..core.dtype import convert_dtype, to_jax_dtype, x64_scope
from ..core.tensor import Tensor, to_tensor

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "tril", "triu", "diag", "diagflat", "meshgrid", "assign", "clone",
    "complex_", "polar", "tril_indices", "triu_indices",
]


def _shape_tuple(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def _dt(dtype, default="float32"):
    return to_jax_dtype(convert_dtype(dtype if dtype is not None else default))


def _make(jdt, build, *args, **kw):
    # 64-bit dtypes (paddle-parity int64 defaults etc.) are created under a
    # scoped jax.enable_x64 — see core.dtype.x64_scope
    with x64_scope(jdt):
        return Tensor(build(*args, **kw))


def zeros(shape, dtype=None, name=None):
    dt = _dt(dtype)
    return _make(dt, jnp.zeros, _shape_tuple(shape), dt)


def ones(shape, dtype=None, name=None):
    dt = _dt(dtype)
    return _make(dt, jnp.ones, _shape_tuple(shape), dt)


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = "bool"
        elif isinstance(fill_value, int):
            dtype = "int64"
        else:
            dtype = "float32"
    dt = _dt(dtype)
    return _make(dt, jnp.full, _shape_tuple(shape), fill_value, dt)


def empty(shape, dtype=None, name=None):
    dt = _dt(dtype)
    return _make(dt, jnp.zeros, _shape_tuple(shape), dt)


def _like_dt(x, dtype):
    return to_jax_dtype(convert_dtype(dtype)) if dtype is not None else x._data.dtype


def zeros_like(x, dtype=None, name=None):
    dt = _like_dt(x, dtype)
    return _make(dt, jnp.zeros, x._data.shape, dt)


def ones_like(x, dtype=None, name=None):
    dt = _like_dt(x, dtype)
    return _make(dt, jnp.ones, x._data.shape, dt)


def full_like(x, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    dt = _like_dt(x, dtype)
    return _make(dt, jnp.full, x._data.shape, fill_value, dt)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = val(start), val(end), val(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = ("int64" if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
                 else "float32")
    dt = _dt(dtype)
    return _make(dt, jnp.arange, start, end, step, dt)


def linspace(start, stop, num, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    dt = _dt(dtype)
    return _make(dt, jnp.linspace, val(start), val(stop), int(val(num)), dtype=dt)


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    dt = _dt(dtype)
    return _make(dt, jnp.logspace, val(start), val(stop), int(val(num)),
                 base=val(base), dtype=dt)


def eye(num_rows, num_columns=None, dtype=None, name=None):
    dt = _dt(dtype)
    return _make(dt, jnp.eye, int(num_rows),
                 int(num_columns) if num_columns is not None else None,
                 dtype=dt)


def _tril(x, diagonal):
    return jnp.tril(x, k=diagonal)


def _triu(x, diagonal):
    return jnp.triu(x, k=diagonal)


def tril(x, diagonal=0, name=None):
    return D.apply("tril", _tril, (x,), {"diagonal": int(diagonal)})


def triu(x, diagonal=0, name=None):
    return D.apply("triu", _triu, (x,), {"diagonal": int(diagonal)})


def _diag(x, offset, padding_value):
    if x.ndim == 1 and padding_value != 0:
        d = jnp.diag(x, k=offset)
        mask = jnp.eye(d.shape[0], d.shape[1], k=offset, dtype=bool)
        return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
    return jnp.diag(x, k=offset)


def diag(x, offset=0, padding_value=0, name=None):
    return D.apply("diag", _diag, (x,), {"offset": int(offset),
                                         "padding_value": padding_value})


def diagflat(x, offset=0, name=None):
    return D.apply("diagflat", lambda a, offset: jnp.diagflat(a, k=offset),
                   (x,), {"offset": int(offset)})


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = jnp.meshgrid(*[a._data if isinstance(a, Tensor) else jnp.asarray(a)
                          for a in args], indexing="ij")
    return [Tensor(o) for o in outs]


def _assign(x):
    return x + jnp.zeros((), x.dtype) if jnp.issubdtype(x.dtype, jnp.number) else jnp.array(x)


def assign(x, output=None):
    if not isinstance(x, Tensor):
        x = to_tensor(np.asarray(x))
    out = D.apply("assign", lambda a: a * 1 if jnp.issubdtype(a.dtype, jnp.number) else jnp.copy(a), (x,))
    if output is not None:
        output._data = out._data
        output._grad_node = out._grad_node
        output._output_index = out._output_index
        output.stop_gradient = out.stop_gradient
        return output
    return out


def clone(x, name=None):
    return assign(x)


def _complex(real, imag):
    return jax.lax.complex(real, imag)


def complex_(real, imag, name=None):
    return D.apply("complex", _complex, (real, imag))


def polar(abs_t, angle, name=None):
    return D.apply("polar", lambda a, b: jax.lax.complex(a * jnp.cos(b), a * jnp.sin(b)),
                   (abs_t, angle))


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    dt = _dt(dtype)
    return _make(dt, jnp.asarray, np.stack([r, c]), dtype=dt)


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    dt = _dt(dtype)
    return _make(dt, jnp.asarray, np.stack([r, c]), dtype=dt)
