"""__getitem__ / __setitem__ support.

Analog of the reference's set_value/slice op family and eager __getitem__
binding (/root/reference/paddle/fluid/pybind/eager_method.cc,
python/paddle/base/variable_index.py).  Basic indices (ints/slices) are baked
into the compiled executable; tensor indices are dynamic inputs; boolean masks
are resolved to integer indices on host (dynamic output shapes cannot live
under XLA), matching the reference's GPU sync behavior for bool indexing.
"""
from __future__ import annotations

import builtins

import numpy as np
import jax.numpy as jnp

from ..core import dispatch as D
from ..core.tensor import Tensor

__all__ = ["getitem", "setitem"]


def _normalize_index(x, index):
    if not isinstance(index, tuple):
        index = (index,)
    dynamic = []
    spec = []
    for e in index:
        if isinstance(e, Tensor):
            if e.dtype.name == "bool":
                idx = np.nonzero(np.asarray(e._data))
                for comp in idx:
                    dynamic.append(jnp.asarray(comp))
                    spec.append(("T",))
            elif e.ndim == 0:
                spec.append(("I", int(e.item())))
            else:
                dynamic.append(e)
                spec.append(("T",))
        elif isinstance(e, np.ndarray):
            if e.dtype == np.bool_:
                for comp in np.nonzero(e):
                    dynamic.append(jnp.asarray(comp))
                    spec.append(("T",))
            else:
                dynamic.append(jnp.asarray(e))
                spec.append(("T",))
        elif isinstance(e, builtins.slice):
            def iv(v):
                if v is None:
                    return None
                return int(v.item()) if isinstance(v, Tensor) else int(v)
            spec.append(("S", iv(e.start), iv(e.stop), iv(e.step)))
        elif e is Ellipsis:
            spec.append(("E",))
        elif e is None:
            spec.append(("N",))
        elif isinstance(e, bool):
            spec.append(("B", e))
        elif isinstance(e, (int, np.integer)):
            spec.append(("I", int(e)))
        elif isinstance(e, (list, tuple)):
            arr = np.asarray(e)
            if arr.dtype == np.bool_:
                for comp in np.nonzero(arr):
                    dynamic.append(jnp.asarray(comp))
                    spec.append(("T",))
            else:
                dynamic.append(jnp.asarray(arr))
                spec.append(("T",))
        else:
            raise TypeError(f"Unsupported index element: {e!r}")
    return dynamic, tuple(spec)


def _rebuild(idx_arrays, spec):
    out = []
    it = iter(idx_arrays)
    for s in spec:
        kind = s[0]
        if kind == "T":
            out.append(next(it))
        elif kind == "S":
            out.append(builtins.slice(s[1], s[2], s[3]))
        elif kind == "E":
            out.append(Ellipsis)
        elif kind == "N":
            out.append(None)
        elif kind == "B":
            out.append(s[1])
        else:
            out.append(s[1])
    return tuple(out)


def getitem(x, index):
    dynamic, spec = _normalize_index(x, index)

    def _impl(a, *idx_arrays, spec):
        return a[_rebuild(idx_arrays, spec)]
    return D.apply("getitem", _impl, (x, *dynamic), {"spec": spec})


def setitem(x, index, value):
    dynamic, spec = _normalize_index(x, index)
    if not isinstance(value, Tensor):
        value = Tensor(jnp.asarray(value, dtype=x._data.dtype))

    def _impl(a, v, *idx_arrays, spec):
        return a.at[_rebuild(idx_arrays, spec)].set(v.astype(a.dtype))
    out = D.apply("setitem", _impl, (x, value, *dynamic), {"spec": spec})
    x._data = out._data
    x._grad_node = out._grad_node
    x._output_index = out._output_index
    if not out.stop_gradient:
        x.stop_gradient = False
    return x
