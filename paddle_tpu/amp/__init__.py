"""AMP: auto_cast + GradScaler + decorate.

Parity with /root/reference/python/paddle/amp/ (auto_cast.py, grad_scaler.py):
O1 = per-op white/black list casting (enforced inside the dispatcher,
paddle_tpu/core/amp_state.py); O2 = cast the whole model to fp16/bf16 with
float32 master weights held by the optimizer.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import amp_state
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler", "is_bfloat16_supported",
           "is_float16_supported", "white_list", "black_list"]


def white_list():
    return set(amp_state.WHITE_LIST)


def black_list():
    return set(amp_state.BLACK_LIST)


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True  # TPU native dtype


class auto_cast:
    """Context manager enabling autocast (O1/O2).

    On TPU the low-precision dtype defaults to bfloat16 — the MXU-native type —
    rather than the reference's float16 default.
    """

    def __init__(self, enable=True, custom_white_list=None, custom_black_list=None,
                 level="O1", dtype="bfloat16", use_promote=True):
        self.enable = enable
        self.level = level
        self.dtype = convert_dtype(dtype).np_dtype
        self._custom_white = set(custom_white_list or ())
        self._custom_black = set(custom_black_list or ())

    def __enter__(self):
        self._saved_white = set(amp_state.WHITE_LIST)
        self._saved_black = set(amp_state.BLACK_LIST)
        if self._custom_white:
            amp_state.WHITE_LIST.update(self._custom_white)
            amp_state.BLACK_LIST.difference_update(self._custom_white)
        if self._custom_black:
            amp_state.BLACK_LIST.update(self._custom_black)
            amp_state.WHITE_LIST.difference_update(self._custom_black)
        self._prev = amp_state.enter_autocast(self.enable, self.dtype, self.level)
        return self

    def __exit__(self, *exc):
        amp_state.restore(self._prev)
        # restore global op lists mutated by custom white/black lists
        amp_state.WHITE_LIST.clear()
        amp_state.WHITE_LIST.update(self._saved_white)
        amp_state.BLACK_LIST.clear()
        amp_state.BLACK_LIST.update(self._saved_black)
        return False


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="float16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """O2 decoration: cast model params to low precision; optimizer keeps
    float32 master weights (reference semantics: optimizer.py master-weight
    path)."""
    if level not in ("O1", "O2"):
        raise ValueError("level must be O1 or O2")
    dt = convert_dtype(dtype)
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        from ..nn.layer.norm import _BatchNormBase, LayerNorm
        for m in model_list:
            for layer in m.sublayers(include_self=True):
                if isinstance(layer, (_BatchNormBase, LayerNorm)):
                    continue  # keep norm params fp32 (reference keeps them fp32)
                for p in layer._parameters.values():
                    if p is not None and jnp.issubdtype(p._data.dtype, jnp.floating):
                        if not hasattr(p, "_master_weight"):
                            p._master_weight = p._data.astype(jnp.float32)
                        p._data = p._data.astype(dt.np_dtype)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


class GradScaler:
    """Dynamic loss scaling (parity with
    /root/reference/python/paddle/amp/grad_scaler.py).

    Note: with bfloat16 on TPU scaling is typically unnecessary (use
    enable=False); kept for float16 parity.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf_t = None   # DEVICE bool; host-synced only in update()
        self._unscaled = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale, jnp.float32))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def scale(self, var):
        if not self._enable:
            return var
        from ..ops.math import scale as _scale
        return _scale(var, self._scale)

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        self._unscaled = True
        inv = 1.0 / self._scale
        # fused finite-check kept ON DEVICE: found_inf stays a device bool
        # through step() (the optimizer masks its update with it) and is
        # host-synced exactly once, in update() — matching the reference's
        # tensor-found_inf flow (python/paddle/amp/grad_scaler.py)
        bad_count = jnp.zeros((), jnp.int32)
        for p in (optimizer._parameter_list or []):
            g = p._grad
            if g is None:
                continue
            arr = g._data.astype(jnp.float32) * inv
            bad_count = bad_count + jnp.sum(~jnp.isfinite(arr)).astype(jnp.int32)
            g._data = arr.astype(g._data.dtype) if g._data.dtype != jnp.float32 else arr
        self._found_inf_t = bad_count > 0

    def step(self, optimizer):
        """Unscale (if the user hasn't already) and step when grads are
        finite.  Matches the reference: step() does NOT update() — callers do
        scaler.step(opt); scaler.update()."""
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        # no host sync: the compiled optimizer update is masked by the
        # device-side found_inf bool
        optimizer._skip_update_mask = self._found_inf_t
        try:
            optimizer.step()
        finally:
            optimizer._skip_update_mask = None

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    @property
    def _found_inf(self):
        t = self._found_inf_t
        return bool(t) if t is not None else False

    def update(self):
        self._unscaled = False
        if not (self._enable and self._dynamic):
            self._found_inf_t = None
            return
        if self._found_inf:   # the step's single host sync
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf_t = None

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "incr_count": self._good_steps,
                "decr_count": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)

from . import debugging  # noqa: E402,F401
