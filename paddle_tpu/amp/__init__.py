"""AMP: auto_cast + GradScaler + decorate.

Parity with /root/reference/python/paddle/amp/ (auto_cast.py, grad_scaler.py):
O1 = per-op white/black list casting (enforced inside the dispatcher,
paddle_tpu/core/amp_state.py); O2 = cast the whole model to fp16/bf16 with
float32 master weights held by the optimizer.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import amp_state
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler", "is_bfloat16_supported",
           "is_float16_supported", "white_list", "black_list"]


def white_list():
    return set(amp_state.WHITE_LIST)


def black_list():
    return set(amp_state.BLACK_LIST)


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True  # TPU native dtype


class auto_cast:
    """Context manager enabling autocast (O1/O2).

    On TPU the low-precision dtype defaults to bfloat16 — the MXU-native type —
    rather than the reference's float16 default.
    """

    def __init__(self, enable=True, custom_white_list=None, custom_black_list=None,
                 level="O1", dtype="bfloat16", use_promote=True):
        self.enable = enable
        self.level = level
        self.dtype = convert_dtype(dtype).np_dtype
        self._custom_white = set(custom_white_list or ())
        self._custom_black = set(custom_black_list or ())

    def __enter__(self):
        self._saved_white = set(amp_state.WHITE_LIST)
        self._saved_black = set(amp_state.BLACK_LIST)
        if self._custom_white:
            amp_state.WHITE_LIST.update(self._custom_white)
            amp_state.BLACK_LIST.difference_update(self._custom_white)
        if self._custom_black:
            amp_state.BLACK_LIST.update(self._custom_black)
            amp_state.WHITE_LIST.difference_update(self._custom_black)
        self._prev = amp_state.enter_autocast(self.enable, self.dtype, self.level)
        return self

    def __exit__(self, *exc):
        amp_state.restore(self._prev)
        # restore global op lists mutated by custom white/black lists
        amp_state.WHITE_LIST.clear()
        amp_state.WHITE_LIST.update(self._saved_white)
        amp_state.BLACK_LIST.clear()
        amp_state.BLACK_LIST.update(self._saved_black)
        return False


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="float16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """O2 decoration: cast model params to low precision; optimizer keeps
    float32 master weights (reference semantics: optimizer.py master-weight
    path)."""
    if level not in ("O1", "O2"):
        raise ValueError("level must be O1 or O2")
    dt = convert_dtype(dtype)
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        from ..nn.layer.norm import _BatchNormBase, LayerNorm
        for m in model_list:
            for layer in m.sublayers(include_self=True):
                if isinstance(layer, (_BatchNormBase, LayerNorm)):
                    continue  # keep norm params fp32 (reference keeps them fp32)
                for p in layer._parameters.values():
                    if p is not None and jnp.issubdtype(p._data.dtype, jnp.floating):
                        if not hasattr(p, "_master_weight"):
                            p._master_weight = p._data.astype(jnp.float32)
                        p._data = p._data.astype(dt.np_dtype)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


_unscale_cache = {}


def _fused_unscale(grads, inv):
    """One compiled program: unscale every grad + global finite check."""
    key = tuple((tuple(g.shape), str(g.dtype)) for g in grads)
    exe = _unscale_cache.get(key)
    if exe is None:
        def run(gs, inv):
            bad = jnp.zeros((), jnp.int32)
            out = []
            for g in gs:
                arr = g.astype(jnp.float32) * inv
                bad = bad + jnp.sum(~jnp.isfinite(arr)).astype(jnp.int32)
                out.append(arr.astype(g.dtype)
                           if g.dtype != jnp.float32 else arr)
            return out, bad > 0
        import jax
        # donate the old grad buffers: their only other refs (p._grad._data)
        # are overwritten right after the call, so XLA reuses them in place
        exe = _unscale_cache[key] = jax.jit(run, donate_argnums=(0,))
    return exe(list(grads), inv)


class GradScaler:
    """Dynamic loss scaling (parity with
    /root/reference/python/paddle/amp/grad_scaler.py).

    Note: with bfloat16 on TPU scaling is typically unnecessary (use
    enable=False); kept for float16 parity.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf_t = None   # DEVICE bool; host-synced only in update()
        self._unscaled = False
        self._cap = None           # jit.capture_step: dynamic state arrays

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale, jnp.float32))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def scale(self, var):
        if not self._enable:
            return var
        if self._cap is not None:
            # captured step: the scale is a dynamic program input
            return var * Tensor(self._cap["scale"].astype(var._data.dtype))
        if isinstance(self._scale, jnp.ndarray):
            # device-resident scale left by a previous captured step
            return var * Tensor(self._scale.astype(var._data.dtype))
        from ..ops.math import scale as _scale
        return _scale(var, self._scale)

    def _scale_arr(self):
        if self._cap is not None:
            return self._cap["scale"]
        return jnp.asarray(self._scale, jnp.float32)

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        self._unscaled = True
        # ONE fused program for unscale + finite-check across every grad
        # (a per-param eager loop costs 3 dispatches per parameter — ruinous
        # over a remote TPU link).  found_inf stays a device bool through
        # step() (the optimizer masks its update with it) and is host-synced
        # exactly once, in update() — matching the reference's tensor-
        # found_inf flow (python/paddle/amp/grad_scaler.py).
        inv = 1.0 / self._scale_arr()
        with_grad = [p for p in (optimizer._parameter_list or [])
                     if p._grad is not None]
        if not with_grad:
            self._found_inf_t = jnp.asarray(False)
            return
        grads = [p._grad._data for p in with_grad]
        new_grads, found = _fused_unscale(grads, inv)
        for p, g in zip(with_grad, new_grads):
            p._grad._data = g
        self._found_inf_t = found

    def step(self, optimizer):
        """Unscale (if the user hasn't already) and step when grads are
        finite.  Matches the reference: step() does NOT update() — callers do
        scaler.step(opt); scaler.update()."""
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        # no host sync: the compiled optimizer update is masked by the
        # device-side found_inf bool
        optimizer._skip_update_mask = self._found_inf_t
        try:
            optimizer.step()
        finally:
            optimizer._skip_update_mask = None

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    @property
    def _found_inf(self):
        t = self._found_inf_t
        return bool(t) if t is not None else False

    def update(self):
        self._unscaled = False
        if not (self._enable and self._dynamic):
            self._found_inf_t = None
            return
        if self._cap is not None:
            # captured step: the whole scale schedule is branch-free device
            # arithmetic — no host sync anywhere in the compiled program
            found = self._found_inf_t
            if found is None:
                found = jnp.asarray(False)
            scale = self._cap["scale"]
            good, bad = self._cap["good"], self._cap["bad"]
            bad1 = jnp.where(found, bad + 1, jnp.zeros_like(bad))
            good1 = jnp.where(found, jnp.zeros_like(good), good + 1)
            decr = found & (bad1 >= self._decr_every_n)
            incr = ~found & (good1 >= self._incr_every_n)
            scale = jnp.where(
                decr, jnp.maximum(scale * self._decr_ratio, 1.0),
                jnp.where(incr, scale * self._incr_ratio, scale))
            self._cap["scale"] = scale
            self._cap["bad"] = jnp.where(decr, jnp.zeros_like(bad1), bad1)
            self._cap["good"] = jnp.where(incr, jnp.zeros_like(good1), good1)
            self._found_inf_t = None
            return
        if self._found_inf:   # the step's single host sync
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(float(self._scale) * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n:
                self._scale = float(self._scale) * self._incr_ratio
                self._good_steps = 0
        self._found_inf_t = None

    # ---- jit.capture_step protocol ----
    def _capture_state(self):
        """Concrete (scale, good, bad) arrays to feed the captured program."""
        return (jnp.asarray(self._scale, jnp.float32),
                jnp.asarray(self._good_steps, jnp.int32),
                jnp.asarray(self._bad_steps, jnp.int32))

    def _begin_capture(self, scale, good, bad):
        self._cap = {"scale": scale, "good": good, "bad": bad}

    def _end_capture(self):
        cap, self._cap = self._cap, None
        return (cap["scale"], cap["good"], cap["bad"])

    def _load_capture_state(self, scale, good, bad):
        # keep device-resident: forcing floats here would host-sync per step
        self._scale = scale
        self._good_steps = good
        self._bad_steps = bad

    def state_dict(self):
        return {"scale": float(self._scale), "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_count": int(self._good_steps),
                "decr_count": int(self._bad_steps)}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)

from . import debugging  # noqa: E402,F401
