"""AMP debugging tools: tensor checker + operator stats.

Parity with /root/reference/python/paddle/amp/debugging.py
(TensorCheckerConfig :173, enable_tensor_checker/disable_tensor_checker,
check_numerics, enable_operator_stats_collection).  The checker rides the
dispatcher's per-op output hook (the analog of the reference's generated
ad_func CheckTensorHasNanOrInf calls, paddle/fluid/eager/nan_inf_utils.h:38).
"""
from __future__ import annotations

import enum
import logging

import jax.numpy as jnp

__all__ = ["DebugMode", "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "check_numerics",
           "enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats"]

_log = logging.getLogger("paddle_tpu.amp.debugging")


class DebugMode(enum.Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


class TensorCheckerConfig:
    """Per-op numeric checking policy.

    enable: master switch.  debug_mode: abort vs log.  checked_op_list /
    skipped_op_list: restrict which dispatcher ops are checked.
    debug_step: optional (start, end) step window; advance with
    update_and_check_step_id() once per iteration (the reference's
    TensorCheckerConfig semantics)."""

    def __init__(self, enable, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None, skipped_op_list=None,
                 debug_step=None, stack_height_limit=1):
        self.enable = bool(enable)
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = set(checked_op_list or [])
        self.skipped_op_list = set(skipped_op_list or [])
        self.debug_step = debug_step
        self.stack_height_limit = stack_height_limit
        self._step = 0

    def update_and_check_step_id(self):
        self._step += 1
        return self._in_window()

    def _in_window(self):
        if self.debug_step is None:
            return True
        lo, hi = self.debug_step
        return lo <= self._step <= hi

    def _should_check(self, op_name):
        if not self.enable or not self._in_window():
            return False
        if op_name in self.skipped_op_list:
            return False
        if self.checked_op_list and op_name not in self.checked_op_list:
            return False
        return True


_active_config: TensorCheckerConfig | None = None


def _checker_cb(op_name, out_arrays):
    cfg = _active_config
    if cfg is None or not cfg._should_check(op_name):
        return
    for a in out_arrays:
        if not jnp.issubdtype(a.dtype, jnp.inexact):
            continue
        bad = int(jnp.sum(~jnp.isfinite(a)))
        if bad:
            msg = (f"[tensor checker] op '{op_name}' produced {bad} "
                   f"non-finite values (shape={tuple(a.shape)}, "
                   f"dtype={a.dtype})")
            if cfg.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
                raise FloatingPointError(msg)
            _log.warning(msg)


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    global _active_config
    _active_config = checker_config
    from ..core import dispatch
    dispatch.set_tensor_checker(_checker_cb)


def disable_tensor_checker():
    global _active_config
    _active_config = None
    from ..core import dispatch
    dispatch.set_tensor_checker(None)


def check_numerics(tensor, op_type="", var_name="",
                   debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Explicit one-tensor check (reference paddle.amp.debugging.check_numerics)."""
    arr = tensor._data if hasattr(tensor, "_data") else jnp.asarray(tensor)
    bad = int(jnp.sum(~jnp.isfinite(arr))) \
        if jnp.issubdtype(arr.dtype, jnp.inexact) else 0
    if bad:
        msg = (f"[check_numerics] {op_type}:{var_name} has {bad} non-finite "
               f"values (shape={tuple(arr.shape)})")
        if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
            raise FloatingPointError(msg)
        _log.warning(msg)
    return tensor


# --- operator stats (reference enable_operator_stats_collection) ----------

_op_stats: dict | None = None
_prev_observer = None


def enable_operator_stats_collection():
    """Count dispatcher ops by name until disabled (the reference collects
    per-dtype op calls during an autocast block)."""
    global _op_stats, _prev_observer
    from ..core import dispatch
    _op_stats = {}

    def obs(op_name, t0, dur_ns):
        rec = _op_stats.setdefault(op_name, [0, 0])
        rec[0] += 1
        rec[1] += dur_ns

    _prev_observer = dispatch.get_op_observer()
    dispatch.set_op_observer(obs)


def disable_operator_stats_collection():
    global _op_stats
    from ..core import dispatch
    dispatch.set_op_observer(_prev_observer)
    stats = _op_stats or {}
    _op_stats = None
    lines = ["<------------------------------ op list ------------------"
             "------------>",
             f"{'op name':<40} {'calls':>8} {'total us':>12}"]
    for name, (n, ns) in sorted(stats.items(), key=lambda kv: -kv[1][1]):
        lines.append(f"{name:<40} {n:>8} {ns / 1000.0:>12.1f}")
    print("\n".join(lines))
    return stats


class collect_operator_stats:
    """Context manager variant."""

    def __enter__(self):
        enable_operator_stats_collection()
        return self

    def __exit__(self, *exc):
        disable_operator_stats_collection()
        return False
