"""paddle_tpu: a TPU-native deep-learning framework.

Capability parity with PaddlePaddle (reference: /root/reference), built
idiomatically on JAX/XLA/Pallas/pjit.  See SURVEY.md for the layer map this
package follows.
"""
from __future__ import annotations

import os as _os

# jax_enable_x64 stays OFF: it widens default intermediates on a bf16
# machine and breaks Pallas/Mosaic lowering (r2 BENCH + index-map
# RecursionError).  int64/float64 parity with the reference (python ints ->
# int64 tensors, python/paddle/tensor/creation.py) is scoped to creation ops
# via core.dtype.x64_scope, which builds 64-bit arrays under
# jax.enable_x64(True); the arrays keep their dtype afterwards.
import warnings as _warnings

import jax as _jax  # noqa: F401

# Honor a caller's JAX_PLATFORMS pin at the CONFIG level before any backend
# init: a hardware-plugin sitecustomize can install a get_backend hook for
# which the env var alone does not prevent plugin client init, and that init
# hangs when the device service is unreachable.  Same pattern as
# tests/conftest.py and distributed/launch/main.py — this makes it hold for
# ANY subprocess that imports the framework with the env var set.
if _os.environ.get("JAX_PLATFORMS"):
    try:
        # full comma-separated value: "tpu,cpu" keeps its cpu fallback
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:
        pass

_warnings.filterwarnings(
    "ignore", message="Explicitly requested dtype.*truncated", category=UserWarning)

__version__ = "0.1.0"

from .core import dispatch as _dispatch
from .core import tape as _tape
from .core.dtype import (  # noqa: F401
    bfloat16, bool_, complex128, complex64, dtype, float16, float32, float64,
    float8_e4m3fn, float8_e5m2, int16, int32, int64, int8, pstring, raw,
    uint8,
)
from .core.enforce import EnforceError  # noqa: F401
from .core.place import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, Place, TPUPlace, XPUPlace,
    device_count, get_device, is_compiled_with_cuda, is_compiled_with_distribute,
    is_compiled_with_rocm, is_compiled_with_xpu, set_device,
)
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.selected_rows import SelectedRows, merge_selected_rows  # noqa: F401
from .core.tensor import Parameter, Tensor, to_tensor  # noqa: F401

no_grad = _dispatch.no_grad
enable_grad = _dispatch.enable_grad
set_grad_enabled = _dispatch.set_grad_enabled
is_grad_enabled = _dispatch.is_grad_enabled
grad = _tape.grad

from . import ops as _ops

_ops.monkey_patch_tensor()

# Public op namespace: paddle_tpu.add / paddle_tpu.reshape / ...
_g = globals()
for _name, _fn in _ops.PUBLIC_OPS.items():
    _g.setdefault(_name, _fn)
del _g, _name, _fn

from .ops.creation import complex_ as complex  # noqa: F401,E402
from .ops.math import einsum  # noqa: F401,E402
from .ops.random import get_rng_state, seed, set_rng_state  # noqa: F401,E402

bool = bool_  # paddle.bool

# Subpackages (imported lazily where heavy).
from . import amp  # noqa: E402
from . import audio  # noqa: E402
from . import autograd  # noqa: E402
from . import device  # noqa: E402
from . import distributed  # noqa: E402
from . import distribution  # noqa: E402
from . import fft  # noqa: E402
from . import framework  # noqa: E402
from . import geometric  # noqa: E402
from . import hapi  # noqa: E402
from . import incubate  # noqa: E402
from . import io  # noqa: E402
from . import jit  # noqa: E402
from . import linalg  # noqa: E402
from . import metric  # noqa: E402
from . import nn  # noqa: E402
from . import profiler  # noqa: E402
from . import quantization  # noqa: E402
from . import reader  # noqa: E402
from . import dataset  # noqa: E402
from . import cost_model  # noqa: E402
from . import inference  # noqa: E402
from . import optimizer  # noqa: E402
from . import hub  # noqa: E402
from . import onnx  # noqa: E402
from . import regularizer  # noqa: E402
from . import signal  # noqa: E402
from . import sparse  # noqa: E402
from . import static  # noqa: E402
from . import sysconfig  # noqa: E402
from . import version  # noqa: E402
from .nn.initializer.attr import ParamAttr  # noqa: E402


_default_dtype = "float32"


def set_default_dtype(d):
    """Default float dtype for parameter/tensor creation (reference
    framework set_default_dtype)."""
    global _default_dtype
    from .core.dtype import convert_dtype
    _default_dtype = convert_dtype(d)


def get_default_dtype():
    return _default_dtype


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor repr options (reference paddle.set_printoptions; reprs here
    render through numpy, so this drives numpy's printoptions)."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not bool(sci_mode)
    _np.set_printoptions(**kw)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone Parameter factory (reference base/layers creation;
    used by custom layers outside Layer.create_parameter)."""
    import jax.numpy as _jnp

    from .core.dtype import to_jax_dtype
    from .nn.initializer import Constant, XavierNormal
    init = default_initializer or (Constant(0.0) if is_bias
                                   else XavierNormal())
    data = init(tuple(int(s) for s in shape), dtype)
    p = Parameter(_jnp.asarray(data, to_jax_dtype(dtype)))
    if attr is not None and getattr(attr, "regularizer", None) is not None:
        p.regularizer = attr.regularizer
    return p


class LazyGuard:
    """Deferred-init guard (reference paddle.LazyGuard).  Parameter init is
    a cheap jnp allocation under XLA, so laziness buys nothing — the guard
    is accepted and is a no-op."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def get_cuda_rng_state():
    """Accelerator RNG state (maps to the framework RNG; reference
    get_cuda_rng_state)."""
    return get_rng_state()


def set_cuda_rng_state(state):
    return set_rng_state(state)


def to_dlpack(x):
    from .utils.dlpack import to_dlpack as _impl
    return _impl(x)


def from_dlpack(capsule):
    from .utils.dlpack import from_dlpack as _impl
    return _impl(capsule)


def batch(reader, batch_size, drop_last=False):
    """Mini-batch reader decorator (reference python/paddle/batch.py)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def iinfo(dtype):
    """Integer dtype info (reference paddle.iinfo over ml_dtypes)."""
    import numpy as _np
    from .core.dtype import to_jax_dtype
    return _np.iinfo(_np.dtype(to_jax_dtype(dtype)))


def finfo(dtype):
    """Float dtype info incl bfloat16 (reference paddle.finfo)."""
    import ml_dtypes as _mld
    import numpy as _np
    from .core.dtype import to_jax_dtype
    dt = _np.dtype(to_jax_dtype(dtype))
    if dt == _np.dtype(_mld.bfloat16):
        return _mld.finfo(_mld.bfloat16)
    return _np.finfo(dt)
from . import strings  # noqa: E402
from . import text  # noqa: E402
from . import utils  # noqa: E402
from . import vision  # noqa: E402

from .framework.io import load, save  # noqa: E402
from .hapi.model import Model, summary  # noqa: E402
from .hapi import callbacks  # noqa: E402  (paddle.callbacks alias)
from .nn.layer.layers import Layer  # noqa: E402

DataParallel = distributed.DataParallel


def disable_static(place=None):
    return None


def enable_static():
    raise NotImplementedError(
        "paddle_tpu is eager-first; use paddle_tpu.jit.to_static for the "
        "captured/compiled execution path."
    )


def in_dynamic_mode():
    return True


def disable_signal_handler():
    return None


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.model import flops as _flops
    return _flops(net, input_size, custom_ops, print_detail)
