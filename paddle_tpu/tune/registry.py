"""TunableKernel registry: the search space each Pallas kernel exposes.

A registration declares, per kernel: the tunable parameters and their
candidate values (``space``), the built-in defaults the fallback chain
bottoms out at, any deprecated env-var levers that still override the
cache, and a ``sweep`` of representative shape keys the autotuner
measures.  The registry is pure data — it imports no kernel module, so
the lint CLI and the subprocess sweep workers can enumerate it without
touching jax.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field

__all__ = ["TunableKernel", "register", "get_kernel", "all_kernels",
           "candidate_configs"]


@dataclass(frozen=True)
class TunableKernel:
    """Search-space declaration for one Pallas kernel.

    name           cache key component ("flash_attention", ...)
    space          param -> tuple of candidate values
    defaults       param -> built-in value (end of the fallback chain)
    env_overrides  param -> deprecated env var that still wins over the
                   cache (with a DeprecationWarning)
    sweep          representative shape keys measured by autotune.py;
                   trace-time lookups resolve to these via the bucket
                   fallback when their own bucket has no entry
    describe       one-line human summary for reports
    """
    name: str
    space: dict = field(default_factory=dict)
    defaults: dict = field(default_factory=dict)
    env_overrides: dict = field(default_factory=dict)
    sweep: tuple = ()
    describe: str = ""


_REGISTRY: dict = {}


def register(kernel: TunableKernel) -> TunableKernel:
    _REGISTRY[kernel.name] = kernel
    return kernel


def get_kernel(name: str):
    return _REGISTRY.get(name)


def all_kernels() -> tuple:
    return tuple(_REGISTRY[k] for k in sorted(_REGISTRY))


def candidate_configs(kernel: TunableKernel):
    """Cartesian product of the kernel's search space, defaults first."""
    names = sorted(kernel.space)
    seen = []
    default = {k: kernel.defaults[k] for k in names}
    seen.append(default)
    for combo in itertools.product(*(kernel.space[k] for k in names)):
        cfg = dict(zip(names, combo))
        if cfg not in seen:
            seen.append(cfg)
    return seen


# ---------------------------------------------------------------------------
# the five shipped kernels
# ---------------------------------------------------------------------------

# dense flash attention: block_q/block_k tile the (seq_q, seq_k) grid.
# Sweep covers the f32 CI shapes and the bf16 shapes real models run, so
# any device the sweep touches gets a same-dtype bucket for both.
register(TunableKernel(
    name="flash_attention",
    space={"block_q": (128, 256, 512, 1024), "block_k": (128, 256, 512, 1024)},
    defaults={"block_q": 512, "block_k": 512},
    env_overrides={"block_q": "PADDLE_TPU_FA_BLOCK_Q",
                   "block_k": "PADDLE_TPU_FA_BLOCK_K"},
    sweep=(
        {"seq_q": 2048, "seq_k": 2048, "head_dim": 128, "dtype": "float32"},
        {"seq_q": 2048, "seq_k": 2048, "head_dim": 128, "dtype": "bfloat16"},
        {"seq_q": 8192, "seq_k": 8192, "head_dim": 128, "dtype": "bfloat16"},
    ),
    describe="dense flash attention fwd/bwd q/k tile sizes",
))

# varlen flash attention shares the block vocabulary but tiles ragged
# token batches; its q-extent is the prefill token bucket, not seq_len.
register(TunableKernel(
    name="flash_attention_varlen",
    space={"block_q": (128, 256, 512, 1024), "block_k": (128, 256, 512, 1024)},
    defaults={"block_q": 512, "block_k": 512},
    env_overrides={"block_q": "PADDLE_TPU_FA_BLOCK_Q",
                   "block_k": "PADDLE_TPU_FA_BLOCK_K"},
    sweep=(
        {"seq_q": 1024, "seq_k": 2048, "head_dim": 128, "dtype": "float32"},
        {"seq_q": 1024, "seq_k": 2048, "head_dim": 128, "dtype": "bfloat16"},
    ),
    describe="varlen (packed-prefill) flash attention tile sizes",
))

# fused RMS/LayerNorm: rows-per-program blocking.
register(TunableKernel(
    name="fused_norms",
    space={"block_r": (64, 128, 256, 512)},
    defaults={"block_r": 256},
    sweep=(
        {"rows": 2048, "hidden": 4096, "dtype": "float32"},
        {"rows": 2048, "hidden": 4096, "dtype": "bfloat16"},
    ),
    describe="fused RMS/LayerNorm rows-per-program block",
))

# ragged paged attention: KV pages walked per grid step.  pages_per_step
# widens the innermost grid dim's work without changing the sequential
# page order, so accumulation — and therefore bytes — is identical.
register(TunableKernel(
    name="paged_attention",
    space={"pages_per_step": (1, 2, 4, 8)},
    defaults={"pages_per_step": 1},
    sweep=(
        {"tq": 8, "kv_heads": 4, "head_dim": 128, "page": 16, "nblk": 128,
         "dtype": "float32"},
        {"tq": 8, "kv_heads": 4, "head_dim": 128, "page": 16, "nblk": 128,
         "dtype": "bfloat16"},
        {"tq": 8, "kv_heads": 4, "head_dim": 128, "page": 32, "nblk": 256,
         "dtype": "int8"},
    ),
    describe="ragged paged attention KV pages per grid step",
))

# fused dequant matmul: int8/int4 weight blocks stream from HBM and
# upcast in VMEM against their scale rows.  block_m/n/k tile the
# (M, N, K) grid; the launch clamps each to a divisor of its dim (and
# block_k to the int4 128-row scale-group nesting), so every candidate
# is feasible at every shape and only the tiling — never the math —
# changes.  Sweep shapes are llama-class decode launches: M is the
# decode batch, K/N the projection and MLP extents.
register(TunableKernel(
    name="quant_matmul",
    space={"block_m": (8, 16, 32), "block_n": (128, 256, 512),
           "block_k": (128, 256, 512)},
    defaults={"block_m": 8, "block_n": 256, "block_k": 256},
    sweep=(
        {"m": 8, "k": 4096, "n": 4096, "dtype": "int8"},
        {"m": 8, "k": 4096, "n": 11008, "dtype": "int8"},
        {"m": 8, "k": 4096, "n": 4096, "dtype": "int4"},
        {"m": 8, "k": 4096, "n": 11008, "dtype": "int4"},
    ),
    describe="fused dequant-matmul weight-block tiles (int8/int4)",
))
