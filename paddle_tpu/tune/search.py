"""Candidate search: enumerate, measure, pick winners, write the cache.

Two measurers share one search loop:

* ``CostModelMeasurer`` scores candidates in-process with the
  arithmetic-intensity model (:mod:`paddle_tpu.tune.cost`) — the CPU CI
  path, exercising the full search/persist/lookup pipeline with no chip.
* ``SubprocessMeasurer`` times real launches, one candidate per child
  process (the ``tools/perf/mfu_ablation.py`` worker pattern): a config
  that OOMs VMEM or wedges the compiler kills only its child, and every
  candidate compiles fresh instead of reusing a sibling's trace cache.
  Candidates are forced into the child via ``PADDLE_TPU_TUNE_FORCE``.
"""
from __future__ import annotations

import json
import math
import os
import subprocess
import sys

from . import cost
from .cache import TuningCache, bucket_signature, device_kind
from .registry import TunableKernel, all_kernels, candidate_configs

__all__ = ["CostModelMeasurer", "SubprocessMeasurer", "sweep_kernel",
           "run_sweep", "untuned_launch_report"]


class CostModelMeasurer:
    """Rank candidates with the roofline model; no jax, no chip."""

    kind = "cost-model"

    def measure(self, kernel: TunableKernel, shape: dict,
                config: dict) -> float:
        return cost.estimate(kernel.name, shape, config)


# Child source for wall-clock measurement.  It builds a representative
# launch for the named kernel from the shape key, forces the candidate
# config through the normal trace-time lookup (so the measured path IS
# the production path), and prints median seconds as JSON.
_WORKER = r"""
import json, sys, time
spec = json.loads(sys.argv[1])
import jax, jax.numpy as jnp
import numpy as np

def build(name, s):
    dt = jnp.dtype(s.get("dtype", "float32"))
    if name == "flash_attention":
        from paddle_tpu.ops.pallas import flash_attention as fa
        rng = np.random.RandomState(0)
        # [B, S, H, D] — the layout attention()/use_flash expect
        q = jnp.asarray(rng.randn(1, s["seq_q"], 8, s["head_dim"]), dt)
        k = jnp.asarray(rng.randn(1, s["seq_k"], 8, s["head_dim"]), dt)
        v = jnp.asarray(rng.randn(1, s["seq_k"], 8, s["head_dim"]), dt)
        fn = jax.jit(lambda q, k, v: fa.attention(q, k, v, causal=True))
        return fn, (q, k, v)
    if name == "flash_attention_varlen":
        import math
        from paddle_tpu.ops.pallas import flash_attention as fa
        from paddle_tpu.ops.pallas import flash_attention_varlen as favl
        rng = np.random.RandomState(0)
        tq, tk, d = s["seq_q"], s["seq_k"], s["head_dim"]
        # [T, H, D] flat tokens, two ragged sequences
        q = jnp.asarray(rng.randn(tq, 8, d), dt)
        k = jnp.asarray(rng.randn(tk, 8, d), dt)
        v = jnp.asarray(rng.randn(tk, 8, d), dt)
        cu_q = jnp.asarray([0, tq // 2, tq], jnp.int32)
        cu_k = jnp.asarray([0, tk // 2, tk], jnp.int32)
        sm = 1.0 / math.sqrt(d)
        if favl.use_varlen_flash(q, k, True):
            fn = jax.jit(lambda q, k, v, cq, ck: favl._varlen_attention(
                True, sm, q, k, v, cq, ck))
            return fn, (q, k, v, cu_q, cu_k)
        # off-chip grace: time the dense composition so candidates tie
        # and the winner degrades to the defaults
        fn = jax.jit(lambda q, k, v: fa._ref_attention(
            q[None], k[None], v[None], True))
        return fn, (q, k, v)
    if name == "fused_norms":
        from paddle_tpu.ops.pallas import fused_norms as fns
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(s["rows"], s["hidden"]), dt)
        w = jnp.ones((s["hidden"],), dt)
        # the fused op has no interpret path — honor its supports() gate
        # (off-chip every candidate times the reference and ties, so the
        # winner degrades to the defaults rather than crashing the child)
        if fns.rms_norm_fused.supports(x.shape, dt.name):
            fn = jax.jit(lambda x, w: fns.rms_norm_fused(x, w, 1e-6))
        else:
            fn = jax.jit(lambda x, w: fns._rms_ref(x, w, 1e-6))
        return fn, (x, w)
    if name == "paged_attention":
        from paddle_tpu.ops.pallas import paged_attention as pa
        rng = np.random.RandomState(0)
        tq, kvh, d = s["tq"], s["kv_heads"], s["head_dim"]
        page, nblk = s["page"], s["nblk"]
        R = 4
        kvdt = dt if s.get("dtype") != "int8" else jnp.int8
        kc = jnp.asarray(rng.randn(R * nblk, kvh, page, d), kvdt)
        vc = jnp.asarray(rng.randn(R * nblk, kvh, page, d), kvdt)
        bt = jnp.asarray(
            rng.randint(0, R * nblk, (R + 1, nblk)), jnp.int32)
        q = jnp.asarray(rng.randn(tq, kvh * 2, d), jnp.float32)
        seg = jnp.asarray(rng.randint(0, R, (tq,)), jnp.int32)
        rel = jnp.asarray(rng.randint(page, page * nblk, (tq,)), jnp.int32)
        fn = jax.jit(lambda *a: pa.ragged_paged_attention_segrel(*a))
        return fn, (q, kc, vc, bt, seg, rel)
    if name == "quant_matmul":
        from paddle_tpu.ops.pallas import quant_matmul as qm
        rng = np.random.RandomState(0)
        m, k, n = s["m"], s["k"], s["n"]
        wdt = s.get("dtype", "int8")
        x = jnp.asarray(rng.randn(m, k), jnp.float32)
        w = jnp.asarray(rng.randn(k, n), jnp.float32)
        q, sc = qm.quantize_weight(w, wdt)
        if qm.supports(m, k, n, wdt):
            fn = jax.jit(lambda x, q, sc: qm.matmul(
                x, q, sc, weight_dtype=wdt))
        else:
            # off-chip grace: time the fake-quant reference so
            # candidates tie and the winner degrades to the defaults
            fn = jax.jit(lambda x, q, sc: qm.reference_matmul(
                x, q, sc, wdt))
        return fn, (x, q, sc)
    raise SystemExit(f"unknown kernel {name}")

fn, args = build(spec["kernel"], spec["shape"])
out = fn(*args)
jax.block_until_ready(out)
times = []
for _ in range(spec.get("iters", 5)):
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    times.append(time.perf_counter() - t0)
times.sort()
print(json.dumps({"seconds": times[len(times) // 2]}))
"""


class SubprocessMeasurer:
    """Wall-clock one candidate per child process on the real backend."""

    kind = "wall-clock"

    def __init__(self, timeout: int = 900, iters: int = 5):
        self.timeout = timeout
        self.iters = iters

    def measure(self, kernel: TunableKernel, shape: dict,
                config: dict) -> float:
        spec = {"kernel": kernel.name, "shape": shape, "iters": self.iters}
        env = dict(os.environ)
        env["PADDLE_TPU_TUNE_FORCE"] = json.dumps({kernel.name: config})
        # the candidate, not a stale cache, must decide geometry
        env.pop("PADDLE_TPU_TUNE_CACHE", None)
        for var in kernel.env_overrides.values():
            env.pop(var, None)
        proc = subprocess.run(
            [sys.executable, "-c", _WORKER, json.dumps(spec)],
            capture_output=True, text=True, env=env, timeout=self.timeout)
        if proc.returncode != 0:
            return math.inf
        try:
            return float(json.loads(proc.stdout.strip().splitlines()[-1])
                         ["seconds"])
        except Exception:
            return math.inf


def sweep_kernel(kernel: TunableKernel, measurer, cache: TuningCache,
                 device: str | None = None, log=None) -> list:
    """Measure every candidate on every sweep shape; persist winners.

    Returns report rows: one dict per sweep shape with the winner, the
    default's score, and the modeled/measured speedup."""
    device = device or device_kind()
    rows = []
    for shape in kernel.sweep:
        sig = bucket_signature(shape)
        best_cfg, best_s, default_s = None, math.inf, math.inf
        for cfg in candidate_configs(kernel):
            s = measurer.measure(kernel, shape, cfg)
            if cfg == {k: kernel.defaults[k] for k in sorted(kernel.space)}:
                default_s = s
            if s < best_s:
                best_cfg, best_s = cfg, s
            if log:
                log(f"  {kernel.name} {sig} {cfg} -> "
                    f"{'inf' if math.isinf(s) else f'{s * 1e6:.2f}us'}")
        if best_cfg is None or math.isinf(best_s):
            rows.append({"kernel": kernel.name, "sig": sig,
                         "error": "no feasible candidate"})
            continue
        cache.put(device, kernel.name, sig, best_cfg,
                  score_s=best_s, measure=measurer.kind)
        rows.append({
            "kernel": kernel.name, "sig": sig, "config": best_cfg,
            "score_s": best_s, "default_s": default_s,
            "speedup": (default_s / best_s
                        if best_s > 0 and not math.isinf(default_s)
                        else None),
            "measure": measurer.kind,
        })
    return rows


def run_sweep(measurer, cache_file: str, kernels=None,
              device: str | None = None, log=None) -> dict:
    """Sweep (a subset of) the registry, save the cache, return a report."""
    cache = TuningCache(cache_file)
    device = device or device_kind()
    names = set(kernels) if kernels else None
    rows = []
    for kern in all_kernels():
        if names is not None and kern.name not in names:
            continue
        rows.extend(sweep_kernel(kern, measurer, cache, device, log=log))
    path = cache.save()
    return {"device": device, "cache": path, "measure": measurer.kind,
            "entries": len(cache), "results": rows}


def untuned_launch_report(root: str | None = None) -> list:
    """graft-lint-style rows for every Pallas launch whose geometry does
    not flow from the tuning-cache lookup helper."""
    from paddle_tpu.analysis import lint_paths
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    target = os.path.join(root, "paddle_tpu", "ops", "pallas")
    findings = lint_paths([target], root=root)
    return [
        {"rule": f.rule, "file": f.location.file, "line": f.location.line,
         "func": f.location.func, "message": f.message}
        for f in findings if f.rule == "untuned-pallas-launch"
    ]
