"""Kernel autotuning: searched launch configs, persisted per device.

Kernels resolve their launch geometry at trace time through
:func:`kernel_config`, which walks forced/env overrides, then the
JSON tuning cache (exact bucket, then nearest same-dtype bucket), then
built-in defaults.  ``tools/perf/autotune.py`` runs the sweep that
populates the cache — wall-clock in subprocess isolation on a chip,
arithmetic-intensity cost model on CPU.
"""
from .cache import (TuningCache, bucket_signature, cache_path, current_cache,
                    device_kind, kernel_config, kernel_config_with_meta,
                    provenance_snapshot, reset_provenance, set_cache_path)
from .registry import (TunableKernel, all_kernels, candidate_configs,
                       get_kernel, register)
from .search import (CostModelMeasurer, SubprocessMeasurer, run_sweep,
                     sweep_kernel, untuned_launch_report)

__all__ = [
    "TuningCache", "bucket_signature", "cache_path", "current_cache",
    "device_kind", "kernel_config", "kernel_config_with_meta",
    "provenance_snapshot", "reset_provenance", "set_cache_path",
    "TunableKernel", "all_kernels", "candidate_configs", "get_kernel",
    "register",
    "CostModelMeasurer", "SubprocessMeasurer", "run_sweep", "sweep_kernel",
    "untuned_launch_report",
]
