"""Arithmetic-intensity cost model: scores candidates without a chip.

Off-chip (CPU CI) the autotuner cannot time kernels, but it can still
rank them: each candidate's runtime is modeled as the roofline max of
compute time and memory time plus a per-grid-program launch overhead,
with a VMEM-working-set feasibility gate.  The constants are a generic
TPU-class device — absolute numbers are meaningless, the RANKING is
what the sweep persists, and on-chip wall-clock measurement replaces
this model entirely (``--wall`` mode).
"""
from __future__ import annotations

import math

__all__ = ["estimate", "f32_matmul_estimate", "PEAK_FLOPS", "PEAK_BW",
           "VMEM_BYTES"]

PEAK_FLOPS = 200e12     # flop/s, generic bf16-class systolic peak
PEAK_BW = 1.0e12        # byte/s HBM
VMEM_BYTES = 64 << 20   # per-core VMEM working-set budget
PER_PROGRAM_S = 1.2e-6  # grid-program launch/prologue overhead
PER_TILE_S = 0.1e-6     # per inner-tile loop overhead (k-blocks, pages)

_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "int8": 1}


def _bytes(dtype: str) -> int:
    return _DTYPE_BYTES.get(dtype, 4)


def _roofline(flops: float, traffic: float, programs: float,
              tiles: float, vmem: float):
    if vmem > VMEM_BYTES:
        return math.inf
    return (max(flops / PEAK_FLOPS, traffic / PEAK_BW)
            + programs * PER_PROGRAM_S + tiles * PER_TILE_S)


def _flash(shape: dict, config: dict) -> float:
    sq, sk, d = shape["seq_q"], shape["seq_k"], shape["head_dim"]
    eb = _bytes(shape.get("dtype", "float32"))
    bq = min(config["block_q"], sq)
    bk = min(config["block_k"], sk)
    heads = shape.get("heads", 8)
    programs = heads * math.ceil(sq / bq)
    tiles = programs * math.ceil(sk / bk)
    flops = 4.0 * heads * sq * sk * d
    # each q-block streams the full K/V once; bigger q-blocks mean fewer
    # K/V passes, bigger k-blocks amortize tile overhead
    traffic = eb * heads * (sq * d * 2 + math.ceil(sq / bq) * sk * d * 2)
    vmem = eb * (bq * d + 2 * bk * d) + 4 * bq * d + 4 * bq * 2
    return _roofline(flops, traffic, programs, tiles, vmem)


def _norms(shape: dict, config: dict) -> float:
    rows, hidden = shape["rows"], shape["hidden"]
    eb = _bytes(shape.get("dtype", "float32"))
    br = min(config["block_r"], rows)
    programs = math.ceil(rows / br)
    flops = 8.0 * rows * hidden
    traffic = eb * rows * hidden * 2
    vmem = eb * br * hidden * 2 + 4 * br * hidden
    return _roofline(flops, traffic, programs, programs, vmem)


def _paged(shape: dict, config: dict) -> float:
    tq, kvh, d = shape["tq"], shape["kv_heads"], shape["head_dim"]
    page, nblk = shape["page"], shape["nblk"]
    eb = _bytes(shape.get("dtype", "float32"))
    p = max(1, config["pages_per_step"])
    steps = math.ceil(nblk / p)
    programs = tq * kvh * steps
    flops = 4.0 * tq * kvh * nblk * page * d
    traffic = eb * tq * kvh * nblk * page * d * 2 + 4.0 * tq * kvh * d
    # p page-pairs resident per step plus the f32 accumulator
    vmem = eb * p * page * d * 2 + 4 * d * 3
    return _roofline(flops, traffic, programs, programs * p, vmem)


def _weight_bytes_per_elem(dtype: str) -> float:
    # int4 nibble-packs two weights per byte; scales ride separately
    return 0.5 if dtype == "int4" else float(_bytes(dtype))


def _quant_matmul(shape: dict, config: dict) -> float:
    """Fused dequant matmul: x [M, K] f32 against a quantized [K, N]
    weight pool.  Traffic is the decode story — activations and the f32
    output are tiny next to the weight bytes, which shrink 4x/8x vs a
    dense f32 operand.  VMEM holds one x block, one quantized weight
    block plus its f32 upcast (the dequant temporary), and the f32
    accumulator/output tile."""
    m, k, n = shape["m"], shape["k"], shape["n"]
    dtype = shape.get("dtype", "int8")
    wb = _weight_bytes_per_elem(dtype)
    bm = min(config["block_m"], m)
    bn = min(config["block_n"], n)
    bk = min(config["block_k"], k)
    programs = math.ceil(m / bm) * math.ceil(n / bn)
    tiles = programs * math.ceil(k / bk)
    flops = 2.0 * m * k * n
    scale_rows = math.ceil(k / 128) if dtype == "int4" else 1
    traffic = (4.0 * m * k                    # activations
               + wb * k * n                   # quantized weight stream
               + 4.0 * scale_rows * n         # scales
               + 4.0 * m * n)                 # f32 output
    vmem = (4.0 * bm * bk                     # x block
            + wb * bk * bn                    # quantized weight block
            + 4.0 * bk * bn                   # f32 dequant temporary
            + 4.0 * bm * bn * 2)              # accumulator + out tile
    return _roofline(flops, traffic, programs, tiles, vmem)


def f32_matmul_estimate(m: int, k: int, n: int) -> float:
    """Roofline seconds for the dense f32 XLA matmul at the same shape —
    the A/B baseline serve_bench and the acceptance gate quote against
    the tuned ``quant_matmul`` estimate.  One program (XLA fuses the
    whole contraction), full-width f32 weight traffic."""
    flops = 2.0 * m * k * n
    traffic = 4.0 * (m * k + k * n + m * n)
    return max(flops / PEAK_FLOPS, traffic / PEAK_BW) + PER_PROGRAM_S


_MODELS = {
    "flash_attention": _flash,
    "flash_attention_varlen": _flash,
    "fused_norms": _norms,
    "paged_attention": _paged,
    "quant_matmul": _quant_matmul,
}


def estimate(kernel: str, shape: dict, config: dict) -> float:
    """Modeled seconds for one launch; math.inf when infeasible."""
    fn = _MODELS.get(kernel)
    if fn is None:
        raise KeyError(f"no cost model for kernel {kernel!r}")
    return fn(shape, config)
